"""Headline benchmark: Qwen3-0.6B decode throughput through the serving engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

This is the BASELINE.json metric ("Qwen3-0.6B tokens/sec/chip; p50 TTFT").
The reference publishes no numbers (BASELINE.md); the comparison bar is the
implicit ">= 1x L4 tokens/sec" north star. L4_BASELINE_TOKS below is our
documented estimate of vLLM Qwen3-0.6B batched decode on the reference's
1x L4 (g6.4xlarge): L4 HBM bandwidth is ~300 GB/s and batched decode of a
1.2 GB bf16 model is bandwidth-bound at <=250 fwd/s => ~32-batch ceiling
~= 8k tok/s, with realistic vLLM efficiency ~30-40% => ~2.5k tok/s.
vs_baseline = measured / 2500.

Measures the REAL serving path (Engine.step: host scheduling + jitted prefill/
decode with donated KV cache), not a stripped microbench.

Budget design (r1/r2 postmortems — the driver caps the whole run at ~900s):
r1 died at backend init (failed init is cached process-wide, so retries need
fresh subprocesses); r2's first 900s TPU attempt consumed the entire window
(full warmup compiles ~10 XLA programs serially over a network-attached chip)
and the CPU fallback never ran. This version is built to ALWAYS leave a JSON
line inside the window:

  1. kill stale ``--measure`` orphans from a previous crashed run by cmdline
     scan (an orphan holds the TPU and wedges every later attempt; the
     ppid-watchdog protects only our own children);
  2. a PROBE/RETRY loop spanning the WHOLE window (r3 postmortem: one 620s
     attempt burned the budget on a single dead interval of an hours-long
     tunnel outage, and the resulting JSON couldn't distinguish "tunnel down
     all window" from "code hung"). Each cycle: a cheap 45s subprocess probe
     (``jax.devices()``); on probe success, a measure attempt sized to the
     remaining budget; on probe failure, sleep ~60s and re-probe. EVERY probe
     is recorded in ``tunnel_probes: [{t, ok, platform}]`` and the final JSON
     carries a top-level ``tpu_unavailable`` flag (true iff no probe ever saw
     a TPU) — environment-down is machine-distinguishable from a regression;
  3. the child streams a PARTIAL result line as soon as the first timed
     window closes — a later hang still leaves a number (the parent keeps
     the last parseable line);
  4. JAX's persistent compilation cache is enabled (.jax_compile_cache/), so
     a retry or a later round skips recompiles entirely;
  5. after the probe window closes, one CPU fallback sized to the remaining
     budget; if even that fails, a JSON line with an "error" field.

The first TPU attempt measures the SHIPPED default path (paged KV — matching
``ServingConfig.paged=True``; ADVICE r3: the headline must cover what
production executes); if that attempt fails, the retry A/Bs the dense path so
a paged-specific lowering failure can't zero the round. The child also emits
a measured dispatch-latency decomposition (``dispatch_rtt_ms`` p50 of a no-op
jitted dispatch, ``device_step_ms`` = fused-step wall minus one RTT) so the
gap to the roofline ceiling splits into a measured link term vs kernel term
(VERDICT r3: "measure the dispatch-latency term instead of arguing it").

Roofline context (VERDICT r2 weak #2 — "fast needs a denominator"): the child
emits bytes-per-token (weights amortized over the batch + KV stream at the
measured mean context), the implied bandwidth-bound ceiling tok/s for the
chip's HBM, and pct_of_ceiling. See _roofline() for the arithmetic.

The measurement child also records the RESOLVED attention impl
("attention_impl": "pallas"|"xla") so a number can never silently measure the
XLA fallback while claiming to be the Pallas path.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

L4_BASELINE_TOKS = 2500.0
# The probe/measure loop + one CPU fallback must ALL fit the driver's ~900s
# cap, with slack for parent startup and the kill/cleanup between attempts.
TOTAL_BUDGET_S = float(os.environ.get("TPU_BENCH_TOTAL_BUDGET_S", 840))
CPU_TIMEOUT_S = 180
# Reserved tail so the CPU fallback always gets a slot even if the probe
# loop consumes everything else.
CPU_RESERVE_S = 150.0
# A measure attempt below this is all compile, no timed window — don't start
# one; keep probing instead (the probe trail is the deliverable then).
MIN_ATTEMPT_S = 150.0
PROBE_TIMEOUT_S = 45.0
PROBE_SLEEP_S = 60.0
# v5e HBM bandwidth (bytes/s) for the roofline denominator; override for
# other chip generations (v4: 1.2e12, v5p: 2.77e12, v6e: 1.6e12).
HBM_BYTES_PER_S = {"v4": 1.2e12, "v5e": 8.19e11, "v5p": 2.77e12,
                   "v6e": 1.6e12}


def _last_tpu_artifact() -> dict | None:
    """Newest banked on-chip bench artifact (BENCH_*.json with
    platform=="tpu" and a real value), summarized for embedding in a
    fallback result. A dead tunnel's CPU number then carries the last REAL
    TPU headline (value, git rev, age) alongside it, so a 1.99 tok/s
    liveness proof can never read as the round's measurement again
    (VERDICT r5 next #4).
    """
    import glob

    here = os.path.dirname(os.path.abspath(__file__))
    best = None   # (mtime, record, path)
    for path in glob.glob(os.path.join(here, "BENCH_*.json")):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if rec.get("platform") != "tpu" or not rec.get("value"):
            continue
        mtime = os.path.getmtime(path)
        if best is None or mtime > best[0]:
            best = (mtime, rec, path)
    if best is None:
        return None
    mtime, rec, path = best
    rev = None
    try:
        p = subprocess.run(["git", "log", "-1", "--format=%h", "--",
                            os.path.basename(path)],
                           capture_output=True, text=True, cwd=here,
                           timeout=10)
        rev = p.stdout.strip() or None
    except Exception:
        pass
    return {
        "value": rec.get("value"),
        "unit": rec.get("unit"),
        "metric": rec.get("metric"),
        "file": os.path.basename(path),
        "git_rev": rev,
        "age_days": round((time.time() - mtime) / 86400.0, 1),
    }


# ---------------------------------------------------------------------------
# Parent: subprocess orchestration (no jax imported here)
# ---------------------------------------------------------------------------


def _kill_stale_measures() -> int:
    """SIGKILL any ``bench.py --measure`` process that isn't our child.

    A measure child orphaned by a previous crashed/killed bench run keeps the
    TPU chip locked indefinitely (observed r2) — its own ppid-watchdog only
    fires on reparenting, which never happens when the whole tree dies except
    the leaf. Matching the cmdline is the reliable signal.
    """
    me = os.getpid()
    killed = 0
    try:
        pids = [int(p) for p in os.listdir("/proc") if p.isdigit()]
    except OSError:
        return 0
    for pid in pids:
        if pid == me:
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().decode("utf-8", "replace").split("\0")
        except OSError:
            continue
        if any("bench.py" in c for c in cmd) and "--measure" in cmd:
            try:
                os.kill(pid, signal.SIGKILL)
                killed += 1
                sys.stderr.write(f"bench: killed stale measure orphan {pid}\n")
            except OSError:
                pass
    return killed


def _run_child(env_overrides: dict, timeout: float):
    """One measurement attempt in a fresh process.

    Returns (json_dict|None, err). Keeps the LAST parseable result line, so a
    child that printed a partial line and then hung past the timeout still
    yields its partial number.
    """
    env = dict(os.environ)
    env["TPU_BENCH_CHILD_BUDGET_S"] = str(max(30.0, timeout - 15.0))
    # Persistent XLA compile cache: a retry (or next round) skips recompiles.
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".jax_compile_cache"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
    env.update(env_overrides)
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--measure"],
            capture_output=True, text=True, timeout=timeout, env=env)
        stdout, stderr, rc = p.stdout, p.stderr, p.returncode
        timed_out = False
    except subprocess.TimeoutExpired as e:
        # communicate() reads the pipes concurrently, so output printed
        # before the timeout IS here (bytes in some Python versions).
        def _s(x):
            return x.decode("utf-8", "replace") if isinstance(x, bytes) \
                else (x or "")
        stdout, stderr, rc = _s(e.stdout), _s(e.stderr), "timeout"
        timed_out = True
    result = None
    for line in (stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                d = json.loads(line)
                if "metric" in d:
                    result = d      # last parseable line wins (partial→final)
            except (ValueError, TypeError):
                pass
    if result is not None:
        return result, (f"timed out after {timeout}s (partial result kept)"
                        if timed_out else None)
    if timed_out:
        return None, f"timed out after {timeout}s"
    tail = ((stderr or "") + (stdout or "")).strip()[-600:]
    return None, f"rc={rc}: {tail}"


def _probe_tpu(timeout: float = PROBE_TIMEOUT_S):
    """One cheap tunnel probe in a fresh subprocess.

    Returns (ok, platform). ``jax.devices()`` under the axon plugin HANGS
    (not raises) while the tunnel is down, so the probe must be a killable
    subprocess, never an in-process import. A probe that initializes fine
    but reports a non-tpu platform means the environment simply has no TPU
    (plugin absent) — that is a terminal "stop probing" signal, unlike a
    timeout, which is a transient-outage signal worth re-probing.
    """
    code = "import jax; print('PLATFORM=' + jax.devices()[0].platform)"
    try:
        p = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return False, None
    for line in (p.stdout or "").splitlines():
        if line.startswith("PLATFORM="):
            plat = line.split("=", 1)[1].strip()
            return plat == "tpu", plat
    return False, None


def main() -> None:
    _kill_stale_measures()
    t0 = time.monotonic()

    def remaining() -> float:
        return TOTAL_BUDGET_S - (time.monotonic() - t0)

    probes = []      # [{t, ok, platform}] — the machine-readable trail
    errors = []
    attempt = 0

    def finish(result: dict) -> None:
        result["tunnel_probes"] = probes
        result["tpu_unavailable"] = not any(p["ok"] for p in probes)
        if result["tpu_unavailable"]:
            # a dead tunnel must never publish a CPU number as the round's
            # headline: carry the newest banked on-chip artifact beside it
            result["last_tpu"] = _last_tpu_artifact()
        if errors:
            if result.get("platform") == "tpu":
                # a successful TPU number after failed attempts: record the
                # attempt trail WITHOUT the "error" key — consumers treat
                # "error" as "no TPU headline number this round"
                result["attempt_errors"] = [e[:200] for e in errors]
            else:
                result.setdefault("error",
                                  " | ".join(e[:200] for e in errors))
        print(json.dumps(result))

    # Probe/measure loop spanning the whole window: the r2/r3 outages were
    # hours long, but a window-spanning retry catches any recovery, where a
    # single up-front attempt burns the budget on one dead interval.
    while remaining() > CPU_RESERVE_S + PROBE_TIMEOUT_S:
        ok, plat = _probe_tpu()
        probes.append({"t": round(time.monotonic() - t0, 1), "ok": ok,
                       "platform": plat})
        sys.stderr.write(f"bench: probe t={probes[-1]['t']} ok={ok} "
                         f"platform={plat}\n")
        if ok:
            window = remaining() - CPU_RESERVE_S
            if window < MIN_ATTEMPT_S:
                # the tunnel recovered too late for a real attempt: say so,
                # or the CPU fallback would read as a healthy round's
                # headline (review r4: finish() only labels unavailability
                # when NO probe succeeded)
                errors.append(
                    f"tpu probe ok at t={probes[-1]['t']}s but only "
                    f"{window:.0f}s left (< {MIN_ATTEMPT_S:.0f}s attempt "
                    f"minimum)")
                break
            # First attempt = shipped default (paged); retry A/Bs dense so a
            # paged-only lowering failure can't zero the round. An operator
            # TPU_BENCH_PAGED pins both attempts.
            overrides = {}
            if attempt > 0 and "TPU_BENCH_PAGED" not in os.environ:
                overrides["TPU_BENCH_PAGED"] = "0"
            attempt += 1
            result, err = _run_child(overrides, window)
            _kill_stale_measures()
            if result is not None:
                if err:
                    result["note"] = err
                finish(result)
                return
            errors.append(f"tpu attempt {attempt}: {err}")
            sys.stderr.write(f"bench: {errors[-1]}\n")
        elif plat is not None:
            break   # backend healthy but no TPU exists — probing won't help
        elif remaining() > CPU_RESERVE_S + PROBE_SLEEP_S + PROBE_TIMEOUT_S:
            time.sleep(PROBE_SLEEP_S)
    # Probe window exhausted (or no TPU in this environment): measure on CPU
    # so the round still has a (clearly labeled) number.
    # NOTE: the env var JAX_PLATFORMS=cpu is NOT enough — the axon TPU plugin
    # wins over it and the child would hang on the same dead backend init
    # (r2 postmortem; tests/conftest.py documents the same trap). The child
    # applies jax.config.update("jax_platforms", "cpu") when it sees
    # TPU_BENCH_PLATFORM=cpu, which does take precedence.
    cpu_env = {"TPU_BENCH_PLATFORM": "cpu", "JAX_PLATFORMS": "cpu"}
    # The CPU number is a liveness proof, not a perf claim: pin the dense
    # path there (the XLA-fallback paged path gathers every page per layer —
    # too slow to finish inside the reserve window).
    cpu_env.setdefault("TPU_BENCH_PAGED", os.environ.get("TPU_BENCH_PAGED",
                                                         "0"))
    result, err = _run_child(cpu_env,
                             min(CPU_TIMEOUT_S, max(60.0, remaining() - 10)))
    if result is not None:
        if not any(p["ok"] for p in probes):
            errors.insert(0, "tpu backend unavailable for the whole probe "
                             "window; cpu fallback measured")
        finish(result)
        return
    errors.append(f"cpu fallback: {err}")
    finish({
        "metric": "qwen3-0.6b decode tokens/sec/chip",
        "value": 0.0,
        "unit": "tokens/sec",
        "vs_baseline": 0.0,
    })


# ---------------------------------------------------------------------------
# Child: the actual measurement (fresh process per attempt)
# ---------------------------------------------------------------------------


def _parent_watchdog() -> None:
    """Exit the measurement child if its orchestrating parent dies.

    An outer ``timeout N python bench.py`` kills only the parent; the
    ``--measure`` child would keep running — and keep the TPU chip locked —
    indefinitely (observed r2: an orphaned child wedged every subsequent
    bench attempt). Reparenting to init (ppid 1) is the orphan signal; the
    parent's cmdline-scan kill covers the remaining tree-death cases.
    """
    import threading

    ppid0 = os.getppid()

    def watch():
        while True:
            if os.getppid() != ppid0:   # reparented = parent died
                os._exit(3)
            time.sleep(10)

    threading.Thread(target=watch, daemon=True).start()


def _roofline(params, cfg, serving, mean_ctx: float, batch: int):
    """Bandwidth-roofline denominator for the decode number.

    Batched decode reads, per fused substep: every weight byte once
    (amortized over the batch) plus each slot's resident KV rows. So

        bytes/token = weights_bytes / batch + mean_ctx * kv_row_bytes
        ceiling tok/s = HBM bytes/s / (bytes/token)

    kv_row_bytes covers k+v across all layers at one token position
    (+ per-row scales when the cache is int8). This is the *ideal* streaming
    cost — activations, the KV write, and logits are negligible beside it —
    so pct_of_ceiling isolates kernel + dispatch overhead (VERDICT r2: "fast
    needs a denominator").
    """
    import jax

    weights_bytes = sum(x.nbytes for x in jax.tree.leaves(params))
    per_row = cfg.head_dim * (1 if serving.kv_dtype == "int8" else 2) \
        + (4 if serving.kv_dtype == "int8" else 0)
    kv_row_bytes = 2 * cfg.num_layers * cfg.num_kv_heads * per_row
    bytes_per_tok = weights_bytes / max(1, batch) + mean_ctx * kv_row_bytes
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    bw = float(os.environ.get("TPU_BENCH_HBM_GBPS", 0)) * 1e9 \
        or HBM_BYTES_PER_S.get(gen, HBM_BYTES_PER_S["v5e"])
    ceiling = bw / bytes_per_tok
    return {
        "weights_bytes": int(weights_bytes),
        "kv_row_bytes": int(kv_row_bytes),
        "mean_ctx": round(mean_ctx, 1),
        "hbm_bytes_per_s": bw,
        "bytes_per_token": round(bytes_per_tok, 1),
        "ceiling_toks_per_s": round(ceiling, 1),
    }


def measure() -> None:
    _parent_watchdog()
    t_start = time.monotonic()
    budget = float(os.environ.get("TPU_BENCH_CHILD_BUDGET_S", 600))

    def remaining() -> float:
        return budget - (time.monotonic() - t_start)

    import jax

    if os.environ.get("TPU_BENCH_PLATFORM") == "cpu":
        # Must be config, not env: the axon TPU plugin outranks JAX_PLATFORMS
        # and would hang this fallback child on the dead backend init it
        # exists to escape.
        jax.config.update("jax_platforms", "cpu")

    # Persistent compile cache (also set via env by the parent; make the
    # direct `python bench.py --measure` path identical).
    cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_compile_cache"))
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass   # cache is an optimization, never a failure

    import jax.numpy as jnp

    from aws_k8s_ansible_provisioner_tpu.config import (QWEN3_0_6B,
                                                        ServingConfig,
                                                        tiny_qwen3)
    from aws_k8s_ansible_provisioner_tpu.models.layers import init_params
    from aws_k8s_ansible_provisioner_tpu.ops.attention import resolve_impl
    from aws_k8s_ansible_provisioner_tpu.serving.engine import Engine, Request

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    impl = resolve_impl("auto")

    # TPU_BENCH_* env overrides let the tuning sweep reuse this exact
    # measurement path; the defaults ARE the tuned config.
    env = os.environ.get
    # --dry (TPU_BENCH_DRY=1): a seconds-class CPU pass over the tiny model
    # that exercises the identical config/field plumbing — every JSON field
    # of a real run (bblock, weights_dtype, dma_steps_per_substep, roofline
    # names) exists here too, so field regressions surface without a chip.
    dry = bool(int(env("TPU_BENCH_DRY", "0")))
    cfg = tiny_qwen3() if dry else QWEN3_0_6B
    # The batch default is COUPLED to the cache dtype: bf16 at batch 128
    # doesn't fit (15 GB cache + 1.2 GB weights > 16 GB HBM), so a bf16
    # sweep run inherits the bf16-feasible batch unless it overrides both.
    kv_dtype = env("TPU_BENCH_KV_DTYPE", "int8" if on_tpu else "auto")
    default_batch = 128 if kv_dtype == "int8" else 64
    serving = ServingConfig(
        # Batch/horizon from the measured v5e sweeps (r2): bf16 32/32 → 3279
        # tok/s, 64/32 → 4190, 64/64 → 4511. int8 KV halves the cache
        # bandwidth and footprint, letting batch scale to 128.
        max_decode_slots=int(env("TPU_BENCH_BATCH",
                                 default_batch if on_tpu else 4)),
        max_cache_len=int(env("TPU_BENCH_CACHE_LEN", 1024 if on_tpu else 128)),
        prefill_buckets=(32,),
        # Large fused horizon amortizes host->device dispatch (the chip is
        # network-attached under the bench harness, ~100 ms RTT/dispatch);
        # serving keeps the smaller default so streaming latency stays bounded.
        decode_horizon=int(env("TPU_BENCH_HORIZON", 96 if on_tpu else 4)),
        # Prefilling 32 queued prompts per dispatch keeps the burst TTFT
        # dispatch-count low (4 dispatches for the 128-slot fill): measured
        # TTFT p50 860 -> 554 ms vs 16/dispatch at identical throughput.
        max_prefill_batch=int(env("TPU_BENCH_PREFILL_BATCH",
                                  32 if on_tpu else 4)),
        # TTFT lever #2 (VERDICT r5 weak #3): chunked prefill interleaves
        # decode between chunks — bench_sweep --ttft drives this axis to
        # turn the one bad cold-burst TTFT into a measured curve.
        prefill_chunk=int(env("TPU_BENCH_PREFILL_CHUNK", "0")),
        kv_dtype=kv_dtype,
        # int8 weights are the SHIPPED default (ServingConfig.weights_dtype;
        # r6): halves the dominant weight-stream term of bytes/token — the
        # roofline ceiling moves automatically (weights_bytes reads the
        # quantized tree). TPU_BENCH_WEIGHTS=bf16 is the A/B opt-out.
        weights_dtype=env("TPU_BENCH_WEIGHTS",
                          ServingConfig.weights_dtype),
        # Default matches ServingConfig.paged=True so the headline number
        # measures the path production actually executes (ADVICE r3). The
        # parent's retry attempt A/Bs TPU_BENCH_PAGED=0 so a paged-specific
        # Mosaic lowering failure can't zero the round's one measurement.
        paged=bool(int(env("TPU_BENCH_PAGED", "1"))),
        # Paged DMA granularity: the double-buffered paged decode kernel
        # streams one page per buffer fill, so page_size is its chunk size —
        # larger pages amortize DMA-issue overhead at the cost of coarser
        # admission.
        page_size=int(env("TPU_BENCH_PAGE_SIZE", "32" if dry else "64")),
        # Decode batch-block: 0 = the engine's startup autotune over
        # {1, 4, 8} (TPU only; exactly what a production pod runs), a
        # positive value pins it for the sweep's bblock axis.
        decode_bblock=int(env("TPU_BENCH_BBLOCK", "0")),
        # One-deep async decode pipeline (r9): the shipped default. The
        # sweep's TPU_BENCH_PIPELINE=0 axis measures the synchronous loop —
        # on a network-attached chip the per-dispatch host bubble it pays is
        # the ~RTT-sized term the pipeline exists to hide.
        decode_pipeline=int(env("TPU_BENCH_PIPELINE", "1")),
        # Ragged mixed-batch attention (r14): prefill chunks ride the decode
        # pipeline inside one packed program instead of draining it at every
        # admission edge. TPU_BENCH_RAGGED=0 is the sweep's sync-fallback
        # axis (drain + separate chunk dispatch per admission).
        ragged_attention=int(env("TPU_BENCH_RAGGED", "1")),
        # the tiny dry model runs f32 on CPU (parity with the test substrate)
        dtype="float32" if dry else "bfloat16",
    )
    params = init_params(cfg, jax.random.PRNGKey(0),
                         jnp.float32 if dry else jnp.bfloat16)
    engine = Engine(cfg, params, serving)
    # Bench-scope warmup: ONLY the batched-prefill and fused-decode programs
    # the measured path dispatches (2 compiles, not ~10 — the r2 timeout was
    # plausibly full warmup eating the whole window).
    engine.warmup(scope="bench")

    # Fill every decode slot with a short prompt; never stop on eos/budget.
    n_slots = serving.max_decode_slots
    gen_budget = serving.max_cache_len - 64
    reqs = []
    for i in range(n_slots):
        reqs.append(engine.submit(
            Request(prompt_ids=[(7 * i + 3) % min(1000, cfg.vocab_size - 20)
                                + 10] * 16,
                    max_tokens=gen_budget, ignore_eos=True)))
    while engine.pending:
        engine.step()
    # TTFT p50 under the burst (all programs pre-compiled by warmup).
    ttfts = sorted(r.t_first_token - r.t_submit for r in reqs)
    ttft_p50_ms = 1e3 * ttfts[len(ttfts) // 2]
    # Warm the decode program path (first decode after prefills).
    for _ in range(3):
        engine.step()

    # Timed decode windows. Each step emits up to decode_horizon tokens per
    # slot, so size within the per-slot budget (all slots stay active
    # throughout) and count ACTUAL emitted tokens via the metrics counter.
    # Budget already consumed: prefill's first token + 3 warm steps
    # (3 * horizon tokens/slot); keep one horizon of slack.
    horizon = max(1, serving.decode_horizon)
    max_steps = max(1, (gen_budget - 4 * horizon - 8) // horizon)
    target_steps = min(100, max_steps) if on_tpu else 4
    # Reserve ~2 steps' headroom against the deadline: a partial number
    # beats a killed child with none.
    first_window = max(1, min(2, target_steps))

    def timed_window(n_steps: int):
        jax.block_until_ready(engine.cache["k"])
        toks0 = engine.metrics.generated_tokens.total()
        t0 = time.monotonic()
        for _ in range(n_steps):
            engine.step()
        jax.block_until_ready(engine.cache["k"])
        dt = time.monotonic() - t0
        return engine.metrics.generated_tokens.total() - toks0, dt

    def result_line(tps: float, partial: bool, extra: dict):
        mean_ctx = float(sum(engine.lengths[:n_slots]) / n_slots)
        roof = _roofline(engine.params, cfg, serving, mean_ctx, n_slots) \
            if on_tpu else {}
        # The decomposition this round's kernel work changes (ISSUE r6):
        # per fused decode substep, the decode-attention stream issues one
        # buffer fill per (layer, slot-block, live page/chunk). bb divides
        # the block count; double-buffering overlaps — but does not remove —
        # each fill. ~14k at the r5 config (bb=1); /bb thereafter.
        bb = max(1, int(getattr(engine, "decode_bblock", 1)))
        stream_chunk = serving.page_size if serving.paged else 256
        dma_steps = (cfg.num_layers
                     * -(-n_slots // bb)
                     * max(1, -(-int(max(1.0, mean_ctx)) // stream_chunk)))
        model_tag = "tiny-qwen3 DRY" if dry else "qwen3-0.6b"
        out = {
            "metric": f"{model_tag} decode tokens/sec/chip "
                      f"(batch={n_slots}, {platform})",
            "value": round(tps, 2),
            "unit": "tokens/sec",
            "vs_baseline": round(tps / L4_BASELINE_TOKS, 3),
            "platform": platform,
            "attention_impl": impl,
            "kv_dtype": serving.kv_dtype,
            "weights_dtype": serving.weights_dtype,
            "paged": serving.paged,
            "decode_pipeline": serving.decode_pipeline,
            "bblock": bb,
            "dma_steps_per_substep": int(dma_steps),
            "prefill_batch": serving.max_prefill_batch,
            "prefill_chunk": serving.prefill_chunk,
            "ttft_p50_ms": round(ttft_p50_ms, 2),
            "batch": n_slots,
            "decode_horizon": horizon,
            **extra,
            **roof,
        }
        if dry:
            # --dry is a field-plumbing proof, never a perf claim: label it
            # and carry the newest banked TPU artifact like any other
            # no-chip result
            out["dry"] = True
            out["tpu_unavailable"] = True
            out["last_tpu"] = _last_tpu_artifact()
        if roof:
            out["pct_of_ceiling"] = round(100 * tps / roof["ceiling_toks_per_s"], 1)
            if "device_only_toks_per_s" in out:
                # The kernel term alone: what the chip does once the link's
                # per-dispatch RTT is subtracted out.
                out["pct_of_ceiling_device_only"] = round(
                    100 * out["device_only_toks_per_s"]
                    / roof["ceiling_toks_per_s"], 1)
        if partial:
            out["partial"] = True
        if on_tpu and impl != "pallas":
            out["warning"] = ("pallas kernel not selected on tpu — number "
                              "measures the XLA fallback")
        print(json.dumps(out), flush=True)

    # First short window → stream a partial line immediately (a later hang
    # still leaves a number in the parent's capture).
    toks, dt = timed_window(first_window)
    assert toks > 0, "no tokens generated in timed window"
    result_line(toks / dt, partial=True, extra={"timed_tokens": int(toks)})

    # Full window, deadline-aware: scale steps to the time the first window
    # measured, never past the remaining per-slot budget or the deadline.
    per_step = dt / first_window
    steps_left = min(target_steps - first_window,
                     int(max(0.0, remaining() - 30.0) / max(per_step, 1e-6)))
    total_toks, total_dt = toks, dt
    if steps_left > 0:
        toks2, dt2 = timed_window(steps_left)
        total_toks += toks2
        total_dt += dt2
    n_steps = first_window + max(0, steps_left)

    # Dispatch-latency decomposition (VERDICT r3 next #2): p50 round-trip of
    # a trivially small jitted dispatch isolates the host<->chip link cost
    # (the bench chip is network-attached); the decode path dispatches ONE
    # fused program per engine.step (engine.py fused horizon), so
    # step wall minus one RTT estimates the device-resident share. This
    # turns "the ~70% gap is the tunnel" from an argument into two numbers:
    # device_only_toks_per_s is the kernel term, the rest is the link.
    link = {}
    if remaining() > 8.0:
        noop = jax.jit(lambda x: x + 1.0)
        tiny = jnp.zeros((8,), jnp.float32)
        jax.block_until_ready(noop(tiny))          # compile outside the timing
        rtts = []
        for _ in range(15):
            t0r = time.monotonic()
            jax.block_until_ready(noop(tiny))
            rtts.append(time.monotonic() - t0r)
        rtt_ms = 1e3 * sorted(rtts)[len(rtts) // 2]
        step_ms = 1e3 * total_dt / n_steps
        dev_ms = max(0.0, step_ms - rtt_ms)
        link = {
            "dispatch_rtt_ms": round(rtt_ms, 2),
            "decode_step_wall_ms": round(step_ms, 2),
            "device_step_ms": round(dev_ms, 2),
        }
        if dev_ms > 0:
            link["device_only_toks_per_s"] = round(
                n_slots * horizon / (dev_ms / 1e3), 1)
    result_line(total_toks / total_dt, partial=False,
                extra={"timed_tokens": int(total_toks),
                       "timed_steps": n_steps,
                       "measure_wall_s": round(time.monotonic() - t_start, 1),
                       **link})


def _coldstart_child() -> None:
    """One time-to-ready sample in a FRESH process: build the tiny engine
    and run full warmup against the compile cache dir the parent chose
    (TPU_BENCH_CACHE_DIR; empty = cold). With TPU_BENCH_AOT_MANIFEST set,
    adopt the manifest first — the server's exact start sequence. Prints one
    JSON line: {"ready_s", "warmup_s"}."""
    import jax

    cache_dir = os.environ.get("TPU_BENCH_CACHE_DIR", "")
    if cache_dir:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # CPU programs compile in ~1s each; the server's 1.0s threshold
        # would cache only some of them and make warm-vs-cold noise.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    import jax.numpy as jnp

    from aws_k8s_ansible_provisioner_tpu.config import ServingConfig, tiny_qwen3
    from aws_k8s_ansible_provisioner_tpu.models.layers import init_params
    from aws_k8s_ansible_provisioner_tpu.serving.engine import Engine

    t0 = time.monotonic()
    cfg = tiny_qwen3()
    serving = ServingConfig(model="tiny-qwen3", max_decode_slots=4,
                            max_cache_len=64)
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.bfloat16)
    engine = Engine(cfg, params, serving)
    manifest = os.environ.get("TPU_BENCH_AOT_MANIFEST", "")
    if manifest:
        engine.load_aot_manifest(manifest)
    t1 = time.monotonic()
    engine.warmup()
    ready = time.monotonic()
    print(json.dumps({"ready_s": round(ready - t0, 2),
                      "warmup_s": round(ready - t1, 2)}), flush=True)


def coldstart() -> None:
    """Time-to-ready A/B/C: cache-cold vs cache-warm vs AOT-preloaded.

    Three fresh child processes build the same tiny engine + full warmup:
      cold  — empty persistent compile cache (every program pays XLA);
      warm  — the cache the cold run just populated (container-restart case);
      aot   — a cache populated by `serving.aot --cache-dir` plus manifest
              adoption, with NO prior engine run (fresh-replica case: the
              deploy pipeline compiled, the pod never has).
    Writes BENCH_coldstart_r01.json; warm and aot must beat cold outright —
    that delta IS the cold-start elimination this subsystem ships.
    """
    import shutil
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__))
    work = tempfile.mkdtemp(prefix="coldstart-")
    env_base = {**os.environ, "JAX_PLATFORMS":
                os.environ.get("JAX_PLATFORMS", "cpu")}

    def child(cache_dir: str, manifest: str = "") -> dict:
        env = {**env_base, "TPU_BENCH_CACHE_DIR": cache_dir}
        if manifest:
            env["TPU_BENCH_AOT_MANIFEST"] = manifest
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--coldstart-child"],
            env=env, capture_output=True, text=True, timeout=600, cwd=here)
        if p.returncode != 0:
            raise RuntimeError(f"coldstart child failed:\n{p.stdout}\n"
                               f"{p.stderr}")
        return json.loads(p.stdout.strip().splitlines()[-1])

    try:
        shared = os.path.join(work, "cache")
        cold = child(shared)             # populates `shared` as it compiles
        warm = child(shared)             # container-restart: same cache
        aot_cache = os.path.join(work, "aot-cache")
        manifest = os.path.join(work, "aot.json")
        t0 = time.monotonic()
        p = subprocess.run(
            [sys.executable, "-m",
             "aws_k8s_ansible_provisioner_tpu.serving.aot",
             "--model", "tiny-qwen3", "--platform", "host", "--tp", "1",
             "--slots", "4", "--max-cache-len", "64", "--quiet",
             "--cache-dir", aot_cache, "--out", manifest],
            env=env_base, capture_output=True, text=True, timeout=600,
            cwd=here)
        if p.returncode != 0:
            raise RuntimeError(f"aot compile failed:\n{p.stdout}\n{p.stderr}")
        aot_compile_s = round(time.monotonic() - t0, 2)
        aot = child(aot_cache, manifest=manifest)  # fresh replica + manifest
    finally:
        shutil.rmtree(work, ignore_errors=True)

    out = {
        "bench": "coldstart", "rev": "r01",
        "model": "tiny-qwen3", "platform": env_base["JAX_PLATFORMS"],
        "cold_ready_s": cold["ready_s"], "cold_warmup_s": cold["warmup_s"],
        "warm_ready_s": warm["ready_s"], "warm_warmup_s": warm["warmup_s"],
        "aot_ready_s": aot["ready_s"], "aot_warmup_s": aot["warmup_s"],
        # deploy-time cost that buys the aot_ready_s floor (runs once per
        # config in the pipeline, not per replica)
        "aot_compile_s": aot_compile_s,
        "warm_speedup": round(cold["ready_s"] / max(0.01, warm["ready_s"]),
                              2),
        "aot_speedup": round(cold["ready_s"] / max(0.01, aot["ready_s"]), 2),
    }
    print(json.dumps(out), flush=True)
    if not (warm["ready_s"] < cold["ready_s"]
            and aot["ready_s"] < cold["ready_s"]):
        raise SystemExit(f"coldstart bench: cache/AOT start did not beat "
                         f"cold ({out})")
    path = os.path.join(here, "BENCH_coldstart_r01.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=1)
        f.write("\n")


def pipeline() -> None:
    """Sync-vs-pipelined decode A/B on the CPU tiny model.

    Two engines in one process (the second reuses the first's jitted
    programs), identical seeded load, decode_pipeline=0 then 1. Reads the
    engine's own split metrics: tok/s, accumulated host-bubble seconds
    (tpu_serve_decode_bubble_seconds_total — the device-idle gap between a
    fetch completing and the next dispatch) and device-busy seconds. The
    pipelined pass must match-or-beat sync tok/s with LESS bubble — that
    delta is the host emit/SSE/scheduling time the one-deep pipeline hides
    behind device compute. Writes BENCH_pipeline_r01.json. On CPU the
    "device" is the XLA host threadpool, so the overlap is real but the
    per-dispatch gap is Python-emit-sized; on a network-attached TPU the
    sync loop additionally pays ~one dispatch RTT per step (see
    BENCH.json's dispatch_rtt_ms ≈ 89.5 ms), which is the production-sized
    version of the same bubble.
    """
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))

    import jax.numpy as jnp

    from aws_k8s_ansible_provisioner_tpu.config import (ServingConfig,
                                                        tiny_qwen3)
    from aws_k8s_ansible_provisioner_tpu.models.layers import init_params
    from aws_k8s_ansible_provisioner_tpu.serving.engine import Engine, Request

    steps = int(os.environ.get("TPU_BENCH_PIPELINE_STEPS", "80"))
    batch = int(os.environ.get("TPU_BENCH_PIPELINE_BATCH", "8"))
    horizon = 4

    def run(decode_pipeline: int) -> dict:
        cfg = tiny_qwen3()
        serving = ServingConfig(
            model="tiny-qwen3", max_decode_slots=batch,
            max_cache_len=16 + (steps + 8) * horizon,
            prefill_buckets=(32,), decode_horizon=horizon,
            decode_pipeline=decode_pipeline, dtype="float32")
        params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
        engine = Engine(cfg, params, serving)
        engine.warmup(scope="bench")
        for i in range(batch):
            engine.submit(Request(
                prompt_ids=[(11 * i + 5) % (cfg.vocab_size - 20) + 10] * 16,
                max_tokens=serving.max_cache_len - 20, ignore_eos=True))
        while engine.pending:
            engine.step()
        for _ in range(5):
            engine.step()           # warm the decode path / fill the pipe
        m = engine.metrics
        toks0 = m.generated_tokens.total()
        bub0 = m.decode_bubble_seconds.total()
        dev0 = m.device_busy_seconds.total()
        t0 = time.monotonic()
        for _ in range(steps):
            engine.step()
        if engine._inflight is not None:
            # count the trailing in-flight dispatch inside the timed window
            # — the pipelined pass must not get a free unfetched dispatch
            engine._drain_decode_pipeline()
        dt = time.monotonic() - t0
        return {
            "toks_per_s": (m.generated_tokens.total() - toks0) / dt,
            "bubble_s": m.decode_bubble_seconds.total() - bub0,
            "device_s": m.device_busy_seconds.total() - dev0,
            "wall_s": dt,
        }

    sync, pipe = run(0), run(1)
    out = {
        "bench": "pipeline", "rev": "r01",
        "model": "tiny-qwen3", "platform": jax.devices()[0].platform,
        "batch": batch, "decode_horizon": horizon, "timed_steps": steps,
        "sync_toks_per_s": round(sync["toks_per_s"], 1),
        "pipe_toks_per_s": round(pipe["toks_per_s"], 1),
        "speedup": round(pipe["toks_per_s"] / max(1e-9, sync["toks_per_s"]),
                         3),
        "sync_bubble_s": round(sync["bubble_s"], 4),
        "pipe_bubble_s": round(pipe["bubble_s"], 4),
        "bubble_reduction_pct": round(
            100.0 * (1.0 - pipe["bubble_s"] / max(1e-9, sync["bubble_s"])),
            1),
        "sync_device_s": round(sync["device_s"], 4),
        "pipe_device_s": round(pipe["device_s"], 4),
        # sync-mode host gap per dispatch: what each dispatch would pay
        # again on top of RTT over a network-attached link
        "sync_bubble_ms_per_step": round(1e3 * sync["bubble_s"] / steps, 3),
    }
    print(json.dumps(out), flush=True)
    if not (pipe["toks_per_s"] >= sync["toks_per_s"]
            and pipe["bubble_s"] < sync["bubble_s"]):
        raise SystemExit(f"pipeline bench: pipelined pass did not beat sync "
                         f"({out})")
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_pipeline_r01.json"), "w",
              encoding="utf-8") as f:
        json.dump(out, f, indent=1)
        f.write("\n")


def ragged() -> None:
    """Ragged-vs-sync mixed-batch A/B under chunked-prefill-heavy load.

    Two engines in one process (the second reuses the first's jitted
    programs), identical seeded load, ragged_attention=0 then 1 — both with
    the one-deep decode pipeline ON and chunked prefill forced, so the A/B
    isolates exactly what ISSUE 14 changed: the legacy path drains the
    pipeline at every prefill/chunk admission edge (one settle + one
    standalone chunk dispatch per chunk), the ragged path packs each chunk
    alongside the live decode batch into one mixed_step dispatch and never
    drains. The timed window keeps a background decode batch generating
    while a stream of long prompts chunk through — the workload whose
    admission edges the old path paid for once per chunk. Reads the
    engine's own metrics (tok/s over the window) plus the pipeline
    drain/dispatch counters (serving/metrics.py PipelineMetrics) and writes
    BENCH_ragged_r01.json. The ragged pass must match-or-beat sync tok/s
    with ZERO admission-edge drains; on CPU the per-drain cost is
    Python-settle-sized, on a network-attached TPU each drain additionally
    pays ~one dispatch RTT (BENCH.json dispatch_rtt_ms ≈ 89.5 ms) before
    the chunk can even dispatch.
    """
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))

    import jax.numpy as jnp

    from aws_k8s_ansible_provisioner_tpu.config import (ServingConfig,
                                                        tiny_qwen3)
    from aws_k8s_ansible_provisioner_tpu.models.layers import init_params
    from aws_k8s_ansible_provisioner_tpu.serving import metrics as _smetrics
    from aws_k8s_ansible_provisioner_tpu.serving.engine import Engine, Request

    batch = int(os.environ.get("TPU_BENCH_RAGGED_BATCH", "4"))
    prompts = int(os.environ.get("TPU_BENCH_RAGGED_PROMPTS", "12"))
    plen = int(os.environ.get("TPU_BENCH_RAGGED_PROMPT_LEN", "96"))
    chunk = int(os.environ.get("TPU_BENCH_RAGGED_CHUNK", "16"))

    def edge_drains() -> int:
        by = _smetrics.pipeline.snapshot().get("drains_by_reason", {})
        return int(by.get("prefill", 0)) + int(by.get("chunk", 0))

    def run(ragged_attention: int) -> dict:
        cfg = tiny_qwen3()
        serving = ServingConfig(
            model="tiny-qwen3", max_decode_slots=batch + 2,
            max_cache_len=512, prefill_buckets=(32,), decode_horizon=4,
            prefill_chunk=chunk, decode_pipeline=1,
            ragged_attention=ragged_attention, dtype="float32")
        params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
        engine = Engine(cfg, params, serving)
        engine.warmup(scope="bench")
        # Background decode batch: long-running streams that occupy `batch`
        # slots for the whole window — the live rows every chunk admission
        # either packs alongside (ragged) or drains out from under (sync).
        for i in range(batch):
            engine.submit(Request(
                prompt_ids=[(11 * i + 5) % (cfg.vocab_size - 20) + 10] * 16,
                max_tokens=360, ignore_eos=True, seed=100 + i))
        while engine.pending:
            engine.step()
        for _ in range(5):
            engine.step()           # warm the decode path / fill the pipe
        # Chunked-prefill-heavy phase: a queue of long prompts churns
        # through the two spare slots, each one chunking plen/chunk times.
        jobs = [engine.submit(Request(
            prompt_ids=[(7 * i + 3) % (cfg.vocab_size - 20) + 10] * plen,
            max_tokens=4, seed=500 + i)) for i in range(prompts)]
        m = engine.metrics
        toks0 = m.generated_tokens.total()
        drains0, disp0 = edge_drains(), \
            _smetrics.pipeline.snapshot()["dispatches_total"]
        t0 = time.monotonic()
        while not all(r.finish_reason for r in jobs):
            engine.step()
        if engine._inflight is not None:
            # count the trailing in-flight dispatch inside the timed window
            engine._drain_decode_pipeline()
        dt = time.monotonic() - t0
        assert all(r.finish_reason == "length" for r in jobs), \
            [r.finish_reason for r in jobs]
        return {
            "toks_per_s": (m.generated_tokens.total() - toks0) / dt,
            "edge_drains": edge_drains() - drains0,
            "dispatches": _smetrics.pipeline.snapshot()["dispatches_total"]
            - disp0,
            "wall_s": dt,
        }

    sync, rag = run(0), run(1)
    out = {
        "bench": "ragged", "rev": "r01",
        "model": "tiny-qwen3", "platform": jax.devices()[0].platform,
        "batch": batch, "prompts": prompts, "prompt_len": plen,
        "prefill_chunk": chunk,
        "sync_toks_per_s": round(sync["toks_per_s"], 1),
        "ragged_toks_per_s": round(rag["toks_per_s"], 1),
        "speedup": round(rag["toks_per_s"] / max(1e-9, sync["toks_per_s"]),
                         3),
        # the structural claim: the old path drained once per admission
        # edge, the ragged path holds the pipe open through every chunk
        "sync_edge_drains": sync["edge_drains"],
        "ragged_edge_drains": rag["edge_drains"],
        "sync_dispatches": sync["dispatches"],
        "ragged_dispatches": rag["dispatches"],
        "sync_wall_s": round(sync["wall_s"], 3),
        "ragged_wall_s": round(rag["wall_s"], 3),
    }
    print(json.dumps(out), flush=True)
    if not (rag["toks_per_s"] >= sync["toks_per_s"]
            and rag["edge_drains"] == 0 and sync["edge_drains"] > 0):
        raise SystemExit(f"ragged bench: mixed path did not beat the sync "
                         f"fallback ({out})")
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_ragged_r01.json"), "w",
              encoding="utf-8") as f:
        json.dump(out, f, indent=1)
        f.write("\n")


def mixed_features() -> None:
    """Feature-vs-plain A/B on the ragged pipeline (the fallback-tax bench).

    ISSUE 16's claim: spec decode, guided decoding, and LoRA ride the same
    ragged mixed-batch pipeline as vanilla traffic, so a workload mixing ALL
    of them (spec + guided + LoRA + chunked prefill, concurrently) holds
    within 10% of plain-traffic tok/s with ZERO feature-reason pipeline
    drains — where the PR-14 gating de-pipelined every tenant the moment
    one guided or LoRA request was admitted. Two engines in one process run
    the same workload shape: run A is a featureless engine under plain
    traffic, run B enables spec decode, loads a LoRA adapter, and tags the
    traffic with grammars/adapters. Reads the engine's own token counters
    plus the pipeline drain ledger (serving/metrics.py PipelineMetrics) and
    writes BENCH_mixedfeat_r01.json. Run B must keep
    drains{prefill,chunk,spec,guided} == 0 and land >= 0.9x run A's tok/s.
    """
    import json as _json
    import tempfile

    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))

    import numpy as np
    import jax.numpy as jnp

    from aws_k8s_ansible_provisioner_tpu.config import (ServingConfig,
                                                        tiny_qwen3)
    from aws_k8s_ansible_provisioner_tpu.models.layers import init_params
    from aws_k8s_ansible_provisioner_tpu.serving import metrics as _smetrics
    from aws_k8s_ansible_provisioner_tpu.serving.engine import Engine, Request
    from aws_k8s_ansible_provisioner_tpu.serving.guided import grammar_for
    from aws_k8s_ansible_provisioner_tpu.utils.tokenizer import ByteTokenizer

    batch = int(os.environ.get("TPU_BENCH_MIXEDFEAT_BATCH", "4"))
    prompts = int(os.environ.get("TPU_BENCH_MIXEDFEAT_PROMPTS", "6"))
    plen = int(os.environ.get("TPU_BENCH_MIXEDFEAT_PROMPT_LEN", "96"))
    chunk = int(os.environ.get("TPU_BENCH_MIXEDFEAT_CHUNK", "16"))
    # background streams must OUTLIVE the timed churn window (the batch is
    # never pure-guided, so mixed batches keep the fused horizon): sized to
    # the cache, finished untimed after the window closes
    bg_toks = int(os.environ.get("TPU_BENCH_MIXEDFEAT_BG_TOKENS", "450"))

    tok = ByteTokenizer()
    cfg = tiny_qwen3(vocab_size=tok.vocab_size,
                     eos_token_id=tok.eos_token_id)

    def write_adapter(tmp: str) -> str:
        """Minimal peft-format adapter dir (rank-4, q/v/up targets)."""
        from safetensors import numpy as st_np

        rng = np.random.default_rng(7)
        os.makedirs(tmp, exist_ok=True)
        with open(os.path.join(tmp, "adapter_config.json"), "w",
                  encoding="utf-8") as f:
            f.write(_json.dumps({
                "peft_type": "LORA", "r": 4, "lora_alpha": 8,
                "target_modules": ["q_proj", "v_proj", "up_proj"]}))
        dims = {"q_proj": (cfg.q_size, cfg.hidden_size),
                "v_proj": (cfg.kv_size, cfg.hidden_size),
                "up_proj": (cfg.intermediate_size, cfg.hidden_size)}
        tensors = {}
        for layer in range(cfg.num_layers):
            for t, (dout, din) in dims.items():
                mod = "mlp" if t == "up_proj" else "self_attn"
                base = f"base_model.model.model.layers.{layer}.{mod}.{t}"
                tensors[f"{base}.lora_A.weight"] = \
                    (0.05 * rng.standard_normal((4, din))).astype(np.float32)
                tensors[f"{base}.lora_B.weight"] = \
                    (0.05 * rng.standard_normal((dout, 4))).astype(np.float32)
        st_np.save_file(tensors,
                        os.path.join(tmp, "adapter_model.safetensors"))
        return tmp

    # grammar bias: pressure the random-weight model toward closing the
    # JSON object (tests/test_guided.py's _PRESSURE) so guided streams
    # finish instead of wandering the grammar until max_tokens
    eos = tok.eos_token_id
    pressure = ((ord(' '), -50.0), (ord('\t'), -50.0), (ord('\n'), -50.0),
                (ord('\r'), -50.0), (ord('['), -20.0), (ord('\\'), -100.0),
                (ord('"'), 30.0), (ord('}'), 20.0), (ord(']'), 15.0),
                (ord(':'), 20.0), (ord(','), 5.0), (eos, 100.0))

    def feature_drains() -> int:
        by = _smetrics.pipeline.snapshot().get("drains_by_reason", {})
        return int(by.get("spec", 0)) + int(by.get("guided", 0))

    def edge_drains() -> int:
        by = _smetrics.pipeline.snapshot().get("drains_by_reason", {})
        return int(by.get("prefill", 0)) + int(by.get("chunk", 0))

    def run(features: bool, adapter_dir: str) -> dict:
        serving = ServingConfig(
            model="tiny-qwen3", max_decode_slots=batch + 2,
            max_cache_len=512, prefill_buckets=(32,), decode_horizon=4,
            prefill_chunk=chunk, decode_pipeline=1, ragged_attention=1,
            ragged_features=1, dtype="float32",
            spec_decode=features, spec_k=4, spec_ngram=3)
        params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
        engine = Engine(cfg, params, serving,
                        lora={"mf": adapter_dir} if features else None)
        engine.warmup(scope="bench")
        g = grammar_for(tok, {"type": "json_object"}, [eos]) \
            if features else None

        def background(i: int):
            return engine.submit(Request(
                prompt_ids=tok.encode("ab" * 8), max_tokens=bg_toks,
                ignore_eos=True, temperature=0.0,
                lora=("mf" if features and i % 2 == 0 else None)))

        churn, done = [], []
        # Background decode rows occupying `batch` slots for the WHOLE
        # window: greedy repetitive prompts (spec-friendly); half carry the
        # adapter in the feature run.
        bg = [background(i) for i in range(batch)]
        while engine.pending:
            engine.step()
        for _ in range(5):
            engine.step()           # warm the decode path / fill the pipe
        m = engine.metrics
        toks0 = m.generated_tokens.total()
        fd0, ed0 = feature_drains(), edge_drains()
        disp0 = _smetrics.pipeline.snapshot()["dispatches_total"]
        t0 = time.monotonic()
        # Churn phase through the two spare slots: long chunking prompts
        # interleaved with guided (feature run) or bias-identical plain
        # (plain run) short jobs. The window closes when the churn clears —
        # the backgrounds are still decoding, so the timed region is the
        # steady mixed state, not a guided-only tail.
        for i in range(prompts):
            churn.append(engine.submit(Request(
                prompt_ids=tok.encode("x" * plen), max_tokens=4,
                temperature=0.0, seed=500 + i)))
            churn.append(engine.submit(Request(
                prompt_ids=tok.encode("json:"), max_tokens=24,
                temperature=0.0, logit_bias=pressure,
                guided=g, seed=900 + i)))
        while not all(r.finish_reason for r in churn):
            engine.step()
            # Keep every background slot occupied: the timed region must
            # stay the steady MIXED state. Spec decode finishes backgrounds
            # ~5x sooner in the feature run; a drained background slot would
            # tip the batch toward pure-guided (horizon 1) and measure a
            # different workload than the plain arm.
            for i, r in enumerate(bg):
                if r.finish_reason:
                    done.append(r)
                    bg[i] = background(i)
        dt = time.monotonic() - t0
        toks = m.generated_tokens.total() - toks0
        while not all(r.finish_reason for r in bg):   # untimed run-out
            engine.step()
        if engine._inflight is not None:
            # trailing in-flight dispatch (reason "drain": deliberate,
            # excluded from the tax ledger)
            engine._drain_decode_pipeline()
        bad = [r.finish_reason for r in bg + done + churn
               if r.finish_reason not in ("stop", "length")]
        assert not bad, bad
        return {
            "toks_per_s": toks / dt,
            "feature_drains": feature_drains() - fd0,
            "edge_drains": edge_drains() - ed0,
            "dispatches": _smetrics.pipeline.snapshot()["dispatches_total"]
            - disp0,
            "wall_s": dt,
        }

    with tempfile.TemporaryDirectory() as tmp:
        adapter = write_adapter(os.path.join(tmp, "mf"))
        plain, feat = run(False, adapter), run(True, adapter)
    ratio = feat["toks_per_s"] / max(1e-9, plain["toks_per_s"])
    out = {
        "bench": "mixedfeat", "rev": "r01",
        "model": "tiny-qwen3", "platform": jax.devices()[0].platform,
        "batch": batch, "prompts": prompts, "prompt_len": plen,
        "prefill_chunk": chunk, "spec_k": 4,
        "plain_toks_per_s": round(plain["toks_per_s"], 1),
        "mixedfeat_toks_per_s": round(feat["toks_per_s"], 1),
        "mixedfeat_ratio": round(ratio, 3),
        # the structural claim: feature traffic pays ZERO pipeline drains —
        # no spec pre-drain, no guided de-pipelining, no admission edges
        "feature_drains": feat["feature_drains"],
        "edge_drains": feat["edge_drains"],
        "plain_dispatches": plain["dispatches"],
        "mixedfeat_dispatches": feat["dispatches"],
        "plain_wall_s": round(plain["wall_s"], 3),
        "mixedfeat_wall_s": round(feat["wall_s"], 3),
    }
    print(json.dumps(out), flush=True)
    if not (ratio >= 0.9 and feat["feature_drains"] == 0
            and feat["edge_drains"] == 0):
        raise SystemExit(f"mixedfeat bench: feature traffic paid the "
                         f"fallback tax ({out})")
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_mixedfeat_r01.json"), "w",
              encoding="utf-8") as f:
        json.dump(out, f, indent=1)
        f.write("\n")


def prefix_tier() -> None:
    """Warm-host-tier TTFT vs cold-re-prefill TTFT A/B (ISSUE 20).

    Two engines in one process (the second reuses the first's jitted
    programs), identical seeded workload: a long prompt A is served, then
    two same-length fillers churn through a deliberately small page pool so
    A's indexed prefix pages are LRU-reclaimed. Then A is re-submitted and
    TTFT is timed. Run COLD has ``kv_host_tier_bytes=0`` (the byte-identity
    escape hatch): reclaim destroys the prefix and the re-submit re-prefills
    all of it through the chunk program, one dispatch per chunk. Run WARM
    has the tier on: reclaim spilled the pages to host RAM, the re-submit
    restores them with one batched scatter and prefills only the suffix
    past the restored frontier. Writes BENCH_prefixtier_r01.json. Bound:
    warm-host TTFT must be >= 3x better than cold re-prefill (the ISSUE 20
    acceptance line for prompts >= 512 tokens) — on CPU the cold run pays
    ~plen/chunk Python+XLA chunk dispatches, on a network-attached TPU each
    additionally pays ~one dispatch RTT, while the warm run pays one
    host->HBM DMA plus a single suffix chunk.
    """
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))

    import jax.numpy as jnp

    from aws_k8s_ansible_provisioner_tpu.config import (ServingConfig,
                                                        tiny_qwen3)
    from aws_k8s_ansible_provisioner_tpu.models.layers import init_params
    from aws_k8s_ansible_provisioner_tpu.serving.engine import Engine, Request

    plen = int(os.environ.get("TPU_BENCH_PREFIXTIER_PROMPT_LEN", "520"))
    chunk = int(os.environ.get("TPU_BENCH_PREFIXTIER_CHUNK", "32"))
    ps = int(os.environ.get("TPU_BENCH_PREFIXTIER_PAGE_SIZE", "16"))
    pool = int(os.environ.get("TPU_BENCH_PREFIXTIER_POOL_PAGES", "56"))

    def mk_prompt(i: int) -> list:
        cfg = tiny_qwen3()
        return [(7 * i + 3 + j) % (cfg.vocab_size - 20) + 10
                for j in range(plen)]

    def run(tier_bytes: int) -> dict:
        # the stock tiny model's 128-token window can't hold a >=512-token
        # prompt — widen the model window; everything else stays tiny
        cfg = tiny_qwen3(max_seq_len=2048)
        serving = ServingConfig(
            model="tiny-qwen3", max_decode_slots=4,
            max_cache_len=plen + 3 * ps, prefill_buckets=(chunk,),
            prefill_chunk=chunk, page_size=ps, paged=True,
            kv_pool_pages=pool, kv_host_tier_bytes=tier_bytes,
            dtype="float32")
        params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
        engine = Engine(cfg, params, serving)
        engine.warmup(scope="bench")

        def serve(prompt: list) -> "Request":
            r = engine.submit(Request(prompt_ids=list(prompt), max_tokens=4,
                                      ignore_eos=True))
            while not r.finish_reason:
                engine.step()
            return r

        a = mk_prompt(0)
        first = serve(a)                  # seeds the prefix chain
        for i in (1, 2):                  # LRU-reclaims A's pages
            serve(mk_prompt(i))
        # one untimed evict->re-serve cycle first, so the timed window
        # measures the steady-state path, not one-time jit compilation of
        # the restore scatter (cold run does the same cycle for symmetry)
        serve(a)
        for i in (1, 2):
            serve(mk_prompt(i))
        t0 = time.monotonic()
        r = engine.submit(Request(prompt_ids=list(a), max_tokens=4,
                                  ignore_eos=True))
        while not r.generated:
            engine.step()
        ttft = time.monotonic() - t0
        while not r.finish_reason:
            engine.step()
        assert r.generated == first.generated, "re-serve must be stream-identical"
        m = engine.metrics
        return {
            "ttft_ms": ttft * 1e3,
            "host_hits": int(m.prefix_tier_hits.value(tier="host")),
            "restore_bytes": int(m.kv_restore_bytes.total()),
            "spill_bytes": int(m.kv_spill_bytes.total()),
        }

    cold, warm = run(0), run(256 * 2**20)
    out = {
        "bench": "prefixtier", "rev": "r01",
        "model": "tiny-qwen3", "platform": jax.devices()[0].platform,
        "prompt_len": plen, "prefill_chunk": chunk, "page_size": ps,
        "kv_pool_pages": pool,
        "coldprefill_ttft_ms": round(cold["ttft_ms"], 2),
        "warmhost_ttft_ms": round(warm["ttft_ms"], 2),
        "prefixtier_speedup": round(cold["ttft_ms"]
                                    / max(1e-9, warm["ttft_ms"]), 3),
        # the structural claim: cold re-prefilled (no tier traffic), warm
        # restored the evicted prefix from host RAM
        "cold_host_hits": cold["host_hits"],
        "warm_host_hits": warm["host_hits"],
        "warm_restore_bytes": warm["restore_bytes"],
        "warm_spill_bytes": warm["spill_bytes"],
    }
    print(json.dumps(out), flush=True)
    if not (out["prefixtier_speedup"] >= 3.0
            and warm["host_hits"] >= 1 and cold["host_hits"] == 0
            and warm["restore_bytes"] > 0):
        raise SystemExit(f"prefixtier bench: host restore did not beat cold "
                         f"re-prefill by >= 3x ({out})")
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_prefixtier_r01.json"), "w",
              encoding="utf-8") as f:
        json.dump(out, f, indent=1)
        f.write("\n")


if __name__ == "__main__":
    if "--measure" in sys.argv:
        measure()
    elif "--coldstart-child" in sys.argv:
        _coldstart_child()
    elif "--coldstart" in sys.argv:
        coldstart()
    elif "--pipeline" in sys.argv:
        pipeline()
    elif "--ragged" in sys.argv:
        ragged()
    elif "--mixed-features" in sys.argv:
        mixed_features()
    elif "--prefix-tier" in sys.argv:
        prefix_tier()
    elif "--dry" in sys.argv:
        # Seconds-class CPU pass over the tiny model, in-process: proves the
        # whole field plumbing (bblock, weights_dtype, dma_steps_per_substep,
        # last_tpu) without a chip and without the probe/retry machinery.
        os.environ["TPU_BENCH_PLATFORM"] = "cpu"
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["TPU_BENCH_DRY"] = "1"
        measure()
    else:
        main()
