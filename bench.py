"""Headline benchmark: Qwen3-0.6B decode throughput through the serving engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

This is the BASELINE.json metric ("Qwen3-0.6B tokens/sec/chip"). The reference
publishes no numbers (BASELINE.md); the comparison bar is the implicit "≥ 1× L4
tokens/sec" north star. L4_BASELINE_TOKS below is our documented estimate of
vLLM Qwen3-0.6B batched decode on the reference's 1× L4 (g6.4xlarge):
L4 HBM bandwidth is ~300 GB/s and batched decode of a 1.2 GB bf16 model is
bandwidth-bound at ≤250 fwd/s ⇒ ~32-batch ceiling ≈ 8 k tok/s, with realistic
vLLM efficiency ~30-40% ⇒ ~2.5 k tok/s. vs_baseline = measured / 2500.

Measures the REAL serving path (Engine.step: host scheduling + jitted prefill/
decode with donated KV cache), not a stripped microbench.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

L4_BASELINE_TOKS = 2500.0


def main() -> None:
    from aws_k8s_ansible_provisioner_tpu.config import QWEN3_0_6B, ServingConfig
    from aws_k8s_ansible_provisioner_tpu.models.layers import init_params
    from aws_k8s_ansible_provisioner_tpu.serving.engine import Engine, Request

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"

    cfg = QWEN3_0_6B
    serving = ServingConfig(
        max_decode_slots=32 if on_tpu else 4,
        max_cache_len=1024 if on_tpu else 128,
        prefill_buckets=(32,),
        # Large fused horizon amortizes host->device dispatch (the chip is
        # network-attached under the bench harness); serving keeps the smaller
        # default so streaming latency stays bounded.
        decode_horizon=32 if on_tpu else 4,
    )
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.bfloat16)
    engine = Engine(cfg, params, serving)

    # Fill every decode slot with a short prompt; never stop on eos/budget.
    n_slots = serving.max_decode_slots
    gen_budget = serving.max_cache_len - 64
    for i in range(n_slots):
        engine.submit(Request(prompt_ids=[(7 * i + 3) % 1000 + 10] * 16,
                              max_tokens=gen_budget, ignore_eos=True))
    while engine.pending:  # prefills (compiles bucket-32 + decode programs)
        engine.step()
    # Warm the decode program.
    for _ in range(3):
        engine.step()

    # Timed decode window. Each step emits up to decode_horizon tokens per
    # slot, so size the window within the per-slot budget (all slots stay
    # active throughout) and count ACTUAL emitted tokens via the metrics
    # counter, not steps * slots.
    horizon = max(1, serving.decode_horizon)
    target_steps = min(100, (gen_budget - 8 * horizon) // horizon) if on_tpu \
        else 4
    jax.block_until_ready(engine.cache["k"])
    toks0 = engine.metrics.generated_tokens.total()
    t0 = time.monotonic()
    steps = 0
    while steps < target_steps:
        engine.step()
        steps += 1
    jax.block_until_ready(engine.cache["k"])
    dt = time.monotonic() - t0
    toks = engine.metrics.generated_tokens.total() - toks0
    assert toks > 0, "no tokens generated in timed window"
    tps = toks / dt
    print(json.dumps({
        "metric": f"qwen3-0.6b decode tokens/sec/chip (batch={n_slots}, {platform})",
        "value": round(tps, 2),
        "unit": "tokens/sec",
        "vs_baseline": round(tps / L4_BASELINE_TOKS, 3),
    }))


if __name__ == "__main__":
    main()
