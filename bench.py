"""Headline benchmark: Qwen3-0.6B decode throughput through the serving engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

This is the BASELINE.json metric ("Qwen3-0.6B tokens/sec/chip; p50 TTFT").
The reference publishes no numbers (BASELINE.md); the comparison bar is the
implicit ">= 1x L4 tokens/sec" north star. L4_BASELINE_TOKS below is our
documented estimate of vLLM Qwen3-0.6B batched decode on the reference's
1x L4 (g6.4xlarge): L4 HBM bandwidth is ~300 GB/s and batched decode of a
1.2 GB bf16 model is bandwidth-bound at <=250 fwd/s => ~32-batch ceiling
~= 8k tok/s, with realistic vLLM efficiency ~30-40% => ~2.5k tok/s.
vs_baseline = measured / 2500.

Measures the REAL serving path (Engine.step: host scheduling + jitted prefill/
decode with donated KV cache), not a stripped microbench.

Robustness (round-1 postmortem): BENCH_r01 died at `jax.devices()` with a
transient "TPU backend setup/compile error (Unavailable)" before measuring
anything. A failed JAX backend init is cached for the life of the process, so
retries must happen in FRESH subprocesses. This file therefore runs as a thin
parent orchestrator (imports no jax):

  1. up to TPU_TRIES attempts of `python bench.py --measure` with the
     environment's default platform (the real chip), bounded by a timeout;
  2. on persistent failure, one explicit `JAX_PLATFORMS=cpu` fallback so the
     round still gets a number (clearly marked "platform": "cpu");
  3. if even that fails, a JSON line with an "error" field — never a bare
     traceback as the only output.

The measurement child also records the RESOLVED attention impl
("attention_impl": "pallas"|"xla") so a number can never silently measure the
XLA fallback while claiming to be the Pallas path.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

L4_BASELINE_TOKS = 2500.0
# Worst-case time-to-first-JSON: 2 x 900 s TPU attempts + 15 s backoff +
# 600 s CPU fallback ≈ 40 min (typical success ~10 min: ~2 min backend init
# over the tunnel + compile + measure; the CPU fallback runs the small
# config and finishes in single-digit minutes).
TPU_TRIES = 2
TPU_TIMEOUT_S = 900
CPU_TIMEOUT_S = 600
RETRY_BACKOFF_S = 15


# ---------------------------------------------------------------------------
# Parent: subprocess orchestration (no jax imported here)
# ---------------------------------------------------------------------------


def _run_child(env_overrides: dict, timeout: float):
    """One measurement attempt in a fresh process. Returns (json_dict|None, err)."""
    env = dict(os.environ)
    env.update(env_overrides)
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--measure"],
            capture_output=True, text=True, timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        return None, f"timed out after {timeout}s"
    for line in reversed((p.stdout or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                d = json.loads(line)
                if "metric" in d:
                    return d, None
            except (ValueError, TypeError):
                pass
    tail = ((p.stderr or "") + (p.stdout or "")).strip()[-600:]
    return None, f"rc={p.returncode}: {tail}"


def main() -> None:
    errors = []
    for attempt in range(1, TPU_TRIES + 1):
        result, err = _run_child({}, TPU_TIMEOUT_S)
        if result is not None:
            print(json.dumps(result))
            return
        errors.append(f"attempt {attempt} (default platform): {err}")
        sys.stderr.write(f"bench: {errors[-1]}\n")
        if attempt < TPU_TRIES:  # no pointless backoff before the fallback
            time.sleep(RETRY_BACKOFF_S * attempt)
    # Persistent accelerator failure: measure on CPU so the round still has a
    # (clearly labeled) number, and carry the TPU error for the record.
    result, err = _run_child({"JAX_PLATFORMS": "cpu"}, CPU_TIMEOUT_S)
    if result is not None:
        result["error"] = "tpu backend unavailable; cpu fallback measured. " \
            + " | ".join(e[:200] for e in errors)
        print(json.dumps(result))
        return
    errors.append(f"cpu fallback: {err}")
    print(json.dumps({
        "metric": "qwen3-0.6b decode tokens/sec/chip",
        "value": 0.0,
        "unit": "tokens/sec",
        "vs_baseline": 0.0,
        "error": " | ".join(e[:300] for e in errors),
    }))


# ---------------------------------------------------------------------------
# Child: the actual measurement (fresh process per attempt)
# ---------------------------------------------------------------------------


def _parent_watchdog() -> None:
    """Exit the measurement child if its orchestrating parent dies.

    An outer ``timeout N python bench.py`` kills only the parent; the
    ``--measure`` child would keep running — and keep the TPU chip locked —
    indefinitely (observed r2: an orphaned child wedged every subsequent
    bench attempt). Reparenting to init (ppid 1) is the orphan signal.
    """
    import threading

    ppid0 = os.getppid()

    def watch():
        while True:
            if os.getppid() != ppid0:   # reparented = parent died
                os._exit(3)
            time.sleep(10)

    threading.Thread(target=watch, daemon=True).start()


def measure() -> None:
    _parent_watchdog()
    import jax
    import jax.numpy as jnp

    from aws_k8s_ansible_provisioner_tpu.config import QWEN3_0_6B, ServingConfig
    from aws_k8s_ansible_provisioner_tpu.models.layers import init_params
    from aws_k8s_ansible_provisioner_tpu.ops.attention import resolve_impl
    from aws_k8s_ansible_provisioner_tpu.serving.engine import Engine, Request

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    impl = resolve_impl("auto")

    cfg = QWEN3_0_6B
    # TPU_BENCH_* env overrides let the tuning sweep reuse this exact
    # measurement path; the defaults ARE the tuned config.
    env = os.environ.get
    # The batch default is COUPLED to the cache dtype: bf16 at batch 128
    # doesn't fit (15 GB cache + 1.2 GB weights > 16 GB HBM), so a bf16
    # sweep run inherits the bf16-feasible batch unless it overrides both.
    kv_dtype = env("TPU_BENCH_KV_DTYPE", "int8" if on_tpu else "auto")
    default_batch = 128 if kv_dtype == "int8" else 64
    serving = ServingConfig(
        # Batch/horizon from the measured v5e sweeps (r2): bf16 32/32 → 3279
        # tok/s, 64/32 → 4190, 64/64 → 4511. int8 KV halves the cache
        # bandwidth and footprint, letting batch scale to 128.
        max_decode_slots=int(env("TPU_BENCH_BATCH",
                                 default_batch if on_tpu else 4)),
        max_cache_len=int(env("TPU_BENCH_CACHE_LEN", 1024 if on_tpu else 128)),
        prefill_buckets=(32,),
        # Large fused horizon amortizes host->device dispatch (the chip is
        # network-attached under the bench harness, ~100 ms RTT/dispatch);
        # serving keeps the smaller default so streaming latency stays bounded.
        decode_horizon=int(env("TPU_BENCH_HORIZON", 96 if on_tpu else 4)),
        # Prefilling 32 queued prompts per dispatch keeps the burst TTFT
        # dispatch-count low (4 dispatches for the 128-slot fill): measured
        # TTFT p50 860 -> 554 ms vs 16/dispatch at identical throughput.
        max_prefill_batch=int(env("TPU_BENCH_PREFILL_BATCH",
                                  32 if on_tpu else 4)),
        kv_dtype=kv_dtype,
    )
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.bfloat16)
    engine = Engine(cfg, params, serving)
    engine.warmup()   # compile every program outside the measured windows

    # Fill every decode slot with a short prompt; never stop on eos/budget.
    n_slots = serving.max_decode_slots
    gen_budget = serving.max_cache_len - 64
    reqs = []
    for i in range(n_slots):
        reqs.append(engine.submit(
            Request(prompt_ids=[(7 * i + 3) % 1000 + 10] * 16,
                    max_tokens=gen_budget, ignore_eos=True)))
    while engine.pending:
        engine.step()
    # TTFT p50 under the burst (all programs pre-compiled by warmup).
    ttfts = sorted(r.t_first_token - r.t_submit for r in reqs)
    ttft_p50_ms = 1e3 * ttfts[len(ttfts) // 2]
    # Warm the decode program path (first decode after prefills).
    for _ in range(3):
        engine.step()

    # Timed decode window. Each step emits up to decode_horizon tokens per
    # slot, so size the window within the per-slot budget (all slots stay
    # active throughout) and count ACTUAL emitted tokens via the metrics
    # counter, not steps * slots.
    # Budget already consumed before the timed window: prefill's first token
    # plus the 3 warmup steps (3 * horizon tokens/slot). Keep one horizon of
    # slack; a too-generous slack made large horizons compute a NEGATIVE step
    # count (the r2 horizon-128 sweep failure mode).
    horizon = max(1, serving.decode_horizon)
    target_steps = min(100, max(1, (gen_budget - 4 * horizon - 8) // horizon)) \
        if on_tpu else 4
    jax.block_until_ready(engine.cache["k"])
    toks0 = engine.metrics.generated_tokens.total()
    t0 = time.monotonic()
    steps = 0
    while steps < target_steps:
        engine.step()
        steps += 1
    jax.block_until_ready(engine.cache["k"])
    dt = time.monotonic() - t0
    toks = engine.metrics.generated_tokens.total() - toks0
    assert toks > 0, "no tokens generated in timed window"
    tps = toks / dt
    out = {
        "metric": f"qwen3-0.6b decode tokens/sec/chip (batch={n_slots}, {platform})",
        "value": round(tps, 2),
        "unit": "tokens/sec",
        "vs_baseline": round(tps / L4_BASELINE_TOKS, 3),
        "platform": platform,
        "attention_impl": impl,
        "kv_dtype": serving.kv_dtype,
        "ttft_p50_ms": round(ttft_p50_ms, 2),
        "batch": n_slots,
        "decode_horizon": horizon,
        "timed_tokens": int(toks),
    }
    if on_tpu and impl != "pallas":
        out["warning"] = ("pallas kernel not selected on tpu — number measures "
                          "the XLA fallback")
    print(json.dumps(out))


if __name__ == "__main__":
    if "--measure" in sys.argv:
        measure()
    else:
        main()
