#!/usr/bin/env bash
# Fired by the session watcher the moment the TPU tunnel recovers: runs the
# prioritized round-5 sweep (VERDICT r4 next #1/#2) and commits artifacts.
# Priorities: (1) does the shipped paged path run on-chip at any batch?
# (2) int8 weights A/B (roofline lever), (3) batch/horizon ceiling pushes.
set -u
cd /root/repo
OUT=bench_sweep_r5.jsonl
: > "$OUT"
run() {
    local label="$1"; shift
    echo "=== sweep: $label ($*)" >&2
    local line
    line="$(env "$@" TPU_BENCH_CHILD_BUDGET_S=390 \
        JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_compile_cache \
        JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=1 \
        JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES=-1 \
        timeout 420 python bench.py --measure 2>"/tmp/sweep_${label}.err" \
        | grep '^{' | tail -1)"
    if [ -n "$line" ]; then
        echo "{\"sweep\": \"$label\", ${line#\{}" >> "$OUT"
    else
        echo "{\"sweep\": \"$label\", \"error\": \"no result; see stderr\", \"stderr_tail\": $(tail -c 400 "/tmp/sweep_${label}.err" | python3 -c 'import json,sys; print(json.dumps(sys.stdin.read()))')}" >> "$OUT"
    fi
    echo "--- $label done" >&2
}
run paged_carry    TPU_BENCH_PAGED=1
run bb8_b128       TPU_BENCH_PAGED=0 PALLAS_DECODE_BBLOCK=8
run bb16_b128      TPU_BENCH_PAGED=0 PALLAS_DECODE_BBLOCK=16
run paged_b64      TPU_BENCH_PAGED=1 TPU_BENCH_BATCH=64
run w8_bb8_b128    TPU_BENCH_PAGED=0 PALLAS_DECODE_BBLOCK=8 TPU_BENCH_WEIGHTS=int8
run dense_b192_bb8 TPU_BENCH_PAGED=0 TPU_BENCH_BATCH=192 PALLAS_DECODE_BBLOCK=8
run dense_h128     TPU_BENCH_PAGED=0 TPU_BENCH_BATCH=128 TPU_BENCH_HORIZON=128 PALLAS_DECODE_BBLOCK=8
run w8_b128        TPU_BENCH_PAGED=0 TPU_BENCH_WEIGHTS=int8
run paged_ps256    TPU_BENCH_PAGED=1 TPU_BENCH_PAGE_SIZE=256
run paged_b96      TPU_BENCH_PAGED=1 TPU_BENCH_BATCH=96
echo "SWEEP COMPLETE" >&2
