#!/usr/bin/env bash
# Fired by the session watcher the moment the TPU tunnel recovers: runs the
# prioritized round-6 sweep (VERDICT r5 next #1/#2/#3) and commits artifacts.
# Priorities: (1) the double-buffered paged kernel's bblock curve at the
# shipped default config (paged + int8 KV + int8 weights) — the PERF.md
# model predicts a 14.3k -> 1.8k DMA-step reduction at bb=8; (2) the
# autotuner's own pick (TPU_BENCH_BBLOCK unset => engine autotune, the
# production path); (3) bf16-weights A/B (the opt-out direction, now that
# int8 is default); (4) the TTFT prefill-lever curve.
set -u
cd /root/repo
OUT=bench_sweep_r6.jsonl
: > "$OUT"
run() {
    local label="$1"; shift
    echo "=== sweep: $label ($*)" >&2
    local line
    line="$(env "$@" TPU_BENCH_CHILD_BUDGET_S=390 \
        JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_compile_cache \
        JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=1 \
        JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES=-1 \
        timeout 420 python bench.py --measure 2>"/tmp/sweep_${label}.err" \
        | grep '^{' | tail -1)"
    if [ -n "$line" ]; then
        echo "{\"sweep\": \"$label\", ${line#\{}" >> "$OUT"
    else
        echo "{\"sweep\": \"$label\", \"error\": \"no result; see stderr\", \"stderr_tail\": $(tail -c 400 "/tmp/sweep_${label}.err" | python3 -c 'import json,sys; print(json.dumps(sys.stdin.read()))')}" >> "$OUT"
    fi
    echo "--- $label done" >&2
}
# 1) shipped default exactly as production serves it: paged, int8 weights
#    (now the config default), engine autotunes bb — THE headline candidate
run shipped_autotune TPU_BENCH_PAGED=1
# 2) the bblock curve the autotuner chooses over (pins per point)
run paged_bb1        TPU_BENCH_PAGED=1 TPU_BENCH_BBLOCK=1
run paged_bb4        TPU_BENCH_PAGED=1 TPU_BENCH_BBLOCK=4
run paged_bb8        TPU_BENCH_PAGED=1 TPU_BENCH_BBLOCK=8
# 3) weights A/B (bf16 = the explicit opt-out) + dense control at bb=8
run paged_bb8_wbf16  TPU_BENCH_PAGED=1 TPU_BENCH_BBLOCK=8 TPU_BENCH_WEIGHTS=bf16
run dense_bb8        TPU_BENCH_PAGED=0 TPU_BENCH_BBLOCK=8
# 4) capacity/geometry pushes at the winning block
run paged_bb8_b64    TPU_BENCH_PAGED=1 TPU_BENCH_BBLOCK=8 TPU_BENCH_BATCH=64
run paged_bb8_ps128  TPU_BENCH_PAGED=1 TPU_BENCH_BBLOCK=8 TPU_BENCH_PAGE_SIZE=128
# 5) TTFT prefill levers (VERDICT next #3: the 2,408 ms number -> a curve)
run ttft_pb16        TPU_BENCH_PAGED=1 TPU_BENCH_BBLOCK=8 TPU_BENCH_PREFILL_BATCH=16
run ttft_pb32_chunk  TPU_BENCH_PAGED=1 TPU_BENCH_BBLOCK=8 TPU_BENCH_PREFILL_BATCH=32 TPU_BENCH_PREFILL_CHUNK=256
echo "SWEEP COMPLETE" >&2
