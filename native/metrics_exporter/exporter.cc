/* TPU metrics exporter (native): Prometheus text endpoint for per-chip TPU
 * telemetry.
 *
 * Native parallel of the DCGM exporter role in the reference stack (Go/C++
 * component scraped on a named port, reference kubernetes-single-node.yaml:
 * 480-504 and otel-observability-setup.yaml:393-468). Output format matches
 * the Python module aws_k8s_ansible_provisioner_tpu/k8s/metrics_exporter.py
 * (same families, same labels — parity-tested) so either binary can back the
 * DaemonSet: this one is the minimal-footprint mode (no Python/JAX in the
 * container, ~100 KB binary, near-zero RSS).
 *
 * Telemetry sources (the chips belong to the ENGINE process, so telemetry
 * must cross the process boundary):
 *   1. the engine's /metrics endpoint (--engine-endpoint, default
 *      127.0.0.1:8000): per-chip tpu_hbm_* gauges pass through, and
 *      tpu_duty_cycle_percent is derived from the rate of
 *      tpu_serve_device_busy_seconds_total between successive scrapes;
 *   2. device-node enumeration (/dev/accel*) — inventory with zero gauges
 *      when no engine answers.
 *
 * Plain POSIX sockets; single-threaded accept loop (a scrape every 5s is the
 * whole load profile). Build: `make -C native exporter`.
 */

#include <arpa/inet.h>
#include <dirent.h>
#include <netdb.h>
#include <netinet/in.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <time.h>
#include <unistd.h>

#include <map>
#include <string>
#include <vector>

namespace {

// Chip index from a device node name: "accel3" -> "3", "7" -> "7", "accel"
// -> "0". Matches device_plugin._chip_index so dashboards agree on identity.
std::string ChipIndex(const std::string& name) {
  std::string digits;
  for (char c : name) {
    if (c >= '0' && c <= '9') digits.push_back(c);
  }
  return digits.empty() ? "0" : digits;
}

std::vector<std::string> DiscoverChips() {
  std::vector<std::string> chips;
  if (DIR* d = opendir("/dev")) {
    while (dirent* e = readdir(d)) {
      if (strncmp(e->d_name, "accel", 5) == 0) chips.push_back(e->d_name);
    }
    closedir(d);
  }
  if (chips.empty()) {
    if (DIR* d = opendir("/dev/vfio")) {
      while (dirent* e = readdir(d)) {
        std::string n = e->d_name;
        if (!n.empty() && n.find_first_not_of("0123456789") == std::string::npos)
          chips.push_back(n);
      }
      closedir(d);
    }
  }
  return chips;
}

// --- engine /metrics scrape (cross-process telemetry source) ---------------

// One chip's telemetry row.
struct ChipStat {
  std::string chip;
  double hbm_used = 0, hbm_capacity = 0, duty = 0, tensorcore = 0;
};

// Minimal HTTP GET: returns body or "" on any failure.
std::string HttpGet(const std::string& host, int port, const char* path,
                    int timeout_ms) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return "";
  timeval tv{timeout_ms / 1000, (timeout_ms % 1000) * 1000};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    hostent* he = gethostbyname(host.c_str());
    if (!he) { close(fd); return ""; }
    memcpy(&addr.sin_addr, he->h_addr, sizeof(addr.sin_addr));
  }
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    close(fd);
    return "";
  }
  std::string req = std::string("GET ") + path + " HTTP/1.0\r\nHost: " + host +
                    "\r\nConnection: close\r\n\r\n";
  if (write(fd, req.data(), req.size()) < 0) { close(fd); return ""; }
  std::string resp;
  char buf[4096];
  ssize_t n;
  while ((n = read(fd, buf, sizeof(buf))) > 0) resp.append(buf, n);
  close(fd);
  size_t hdr_end = resp.find("\r\n\r\n");
  return hdr_end == std::string::npos ? "" : resp.substr(hdr_end + 4);
}

// Parse `name{chip="N",...} value` SAMPLE lines for one family. Iterates
// line-by-line from each line START (a substring find() would also land
// inside `# HELP <family> ...` comment lines and fabricate a phantom
// chip="0" sample from atof of a help word — review r2 #3).
std::map<std::string, double> ParseFamily(const std::string& body,
                                          const std::string& family) {
  std::map<std::string, double> out;
  size_t start = 0;
  while (start < body.size()) {
    size_t eol = body.find('\n', start);
    size_t len = (eol == std::string::npos ? body.size() : eol) - start;
    std::string line = body.substr(start, len);
    start = (eol == std::string::npos) ? body.size() : eol + 1;
    if (line.compare(0, family.size(), family) != 0) continue;
    char next = line.size() > family.size() ? line[family.size()] : '\0';
    if (next != '{' && next != ' ') continue;  // a longer family name
    std::string chip = "0";
    size_t cpos = line.find("chip=\"");
    if (cpos != std::string::npos) {
      size_t cend = line.find('"', cpos + 6);
      if (cend != std::string::npos) chip = line.substr(cpos + 6, cend - cpos - 6);
    }
    size_t sp = line.rfind(' ');
    if (sp == std::string::npos) continue;
    out[chip] = atof(line.c_str() + sp + 1);
  }
  return out;
}

// Duty-cycle state: previous busy-seconds reading per process lifetime.
double g_prev_busy = -1;
double g_prev_t = 0;
std::string g_engine_host = "127.0.0.1";
int g_engine_port = 8000;

double MonotonicSeconds() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

// Engine-scrape source: fills chips + duty; false if no engine answered.
bool PollEngine(std::vector<ChipStat>* chips) {
  std::string body = HttpGet(g_engine_host, g_engine_port, "/metrics", 2000);
  if (body.empty()) return false;
  std::map<std::string, double> busy =
      ParseFamily(body, "tpu_serve_device_busy_seconds_total");
  if (busy.empty()) return false;
  double total = 0;
  for (auto& kv : busy) total += kv.second;
  double now = MonotonicSeconds();
  double duty = 0;
  if (g_prev_busy >= 0 && now > g_prev_t) {
    duty = 100.0 * (total - g_prev_busy) / (now - g_prev_t);
    if (duty < 0) duty = 0;
    if (duty > 100) duty = 100;
  }
  g_prev_busy = total;
  g_prev_t = now;
  std::map<std::string, double> used = ParseFamily(body, "tpu_hbm_used_bytes");
  std::map<std::string, double> cap =
      ParseFamily(body, "tpu_hbm_capacity_bytes");
  std::map<std::string, bool> ids;
  for (auto& kv : used) ids[kv.first] = true;
  for (auto& kv : cap) ids[kv.first] = true;
  if (ids.empty()) {
    for (const std::string& c : DiscoverChips()) ids[ChipIndex(c)] = true;
    if (ids.empty()) ids["0"] = true;
  }
  for (auto& kv : ids) {
    ChipStat s;
    s.chip = kv.first;
    if (used.count(kv.first)) s.hbm_used = used[kv.first];
    if (cap.count(kv.first)) s.hbm_capacity = cap[kv.first];
    s.duty = duty;
    chips->push_back(s);
  }
  return true;
}

std::string FormatG(double v) {
  char buf[64];
  snprintf(buf, sizeof(buf), "%g", v);
  return buf;
}

std::string RenderMetrics() {
  std::vector<ChipStat> chips;
  if (!PollEngine(&chips)) {
    // Device-node enumeration only: gauges 0, inventory + liveness intact.
    for (const std::string& c : DiscoverChips()) {
      ChipStat s;
      s.chip = ChipIndex(c);
      chips.push_back(s);
    }
  }
  std::string out;
  out += "# HELP tpu_exporter_up TPU metrics exporter liveness\n";
  out += "# TYPE tpu_exporter_up gauge\n";
  out += "tpu_exporter_up 1\n";
  out += "# HELP tpu_chips_total TPU chips visible on this host\n";
  out += "# TYPE tpu_chips_total gauge\n";
  out += "tpu_chips_total " + std::to_string(chips.size()) + "\n";
  struct Family {
    const char* name;
    const char* help;
    double ChipStat::*field;
  };
  const Family families[] = {
      {"tpu_hbm_used_bytes", "HBM bytes in use", &ChipStat::hbm_used},
      {"tpu_hbm_capacity_bytes", "HBM capacity in bytes",
       &ChipStat::hbm_capacity},
      {"tpu_duty_cycle_percent", "Accelerator busy percent", &ChipStat::duty},
      {"tpu_tensorcore_utilization_percent", "MXU utilization percent",
       &ChipStat::tensorcore},
  };
  for (const Family& f : families) {
    out += std::string("# HELP ") + f.name + " " + f.help + "\n";
    out += std::string("# TYPE ") + f.name + " gauge\n";
    for (const ChipStat& s : chips) {
      out += std::string(f.name) + "{chip=\"" + s.chip + "\",kind=\"tpu\"} " +
             FormatG(s.*(f.field)) + "\n";
    }
  }
  return out;
}

void Respond(int fd, const char* status, const char* ctype,
             const std::string& body) {
  std::string resp = std::string("HTTP/1.1 ") + status +
                     "\r\nContent-Type: " + ctype +
                     "\r\nContent-Length: " + std::to_string(body.size()) +
                     "\r\nConnection: close\r\n\r\n" + body;
  size_t off = 0;
  while (off < resp.size()) {
    ssize_t n = write(fd, resp.data() + off, resp.size() - off);
    if (n <= 0) break;
    off += static_cast<size_t>(n);
  }
}

}  // namespace

int main(int argc, char** argv) {
  int port = 9400;
  for (int i = 1; i + 1 < argc; i += 2) {
    if (strcmp(argv[i], "--port") == 0) port = atoi(argv[i + 1]);
    if (strcmp(argv[i], "--engine-endpoint") == 0) {
      std::string ep = argv[i + 1];
      size_t colon = ep.rfind(':');
      if (colon != std::string::npos) {
        g_engine_host = ep.substr(0, colon);
        g_engine_port = atoi(ep.c_str() + colon + 1);
      }
    }
  }
  signal(SIGPIPE, SIG_IGN);

  int srv = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(srv, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(srv, 16) != 0) {
    perror("bind/listen");
    return 1;
  }
  fprintf(stderr, "tpu-metrics-exporter (native) on :%d/metrics\n", port);

  for (;;) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) continue;
    char buf[2048];
    ssize_t n = read(fd, buf, sizeof(buf) - 1);
    if (n > 0) {
      buf[n] = '\0';
      if (strstr(buf, "GET /metrics") == buf) {
        Respond(fd, "200 OK", "text/plain; version=0.0.4", RenderMetrics());
      } else if (strstr(buf, "GET /health") == buf) {
        Respond(fd, "200 OK", "application/json", "{\"status\": \"ok\"}");
      } else {
        Respond(fd, "404 Not Found", "text/plain", "not found\n");
      }
    }
    close(fd);
  }
}
