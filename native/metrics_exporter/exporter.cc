/* TPU metrics exporter (native): Prometheus text endpoint for per-chip TPU
 * telemetry.
 *
 * Native parallel of the DCGM exporter role in the reference stack (Go/C++
 * component scraped on a named port, reference kubernetes-single-node.yaml:
 * 480-504 and otel-observability-setup.yaml:393-468). Output format is
 * byte-compatible with the Python module
 * aws_k8s_ansible_provisioner_tpu/k8s/metrics_exporter.py (same families,
 * same labels) so either binary can back the DaemonSet: this one is the
 * minimal-footprint mode (no Python/JAX in the container, ~100 KB static
 * binary, near-zero RSS), the Python one additionally reads HBM telemetry
 * through a live JAX runtime.
 *
 * Plain POSIX sockets; single-threaded accept loop (a scrape every 5s is the
 * whole load profile). Build: `make -C native exporter`.
 */

#include <arpa/inet.h>
#include <dirent.h>
#include <netinet/in.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <string>
#include <vector>

namespace {

// Chip index from a device node name: "accel3" -> "3", "7" -> "7", "accel"
// -> "0". Matches device_plugin._chip_index so dashboards agree on identity.
std::string ChipIndex(const std::string& name) {
  std::string digits;
  for (char c : name) {
    if (c >= '0' && c <= '9') digits.push_back(c);
  }
  return digits.empty() ? "0" : digits;
}

std::vector<std::string> DiscoverChips() {
  std::vector<std::string> chips;
  if (DIR* d = opendir("/dev")) {
    while (dirent* e = readdir(d)) {
      if (strncmp(e->d_name, "accel", 5) == 0) chips.push_back(e->d_name);
    }
    closedir(d);
  }
  if (chips.empty()) {
    if (DIR* d = opendir("/dev/vfio")) {
      while (dirent* e = readdir(d)) {
        std::string n = e->d_name;
        if (!n.empty() && n.find_first_not_of("0123456789") == std::string::npos)
          chips.push_back(n);
      }
      closedir(d);
    }
  }
  return chips;
}

std::string RenderMetrics() {
  std::vector<std::string> chips = DiscoverChips();
  std::string out;
  out += "# HELP tpu_exporter_up TPU metrics exporter liveness\n";
  out += "# TYPE tpu_exporter_up gauge\n";
  out += "tpu_exporter_up 1\n";
  out += "# HELP tpu_chips_total TPU chips visible on this host\n";
  out += "# TYPE tpu_chips_total gauge\n";
  out += "tpu_chips_total " + std::to_string(chips.size()) + "\n";
  struct Family { const char* name; const char* help; };
  const Family families[] = {
      {"tpu_hbm_used_bytes", "HBM bytes in use"},
      {"tpu_hbm_capacity_bytes", "HBM capacity in bytes"},
      {"tpu_duty_cycle_percent", "Accelerator busy percent"},
      {"tpu_tensorcore_utilization_percent", "MXU utilization percent"},
  };
  for (const Family& f : families) {
    out += std::string("# HELP ") + f.name + " " + f.help + "\n";
    out += std::string("# TYPE ") + f.name + " gauge\n";
    for (const std::string& chip : chips) {
      // Device-node enumeration only (runtime-independent mode): gauges are 0,
      // which keeps the scrape target and chip inventory alive; the Python
      // exporter fills real HBM numbers when it owns the runtime.
      out += std::string(f.name) + "{chip=\"" + ChipIndex(chip) +
             "\",kind=\"tpu\"} 0\n";
    }
  }
  return out;
}

void Respond(int fd, const char* status, const char* ctype,
             const std::string& body) {
  std::string resp = std::string("HTTP/1.1 ") + status +
                     "\r\nContent-Type: " + ctype +
                     "\r\nContent-Length: " + std::to_string(body.size()) +
                     "\r\nConnection: close\r\n\r\n" + body;
  size_t off = 0;
  while (off < resp.size()) {
    ssize_t n = write(fd, resp.data() + off, resp.size() - off);
    if (n <= 0) break;
    off += static_cast<size_t>(n);
  }
}

}  // namespace

int main(int argc, char** argv) {
  int port = 9400;
  for (int i = 1; i + 1 < argc; i += 2) {
    if (strcmp(argv[i], "--port") == 0) port = atoi(argv[i + 1]);
  }
  signal(SIGPIPE, SIG_IGN);

  int srv = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(srv, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(srv, 16) != 0) {
    perror("bind/listen");
    return 1;
  }
  fprintf(stderr, "tpu-metrics-exporter (native) on :%d/metrics\n", port);

  for (;;) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) continue;
    char buf[2048];
    ssize_t n = read(fd, buf, sizeof(buf) - 1);
    if (n > 0) {
      buf[n] = '\0';
      if (strstr(buf, "GET /metrics") == buf) {
        Respond(fd, "200 OK", "text/plain; version=0.0.4", RenderMetrics());
      } else if (strstr(buf, "GET /health") == buf) {
        Respond(fd, "200 OK", "application/json", "{\"status\": \"ok\"}");
      } else {
        Respond(fd, "404 Not Found", "text/plain", "not found\n");
      }
    }
    close(fd);
  }
}
