/* TPU serving runtime core: slot allocator + admission queue + page accounting.
 *
 * Native (C++) equivalent of the scheduler/allocator machinery that lives in
 * C++ inside the reference stack's external vLLM engine (SURVEY.md §2.2 row 1:
 * "continuous batching, paged KV cache"). The JAX engine keeps the compute
 * path; this library owns the host-side bookkeeping hot path:
 *   - FCFS admission queue with cancellation,
 *   - decode-slot lifecycle (acquire on prefill, release on finish),
 *   - KV page accounting for the slot-contiguous cache layout
 *     (serving/kv_cache.py): pages_per_slot = max_len / page_size, usage
 *     derived from per-slot lengths.
 *
 * Exposed as a C ABI for ctypes binding (no pybind11 in the image — see
 * aws_k8s_ansible_provisioner_tpu/runtime/native.py). Thread-safe: every call
 * takes the runtime mutex; the Python engine may submit from HTTP threads
 * while the scheduler thread pops admissions.
 */
#ifndef TPU_SERVE_RUNTIME_H_
#define TPU_SERVE_RUNTIME_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct ts_runtime ts_runtime;

typedef struct ts_stats {
  int32_t num_slots;
  int32_t active_slots;
  int32_t queue_depth;
  int64_t pages_total;
  int64_t pages_in_use;
  int64_t admitted_total;
  int64_t finished_total;
  int64_t cancelled_total;
} ts_stats;

/* Create a runtime for `num_slots` decode slots, each holding `max_len`
 * tokens of KV in pages of `page_size` tokens. Returns NULL on bad args. */
ts_runtime* ts_create(int32_t num_slots, int32_t max_len, int32_t page_size);
void ts_destroy(ts_runtime* rt);

/* Enqueue request `req_id` (caller-assigned, unique) with a `prompt_len`-token
 * prompt and a `max_tokens` generation budget. Returns 0, or -1 if the prompt
 * can never fit a slot (prompt_len + 1 > max_len). */
int32_t ts_submit(ts_runtime* rt, int64_t req_id, int32_t prompt_len,
                  int32_t max_tokens);

/* Like ts_submit but enqueues at the FRONT of the FCFS queue. Used by the
 * engine's paged-KV preemption (vLLM-style recompute): a preempted request
 * re-enters first so it is resumed as soon as pages free up, preserving
 * arrival-order fairness. */
int32_t ts_submit_front(ts_runtime* rt, int64_t req_id, int32_t prompt_len,
                        int32_t max_tokens);

/* Cancel a request: removed from the queue if still pending (returns 1);
 * marked for reap if running in a slot (returns 2); unknown id returns 0. */
int32_t ts_cancel(ts_runtime* rt, int64_t req_id);

/* Pop the next admission decision: if a request is pending and a slot is
 * free, assigns the slot (FCFS) and writes req_id/slot. Returns 1 on an
 * admission, 0 if nothing to admit. Cancelled-while-pending requests are
 * skipped and written to `cancelled_id` (one per call, check *n_cancelled). */
int32_t ts_pop_admission(ts_runtime* rt, int64_t* req_id, int32_t* slot,
                         int64_t* cancelled_id, int32_t* n_cancelled);

/* Paged-KV admission: identical to ts_pop_admission, but the head request is
 * only admitted when its worst-case prompt page need —
 * ceil((prompt_len + 1) / page_size) — fits `free_pages` (the engine's
 * allocator headroom at call time). Head-of-line blocking is deliberate
 * (FCFS fairness, the vLLM scheduler's behavior): a big head request waits
 * for pages rather than being overtaken. */
int32_t ts_pop_admission_paged(ts_runtime* rt, int64_t free_pages,
                               int64_t* req_id, int32_t* slot,
                               int64_t* cancelled_id, int32_t* n_cancelled);

/* Record prefill completion for `slot` at `length` tokens (prompt + first
 * generated token). */
void ts_note_prefill(ts_runtime* rt, int32_t slot, int32_t length);

/* Record one decode step for `slot` (length += n). */
void ts_note_decode(ts_runtime* rt, int32_t slot, int32_t n);

/* Release `slot` (request finished/cancelled). Returns the req_id that held
 * it, or -1 if the slot was already free. */
int64_t ts_release(ts_runtime* rt, int32_t slot);

/* Next slot marked cancelled-while-running, or -1. (Engine reaps these.) */
int32_t ts_next_cancelled_slot(ts_runtime* rt);

void ts_get_stats(ts_runtime* rt, ts_stats* out);

#ifdef __cplusplus
}
#endif

#endif /* TPU_SERVE_RUNTIME_H_ */
