/* Implementation of the TPU serving runtime core (see runtime.h). */

#include "runtime.h"

#include <deque>
#include <mutex>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

struct Pending {
  int64_t req_id;
  int32_t prompt_len;
  int32_t max_tokens;
};

}  // namespace

struct ts_runtime {
  std::mutex mu;
  int32_t num_slots = 0;
  int32_t max_len = 0;
  int32_t page_size = 0;

  std::deque<Pending> queue;
  std::unordered_set<int64_t> cancelled_pending;

  // Per-slot state: -1 = free, else req_id.
  std::vector<int64_t> slot_req;
  std::vector<int32_t> slot_len;
  std::vector<uint8_t> slot_cancelled;

  // Free slots in least-recently-released order. Admission pops the front,
  // release pushes the back: a freed slot is reused LAST, which maximizes
  // how long its K/V rows stay available to the engine's prefix cache
  // (lowest-free-index allocation would recycle the most useful slot first).
  std::deque<int32_t> free_slots;

  int64_t admitted_total = 0;
  int64_t finished_total = 0;
  int64_t cancelled_total = 0;
};

extern "C" {

ts_runtime* ts_create(int32_t num_slots, int32_t max_len, int32_t page_size) {
  if (num_slots <= 0 || max_len <= 0 || page_size <= 0) return nullptr;
  auto* rt = new ts_runtime();
  rt->num_slots = num_slots;
  rt->max_len = max_len;
  rt->page_size = page_size;
  rt->slot_req.assign(num_slots, -1);
  rt->slot_len.assign(num_slots, 0);
  rt->slot_cancelled.assign(num_slots, 0);
  for (int32_t s = 0; s < num_slots; ++s) rt->free_slots.push_back(s);
  return rt;
}

void ts_destroy(ts_runtime* rt) { delete rt; }

int32_t ts_submit(ts_runtime* rt, int64_t req_id, int32_t prompt_len,
                  int32_t max_tokens) {
  if (prompt_len < 0 || prompt_len + 1 > rt->max_len) return -1;
  std::lock_guard<std::mutex> lock(rt->mu);
  rt->queue.push_back(Pending{req_id, prompt_len, max_tokens});
  return 0;
}

int32_t ts_cancel(ts_runtime* rt, int64_t req_id) {
  std::lock_guard<std::mutex> lock(rt->mu);
  for (const auto& p : rt->queue) {
    if (p.req_id == req_id) {
      rt->cancelled_pending.insert(req_id);
      return 1;
    }
  }
  for (int32_t s = 0; s < rt->num_slots; ++s) {
    if (rt->slot_req[s] == req_id) {
      rt->slot_cancelled[s] = 1;
      return 2;
    }
  }
  return 0;
}

int32_t ts_submit_front(ts_runtime* rt, int64_t req_id, int32_t prompt_len,
                        int32_t max_tokens) {
  if (prompt_len < 0 || prompt_len + 1 > rt->max_len) return -1;
  std::lock_guard<std::mutex> lock(rt->mu);
  rt->queue.push_front(Pending{req_id, prompt_len, max_tokens});
  return 0;
}

int32_t ts_pop_admission_paged(ts_runtime* rt, int64_t free_pages,
                               int64_t* req_id, int32_t* slot,
                               int64_t* cancelled_id, int32_t* n_cancelled) {
  std::lock_guard<std::mutex> lock(rt->mu);
  *n_cancelled = 0;
  int32_t free_slot =
      rt->free_slots.empty() ? -1 : rt->free_slots.front();
  while (!rt->queue.empty()) {
    Pending p = rt->queue.front();
    auto it = rt->cancelled_pending.find(p.req_id);
    if (it != rt->cancelled_pending.end()) {
      // Report one cancelled-in-queue request per call so the caller can
      // notify its waiter; remaining ones surface on subsequent calls.
      rt->queue.pop_front();
      rt->cancelled_pending.erase(it);
      rt->cancelled_total += 1;
      *cancelled_id = p.req_id;
      *n_cancelled = 1;
      return 0;
    }
    if (free_slot < 0) return 0;  // queue non-empty but no capacity
    // Worst-case page need of the head prompt (+1 row for the first decoded
    // token). Head-of-line blocks until pages free up — FCFS fairness.
    const int64_t needed =
        (static_cast<int64_t>(p.prompt_len) + 1 + rt->page_size - 1) /
        rt->page_size;
    if (needed > free_pages) return 0;
    rt->queue.pop_front();
    rt->free_slots.pop_front();
    rt->slot_req[free_slot] = p.req_id;
    rt->slot_len[free_slot] = 0;
    rt->slot_cancelled[free_slot] = 0;
    rt->admitted_total += 1;
    *req_id = p.req_id;
    *slot = free_slot;
    return 1;
  }
  return 0;
}

int32_t ts_pop_admission(ts_runtime* rt, int64_t* req_id, int32_t* slot,
                         int64_t* cancelled_id, int32_t* n_cancelled) {
  // Dense (slot-contiguous) admission = paged admission with infinite pages.
  return ts_pop_admission_paged(rt, INT64_MAX, req_id, slot, cancelled_id,
                                n_cancelled);
}

void ts_note_prefill(ts_runtime* rt, int32_t slot, int32_t length) {
  std::lock_guard<std::mutex> lock(rt->mu);
  if (slot >= 0 && slot < rt->num_slots) rt->slot_len[slot] = length;
}

void ts_note_decode(ts_runtime* rt, int32_t slot, int32_t n) {
  std::lock_guard<std::mutex> lock(rt->mu);
  if (slot >= 0 && slot < rt->num_slots) {
    rt->slot_len[slot] += n;
    if (rt->slot_len[slot] > rt->max_len) rt->slot_len[slot] = rt->max_len;
  }
}

int64_t ts_release(ts_runtime* rt, int32_t slot) {
  std::lock_guard<std::mutex> lock(rt->mu);
  if (slot < 0 || slot >= rt->num_slots || rt->slot_req[slot] < 0) return -1;
  int64_t id = rt->slot_req[slot];
  rt->slot_req[slot] = -1;
  rt->slot_len[slot] = 0;
  rt->free_slots.push_back(slot);
  if (rt->slot_cancelled[slot]) rt->cancelled_total += 1; else rt->finished_total += 1;
  rt->slot_cancelled[slot] = 0;
  return id;
}

int32_t ts_next_cancelled_slot(ts_runtime* rt) {
  std::lock_guard<std::mutex> lock(rt->mu);
  for (int32_t s = 0; s < rt->num_slots; ++s) {
    if (rt->slot_req[s] >= 0 && rt->slot_cancelled[s]) return s;
  }
  return -1;
}

void ts_get_stats(ts_runtime* rt, ts_stats* out) {
  std::lock_guard<std::mutex> lock(rt->mu);
  out->num_slots = rt->num_slots;
  int32_t active = 0;
  int64_t pages_used = 0;
  const int64_t pages_per_slot =
      (rt->max_len + rt->page_size - 1) / rt->page_size;
  for (int32_t s = 0; s < rt->num_slots; ++s) {
    if (rt->slot_req[s] >= 0) {
      ++active;
      pages_used +=
          (rt->slot_len[s] + rt->page_size - 1) / rt->page_size;
    }
  }
  out->active_slots = active;
  out->queue_depth = static_cast<int32_t>(rt->queue.size());
  out->pages_total = pages_per_slot * rt->num_slots;
  out->pages_in_use = pages_used;
  out->admitted_total = rt->admitted_total;
  out->finished_total = rt->finished_total;
  out->cancelled_total = rt->cancelled_total;
}

}  // extern "C"
