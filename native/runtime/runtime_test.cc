/* C++ smoke test for the runtime core: admission FCFS, cancellation paths,
 * slot lifecycle, page accounting. Run via `make -C native test`. */

#include "runtime.h"

#include <assert.h>
#include <stdio.h>

int main() {
  ts_runtime* rt = ts_create(2, 64, 16);
  assert(rt != nullptr);
  assert(ts_create(0, 64, 16) == nullptr);

  // Oversized prompt rejected.
  assert(ts_submit(rt, 100, 64, 8) == -1);
  assert(ts_submit(rt, 1, 10, 8) == 0);
  assert(ts_submit(rt, 2, 10, 8) == 0);
  assert(ts_submit(rt, 3, 10, 8) == 0);

  int64_t rid = -1, cid = -1;
  int32_t slot = -1, ncan = 0;

  // FCFS over 2 slots: ids 1 and 2 admitted; 3 waits.
  assert(ts_pop_admission(rt, &rid, &slot, &cid, &ncan) == 1);
  assert(rid == 1 && slot == 0 && ncan == 0);
  assert(ts_pop_admission(rt, &rid, &slot, &cid, &ncan) == 1);
  assert(rid == 2 && slot == 1);
  assert(ts_pop_admission(rt, &rid, &slot, &cid, &ncan) == 0 && ncan == 0);

  ts_note_prefill(rt, 0, 11);
  ts_note_decode(rt, 0, 1);
  ts_stats st;
  ts_get_stats(rt, &st);
  assert(st.active_slots == 2 && st.queue_depth == 1);
  assert(st.pages_total == 2 * (64 / 16));
  assert(st.pages_in_use == 1 /* ceil(12/16) */);

  // Cancel the queued request: surfaced via pop, no admission.
  assert(ts_cancel(rt, 3) == 1);
  assert(ts_pop_admission(rt, &rid, &slot, &cid, &ncan) == 0);
  assert(ncan == 1 && cid == 3);

  // Cancel a running request: reaped via next_cancelled_slot + release.
  assert(ts_cancel(rt, 2) == 2);
  assert(ts_next_cancelled_slot(rt) == 1);
  assert(ts_release(rt, 1) == 2);
  assert(ts_next_cancelled_slot(rt) == -1);
  assert(ts_release(rt, 1) == -1);  // double release is a no-op

  // Freed slot is reusable.
  assert(ts_submit(rt, 4, 5, 8) == 0);
  assert(ts_pop_admission(rt, &rid, &slot, &cid, &ncan) == 1);
  assert(rid == 4 && slot == 1);

  ts_get_stats(rt, &st);
  assert(st.admitted_total == 3 && st.cancelled_total == 2);
  assert(ts_cancel(rt, 999) == 0);

  ts_destroy(rt);
  printf("runtime_test: all assertions passed\n");
  return 0;
}
