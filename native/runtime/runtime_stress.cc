/* Threaded stress test for the runtime core — the sanitizer target.
 *
 * The Python engine drives the scheduler from the HTTP threads (submit/
 * cancel) and the engine thread (pop_admission, note_prefill, note_decode,
 * release) concurrently;
 * this harness reproduces that contention pattern raw: one "engine" thread
 * admits/advances/releases while N client threads submit and cancel at
 * random. Built and run under -fsanitize=thread and
 * -fsanitize=address,undefined by `make -C native tsan asan` (the reference
 * has no compiled code and so no sanitizer story at all — SURVEY.md §5
 * "Race detection/sanitizers: none").
 *
 * Exit 0 requires: no sanitizer report, and the terminal accounting
 * invariant admitted == finished + cancelled_running holds with every slot
 * free and the queue empty.
 */

#include "runtime.h"

#include <atomic>
#include <cassert>
#include <cstdio>
#include <cstdlib>
#include <random>
#include <thread>
#include <vector>

namespace {

constexpr int kClients = 4;
constexpr int kReqsPerClient = 2000;
constexpr int kSlots = 8;
constexpr int kMaxLen = 64;

std::atomic<long> submitted{0};
std::atomic<long> cancel_calls{0};
std::atomic<bool> clients_done{false};

void client(ts_runtime* rt, int id) {
  std::mt19937 rng(id * 7919 + 17);
  for (int i = 0; i < kReqsPerClient; ++i) {
    int64_t req = static_cast<int64_t>(id) * 1000000 + i;
    int32_t prompt = 1 + static_cast<int32_t>(rng() % (kMaxLen - 2));
    if (ts_submit(rt, req, prompt, 8) == 0) submitted.fetch_add(1);
    if (rng() % 4 == 0) {  // cancel a recent request (maybe queued/running)
      ts_cancel(rt, req - static_cast<int64_t>(rng() % 3));
      cancel_calls.fetch_add(1);
    }
  }
}

}  // namespace

int main() {
  ts_runtime* rt = ts_create(kSlots, kMaxLen, 16);
  if (!rt) return 2;

  std::vector<std::thread> threads;
  threads.reserve(kClients);
  for (int c = 0; c < kClients; ++c) threads.emplace_back(client, rt, c);

  // Engine loop: admit, advance, release — concurrently with the clients.
  std::mt19937 rng(42);
  long admitted = 0, finished = 0, cancelled_q = 0;
  std::vector<int32_t> active;
  auto drain_step = [&](bool allow_idle_exit) {
    int64_t req_id = 0, cancelled_id = 0;
    int32_t slot = 0, n_cancelled = 0;
    int32_t got = ts_pop_admission(rt, &req_id, &slot, &cancelled_id,
                                   &n_cancelled);
    if (n_cancelled) { ++cancelled_q; return true; }
    if (got) {
      ++admitted;
      ts_note_prefill(rt, slot, 4);
      active.push_back(slot);
    }
    // advance + sometimes finish a random active slot
    if (!active.empty()) {
      size_t pick = rng() % active.size();
      ts_note_decode(rt, active[pick], 1);
      int32_t c = ts_next_cancelled_slot(rt);
      (void)c;  // exercised for races; release below settles it
      if (rng() % 3 == 0) {
        if (ts_release(rt, active[pick]) >= 0) ++finished;
        active.erase(active.begin() + pick);
      }
      return true;
    }
    return !allow_idle_exit;
  };
  std::thread engine([&] {
    while (!clients_done.load()) drain_step(false);
  });
  for (auto& t : threads) t.join();
  clients_done.store(true);
  engine.join();
  // drain everything left
  for (;;) {
    ts_stats st;
    ts_get_stats(rt, &st);
    if (st.queue_depth == 0 && active.empty()) break;
    drain_step(true);
  }
  while (!active.empty()) {
    if (ts_release(rt, active.back()) >= 0) ++finished;
    active.pop_back();
  }

  ts_stats st;
  ts_get_stats(rt, &st);
  bool ok = st.active_slots == 0 && st.queue_depth == 0 &&
            st.admitted_total == st.finished_total + st.cancelled_total -
                                     cancelled_q &&
            st.admitted_total == admitted &&
            submitted.load() ==
                st.admitted_total + static_cast<long>(cancelled_q);
  std::printf(
      "stress: submitted=%ld admitted=%lld finished=%lld cancelled=%lld "
      "(queue-cancelled=%ld) -> %s\n",
      submitted.load(), static_cast<long long>(st.admitted_total),
      static_cast<long long>(st.finished_total),
      static_cast<long long>(st.cancelled_total), cancelled_q,
      ok ? "OK" : "ACCOUNTING MISMATCH");
  ts_destroy(rt);
  return ok ? 0 : 1;
}
