"""Multi-host (DCN) support: process-spanning meshes + per-process data feed.

The reference's scale-out story is NCCL inside vLLM — which it never actually
configures (SURVEY.md §2.3: single L4). Here multi-host is first-class and
TPU-native: on a multi-host slice (v5e-16+) or across slices, every host runs
the SAME program, ``jax.distributed.initialize`` wires the processes together
(TPU pods auto-detect coordinator/count from the metadata server; explicit
args cover CPU rigs and tests), the mesh simply spans ``jax.devices()`` —
which after initialization enumerates ALL hosts' chips — and XLA routes
collectives over ICI within a host/slice and DCN across (the compiler knows
the topology; nothing to install or configure, deleting the reference's
implicit NCCL layer entirely).

Data feeding is the one part that is per-process: a host may only materialize
the shards its own devices own. ``device_put_global`` builds a global array
from a (deterministically generated) global numpy batch by asking the
sharding which index-slices this process's devices hold — every host computes
the same cheap synthetic/tokenized batch and materializes only its slice, so
no host ever holds the global batch on device and no host-to-host data
exchange happens at feed time.

Self-test (run one per process, any machine, no TPUs needed):

    XLA_FLAGS=--xla_force_host_platform_device_count=4 \\
    python -m aws_k8s_ansible_provisioner_tpu.parallel.multihost \\
        --coordinator localhost:9955 --num-processes 2 --process-id <i>

It builds a (dp=4, tp=2) process-spanning mesh over all 8 global devices,
runs two sharded training steps with per-process feeding, and prints the
loss — which must be identical on every process AND equal to a
single-process run on the same seed (tests/test_multihost.py asserts both).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

log = logging.getLogger("tpu_serve.multihost")


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> dict:
    """Initialize the JAX distributed runtime (idempotent).

    With no arguments on a TPU pod, coordinator/count/id auto-detect from the
    TPU metadata environment. Explicit args are for DCN rigs without metadata
    (and for multi-process CPU tests). Returns a summary dict.
    """
    # jax.distributed.is_initialized() only exists from jax 0.5; on older
    # runtimes (the pinned image ships 0.4.x) fall back to the internal
    # client handle the initialize() call populates.
    if hasattr(jax.distributed, "is_initialized"):
        initialized = jax.distributed.is_initialized()
    else:
        from jax._src import distributed as _dist

        initialized = _dist.global_state.client is not None
    if not initialized:
        if coordinator_address is None:
            jax.distributed.initialize()
        else:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id)
    info = {
        "process_id": jax.process_index(),
        "num_processes": jax.process_count(),
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
    }
    log.info("distributed: %s", info)
    return info


def device_put_global(global_np: np.ndarray, mesh, pspec: P) -> jax.Array:
    """Materialize a globally-sharded array from a host-replicated numpy batch.

    Every process passes the SAME ``global_np`` (deterministic generation is
    the contract — e.g. training/loop.synthetic_data_fn keyed on (seed,
    step)); each materializes only the slices its own devices hold, so the
    per-host device footprint is the shard, not the batch.
    """
    sharding = NamedSharding(mesh, pspec)
    return jax.make_array_from_callback(
        global_np.shape, sharding, lambda idx: global_np[idx],
        dtype=global_np.dtype)


def _selftest(args) -> None:
    import optax

    jax.config.update("jax_platforms", "cpu")
    init_distributed(args.coordinator, args.num_processes, args.process_id)

    from aws_k8s_ansible_provisioner_tpu.config import MeshConfig, tiny_qwen3
    from aws_k8s_ansible_provisioner_tpu.parallel import make_mesh
    from aws_k8s_ansible_provisioner_tpu.parallel.sharding import tokens_pspec
    from aws_k8s_ansible_provisioner_tpu.training import (init_train_state,
                                                          make_train_step)

    cfg = tiny_qwen3()
    mesh_cfg = MeshConfig(dp=args.dp, tp=args.tp)
    if mesh_cfg.num_devices != jax.device_count():
        raise ValueError(
            f"selftest mesh dp*tp={mesh_cfg.num_devices} must span ALL "
            f"{jax.device_count()} global devices — a smaller mesh would "
            f"leave some processes without addressable shards")
    # jax.devices() now spans every process — the mesh is the multi-host mesh
    mesh = make_mesh(mesh_cfg, devices=jax.devices())
    opt = optax.adamw(1e-3)
    state = init_train_state(cfg, mesh, opt, seed=args.seed)
    step = make_train_step(cfg, mesh, opt)
    # the SAME deterministic stream the training loop uses — every process
    # generates identical batches and materializes only its own shards
    from aws_k8s_ansible_provisioner_tpu.training import synthetic_data_fn
    data = synthetic_data_fn(cfg, 4 * mesh_cfg.dp, 16, args.seed)
    loss = None
    for s in range(2):
        tokens, mask = data(s)
        g_tok = device_put_global(tokens, mesh, tokens_pspec())
        g_mask = device_put_global(mask, mesh, tokens_pspec())
        state, loss = step(state, g_tok, g_mask)
    # every process prints the (replicated) loss; the test asserts equality
    print(f"MULTIHOST_SELFTEST process={jax.process_index()}/"
          f"{jax.process_count()} devices={jax.device_count()} "
          f"loss={float(loss):.6f}", flush=True)


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description="multi-host self-test")
    p.add_argument("--coordinator", default=None)
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--dp", type=int, default=4)
    p.add_argument("--tp", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    _selftest(args)


if __name__ == "__main__":
    main()
