"""Device-mesh construction for TPU slices.

The reference has no distributed backend of its own (SURVEY.md §2.3: no
NCCL/MPI/Gloo anywhere; single-GPU instance) — scaling exists only latently via
multi-replica serving. Here the communication fabric is the TPU ICI mesh driven
entirely by XLA collectives: we declare a logical ``Mesh`` with named axes and
annotate shardings; the compiler emits all_gather/reduce_scatter/ppermute over
ICI. Nothing to install, configure, or health-check — which deletes the entire
class of comms setup the reference delegates to its external CUDA stack.

Axes (see ``config.MeshConfig``):
- ``dp``: data parallel (batch / decode slots).
- ``tp``: tensor parallel (attention heads + MLP intermediate, Megatron layout).
- ``sp``: sequence/context parallel (ring attention over ICI neighbors).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from aws_k8s_ansible_provisioner_tpu.config import MeshConfig


def make_mesh(mesh_cfg: MeshConfig, devices: Optional[Sequence] = None) -> Mesh:
    """Build a (dp, sp, ep, tp) mesh over the given (or all) devices.

    Axis order puts ``tp`` innermost (and ``ep`` next) so on a real slice they
    map to ICI-adjacent chips (jax device order is ICI-topology-aware): tp
    psums every matmul and ep all-to-alls every MoE layer, while ``dp`` — the
    axis with the least communication (one gradient psum per step in training,
    none in serving) — gets the outermost, potentially-DCN hops.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = mesh_cfg.num_devices
    if len(devices) < n:
        raise ValueError(
            f"mesh {mesh_cfg} needs {n} devices, have {len(devices)}")
    # Single source of truth for axis names/order: MeshConfig.axis_names
    # (innermost = hottest collectives: tp psums every matmul, ep all-to-alls
    # every MoE layer, pp ppermutes once per pipeline tick, dp psums once per
    # step). PartitionSpecs refer to axes by name, so the order here only
    # controls the device layout.
    names = mesh_cfg.axis_names
    arr = np.asarray(devices[:n]).reshape(
        [getattr(mesh_cfg, a) for a in names])
    return Mesh(arr, names)


def auto_mesh_config(n_devices: int, want_sp: bool = True,
                     max_tp: int = 8) -> MeshConfig:
    """Factor a device count into a (dp, tp, sp) MeshConfig.

    Preference order: use tp up to ``max_tp`` (ICI-local, cheapest collectives),
    then sp if requested and divisible, remainder to dp. Used by
    ``__graft_entry__.dryrun_multichip`` and by serving auto-setup.
    """
    tp = 1
    rem = n_devices
    for cand in (8, 4, 2):
        if cand <= max_tp and rem % cand == 0:
            tp = cand
            rem //= cand
            break
    sp = 1
    if want_sp and rem % 2 == 0:
        sp = 2
        rem //= 2
    return MeshConfig(dp=rem, tp=tp, sp=sp)
