"""Ring attention: causal self-attention over a sequence-sharded axis.

Long-context support the reference entirely lacks (SURVEY.md §5 "Long-context /
sequence parallelism: absent entirely"). Sequences are sharded over the ``sp``
mesh axis; each device holds one contiguous block of the sequence. K/V blocks
rotate around the ring via ``lax.ppermute`` (XLA lowers this to ICI
neighbor-to-neighbor DMA) while every device accumulates attention for its local
queries with an **online softmax** (running max / normalizer / weighted
accumulator, flash-attention style) so the full [T, T] score matrix never
materializes and memory stays O(T_local²) per device.

Causality across blocks: query block ``b_q`` attends to key block ``b_k`` iff
``b_k <= b_q``; the diagonal block applies the in-block triangular mask. Blocks
that are fully masked still traverse the ring (the schedule is static — XLA
requires it) but contribute zeros through the masked softmax.

Communication cost: (sp-1) ppermutes of the local K/V block per layer —
bandwidth-optimal for causal attention on a ring, and overlappable with the
per-block compute by XLA's async collective scheduling.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _block_attend(q32: jnp.ndarray, k_blk: jnp.ndarray, v_blk: jnp.ndarray,
                  mask: jnp.ndarray, m: jnp.ndarray, l: jnp.ndarray,
                  acc: jnp.ndarray):
    """One online-softmax accumulation step against a single K/V block.

    q32: [B, Tq, Hq, D] float32; k_blk/v_blk: [B, Tk, Hq, D] (kv already
    head-repeated); mask: [Tq, Tk] bool; m/l: [B, Hq, Tq]; acc: [B, Hq, Tq, D].
    """
    scale = 1.0 / jnp.sqrt(jnp.asarray(q32.shape[-1], jnp.float32))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q32,
                        k_blk.astype(jnp.float32)) * scale
    logits = jnp.where(mask[None, None], logits, _NEG_INF)
    m_new = jnp.maximum(m, logits.max(axis=-1))
    alpha = jnp.exp(m - m_new)                      # correction for old acc
    p = jnp.exp(logits - m_new[..., None])          # [B, H, Tq, Tk]
    l = l * alpha + p.sum(axis=-1)
    acc = acc * alpha[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
    return m_new, l, acc


def _repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    if n_rep == 1:
        return x
    return jnp.repeat(x, n_rep, axis=-2)


def ring_attend_local(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      axis_name: str = "sp") -> jnp.ndarray:
    """Per-device body: causal ring attention over ``axis_name``.

    q: [B, Tl, Hq, D]; k/v: [B, Tl, Hkv, D] — the *local* sequence block.
    Must run inside shard_map (or any context where ``axis_name`` is bound).
    Returns the local context block [B, Tl, Hq, D].
    """
    B, Tl, Hq, D = q.shape
    Hkv = k.shape[2]
    sp = jax.lax.psum(1, axis_name)
    my_blk = jax.lax.axis_index(axis_name)

    q32 = q.astype(jnp.float32)
    k = _repeat_kv(k, Hq // Hkv)
    v = _repeat_kv(v, Hq // Hkv)

    m = jnp.full((B, Hq, Tl), _NEG_INF, jnp.float32)
    l = jnp.zeros((B, Hq, Tl), jnp.float32)
    acc = jnp.zeros((B, Hq, Tl, D), jnp.float32)

    qpos = my_blk * Tl + jnp.arange(Tl)
    perm = [(j, (j + 1) % sp) for j in range(sp)]

    def body(i, carry):
        k_blk, v_blk, m, l, acc = carry
        src_blk = (my_blk - i) % sp                  # which block we now hold
        kpos = src_blk * Tl + jnp.arange(Tl)
        mask = qpos[:, None] >= kpos[None, :]        # causal across blocks
        m, l, acc = _block_attend(q32, k_blk, v_blk, mask, m, l, acc)
        # rotate K/V to the next device (skip after the last accumulation)
        k_blk, v_blk = jax.lax.cond(
            i < sp - 1,
            lambda kv: tuple(jax.lax.ppermute(x, axis_name, perm) for x in kv),
            lambda kv: kv,
            (k_blk, v_blk),
        )
        return k_blk, v_blk, m, l, acc

    _, _, m, l, acc = jax.lax.fori_loop(0, sp, body, (k, v, m, l, acc))
    # Every query row has attended at least its own diagonal block ⇒ l >= 1.
    out = acc / l[..., None]                         # [B, H, Tq, D]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def make_ring_attend(mesh: Mesh, axis_name: str = "sp"):
    """AttendFn (models/layers) running ring attention over ``mesh``'s sp axis.

    q/k/v arrive as *global* arrays inside jit; shard_map partitions them
    batch→dp, sequence→sp, heads→tp and binds the sp axis for the ring. The
    cache is passed through untouched (training / full-sequence path).
    """

    # jax.shard_map(check_vma=...) is the >= 0.6 API; the pinned 0.4.x image
    # only has jax.experimental.shard_map (check_rep). Same semantics here:
    # both flags just disable the replication/varying-manual-axes check.
    if hasattr(jax, "shard_map"):
        local = jax.shard_map(
            lambda q, k, v: ring_attend_local(q, k, v, axis_name),
            mesh=mesh,
            in_specs=(P("dp", axis_name, "tp", None),) * 3,
            out_specs=P("dp", axis_name, "tp", None),
            check_vma=False,
        )
    else:
        from jax.experimental.shard_map import shard_map as _shard_map

        local = _shard_map(
            lambda q, k, v: ring_attend_local(q, k, v, axis_name),
            mesh=mesh,
            in_specs=(P("dp", axis_name, "tp", None),) * 3,
            out_specs=P("dp", axis_name, "tp", None),
            check_rep=False,
        )

    def attend(q, k, v, cache) -> Tuple[jnp.ndarray, object]:
        return local(q, k, v), cache

    return attend
