"""Sharding rules: how every parameter, activation, and cache leaf is laid out.

Megatron-style tensor parallelism expressed as ``PartitionSpec``s over the
(dp, sp, tp) mesh (parallel/mesh.py). XLA's GSPMD propagates these through the
whole program and inserts the ICI collectives — this module is the *entire*
distributed "backend" (SURVEY.md §2.3: the reference has none; §5: "no
NCCL/MPI/Gloo/UCX"; the TPU equivalent is compiler-emitted collectives).

Layout summary (weights are ``[in, out]``, layers stacked on a leading L axis):

- attention q/k/v projections: column-parallel — heads sharded over ``tp``;
  output projection ``wo``: row-parallel (partial sums psum'd by XLA).
- MLP up/gate: column-parallel on the intermediate dim; down: row-parallel.
- embedding table: vocab-sharded over ``tp`` (tied logits come out
  vocab-sharded, exactly what the loss wants); untied ``lm_head``: vocab-
  sharded on the output dim.
- norms and per-head q/k norms: replicated (tiny).
- token/position arrays: batch over ``dp``, sequence over ``sp``.
- decode KV cache ``[L, slots, Hkv, S, D]``: kv heads over ``tp``, slots over
  ``dp`` (each data-parallel group owns its slots).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from aws_k8s_ansible_provisioner_tpu.config import ModelConfig


def check_tp_divisibility(cfg: ModelConfig, tp: int, ep: int = 1) -> None:
    """TP must evenly split query heads, kv heads, and the MLP intermediate
    (the MoE expert intermediate when sparse); ep must split the experts."""
    dims = [("num_heads", cfg.num_heads),
            ("num_kv_heads", cfg.num_kv_heads),
            ("vocab_size", cfg.vocab_size)]
    if cfg.num_experts > 0:
        dims.append(("moe_intermediate_size", cfg.moe_intermediate_size))
    else:
        dims.append(("intermediate_size", cfg.intermediate_size))
    for name, dim in dims:
        if dim % tp != 0:
            raise ValueError(f"tp={tp} does not divide {name}={dim} "
                             f"for model {cfg.name}")
    if ep > 1 and cfg.num_experts % ep != 0:
        raise ValueError(f"ep={ep} does not divide num_experts="
                         f"{cfg.num_experts} for model {cfg.name}")


def _layer_pspecs(cfg: ModelConfig, quant_weights: bool = False) -> dict:
    """PartitionSpecs mirroring models/layers.init_layer_params structure.

    ``quant_weights`` adds the int8 scheme's per-out-channel ``scale`` leaves
    (models/quant.py): a scale shards exactly like its kernel's OUT axis —
    column-parallel kernels carry tp-sharded scales, row-parallel kernels
    replicated ones (their out axis is replicated)."""

    def col(bias: bool) -> dict:  # [L, in, out] — shard out
        p = {"kernel": P(None, None, "tp")}
        if bias:
            p["bias"] = P(None, "tp")
        if quant_weights:
            p["scale"] = P(None, "tp")      # [L, out]
        return p

    def row(bias: bool) -> dict:  # [L, in, out] — shard in, replicate out
        p = {"kernel": P(None, "tp", None)}
        if bias:
            p["bias"] = P(None, None)
        if quant_weights:
            p["scale"] = P(None, None)      # [L, out] (out replicated)
        return p

    def norm() -> dict:
        p = {"weight": P(None, None)}
        if cfg.norm == "layernorm":
            p["bias"] = P(None, None)
        return p

    specs = {
        "input_norm": norm(),
        "wq": col(cfg.attention_bias),
        "wk": col(cfg.attention_bias),
        "wv": col(cfg.attention_bias),
        "wo": row(cfg.attention_bias),
    }
    if cfg.qk_norm:
        specs["q_norm"] = {"weight": P(None, None)}
        specs["k_norm"] = {"weight": P(None, None)}
    if cfg.num_experts > 0:
        # MoE: experts sharded over ep, each expert Megatron-split over tp
        # (gate/up column-parallel on the expert intermediate, down row-
        # parallel); the tiny router replicates. GSPMD derives the gshard
        # dispatch collectives from these specs (ops/moe.py). Quantized
        # expert scales [L, E, out] shard with their kernel's expert + out
        # axes (gate/up out = tp-sharded intermediate; down out = replicated
        # hidden).
        specs["router"] = {"kernel": P(None, None, None)}
        specs["w_gate"] = {"kernel": P(None, "ep", None, "tp")}
        specs["w_up"] = {"kernel": P(None, "ep", None, "tp")}
        specs["w_down"] = {"kernel": P(None, "ep", "tp", None)}
        if quant_weights:
            specs["w_gate"]["scale"] = P(None, "ep", "tp")
            specs["w_up"]["scale"] = P(None, "ep", "tp")
            specs["w_down"]["scale"] = P(None, "ep", None)
    else:
        if cfg.gated_mlp:
            specs["w_gate"] = col(cfg.mlp_bias)
        specs["w_up"] = col(cfg.mlp_bias)
        specs["w_down"] = row(cfg.mlp_bias)
    if not cfg.parallel_block:
        specs["post_norm"] = norm()
    return specs


def param_pspecs(cfg: ModelConfig, quant_weights: bool = False) -> dict:
    """Full-parameter PartitionSpec pytree (same structure as init_params;
    with ``quant_weights`` the structure of models/quant.quantize_params,
    including MoE expert scales)."""
    specs: dict = {
        "embed": {"weight": P("tp", None)},  # vocab-sharded
        "layers": _layer_pspecs(cfg, quant_weights=quant_weights),
        "final_norm": {"weight": P(None)},
    }
    if quant_weights:
        specs["embed"]["scale"] = P("tp")    # [V] per-vocab-row
    if cfg.pos_embed == "learned":
        # OPT position table: tiny, replicate.
        specs["pos_embed"] = {"weight": P(None, None)}
    if cfg.norm == "layernorm":
        specs["final_norm"]["bias"] = P(None)
    if not cfg.tie_embeddings:
        specs["lm_head"] = {"kernel": P(None, "tp")}
        if cfg.parallel_block:
            specs["lm_head"]["bias"] = P("tp")
        if quant_weights:
            specs["lm_head"]["scale"] = P("tp")   # [V]
    return specs


def cache_pspecs(quant: bool = False) -> dict:
    """Decode cache [L, slots, Hkv, S, D]: slots over dp, kv heads over tp,
    sequence over sp (no-op on meshes with a size-1 sp axis; with sp > 1 the
    cache window scales with the sp group's aggregate HBM — the long-context
    serving axis). With ``quant`` the int8 cache's per-row scale leaves
    ``ks``/``vs`` [L, slots, Hkv, S] shard identically (minus the head_dim
    axis)."""
    specs = {
        "k": P(None, "dp", "tp", "sp", None),
        "v": P(None, "dp", "tp", "sp", None),
    }
    if quant:
        specs["ks"] = P(None, "dp", "tp", "sp")
        specs["vs"] = P(None, "dp", "tp", "sp")
    return specs


def pool_pspecs(quant: bool = False) -> dict:
    """Paged KV pool [L, pages, Hkv, page, D]: PAGES over dp, kv heads over
    tp. Page identity is head-independent, so block tables, lengths, and the
    host allocators are tp-shard-invariant — each tp chip holds its heads'
    slice of every page. The dp axis partitions the page POOL itself: slots
    are dp-sharded, each dp group owns one page-axis partition with its own
    host allocator, and a slot's table only ever references its group's
    partition (Engine writes GLOBAL ids = local + group * partition; the
    shard_map kernels subtract their partition base). On dp=1 meshes the dp
    axis has size 1 and this degenerates to the tp-only layout. Only sp
    keeps the dense cache (a page is a contiguous row run — splitting it
    across sequence shards defeats paging)."""
    specs = {
        "k": P(None, "dp", "tp", None, None),
        "v": P(None, "dp", "tp", None, None),
    }
    if quant:
        specs["ks"] = P(None, "dp", "tp", None)
        specs["vs"] = P(None, "dp", "tp", None)
    return specs


def tokens_pspec(seq_sharded: bool = False) -> P:
    """[B, T] activations: batch over dp, optionally sequence over sp."""
    return P("dp", "sp" if seq_sharded else None)


def param_shardings(mesh: Mesh, cfg: ModelConfig,
                    quant_weights: bool = False) -> Any:
    return jax.tree.map(lambda s: NamedSharding(mesh, s),
                        param_pspecs(cfg, quant_weights=quant_weights),
                        is_leaf=lambda x: isinstance(x, P))


def shard_params(params: Any, mesh: Mesh, cfg: ModelConfig) -> Any:
    """Place an (unsharded or host) param pytree onto the mesh per the rules.
    Detects int8-quantized trees (models/quant.py) and picks the matching
    spec structure."""
    from aws_k8s_ansible_provisioner_tpu.models.quant import weights_quantized

    shardings = param_shardings(mesh, cfg,
                                quant_weights=weights_quantized(params))
    return jax.tree.map(jax.device_put, params, shardings)


def make_sharded_device_put(mesh: Mesh, cfg: ModelConfig):
    """Per-leaf placement callback for ``hf_loader.load_checkpoint``.

    Maps each pytree path to its PartitionSpec and device_puts the leaf with
    that NamedSharding as it is converted: the host→device transfer per device
    is the SHARD, and no device ever holds a full-model buffer — the property
    that lets an 8B checkpoint load onto a v5e-8 slice whose chips each hold
    1/8 of the weights (SURVEY.md §7 hard part #3).
    """
    flat, _ = jax.tree_util.tree_flatten_with_path(
        param_pspecs(cfg), is_leaf=lambda x: isinstance(x, P))
    specs = {jax.tree_util.keystr(path): s for path, s in flat}

    def put(path: str, arr):
        spec = specs.get(path)
        if spec is None:  # unexpected leaf: replicate (never silently drop)
            spec = P()
        return jax.device_put(arr, NamedSharding(mesh, spec))

    return put
