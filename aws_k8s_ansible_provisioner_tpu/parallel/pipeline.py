"""Pipeline parallelism: GPipe-style microbatched layer stages over a pp axis.

The reference has no parallelism of any kind in-repo (SURVEY.md §2.3) — pp is
net-new capability, TPU-first: stages are a mesh axis, the inter-stage hop is
a single ``lax.ppermute`` over ICI neighbors per pipeline tick, and the whole
schedule is one ``lax.scan`` inside ``shard_map`` — XLA sees a static loop of
(stage compute, neighbor permute) and overlaps the DMA with compute. No
microbatch queues, no send/recv runtime, no NCCL groups: the schedule IS the
program.

Design:
- The stacked layer params ``[L, ...]`` reshape to ``[PP, L/PP, ...]`` and
  shard ``P("pp", ...)`` — each device holds one stage's contiguous layer
  block (`to_pipeline_params`). Embedding/final-norm/head replicate (small
  next to the layer stack).
- GPipe schedule: M microbatches flow through PP stages in M + PP - 1 ticks.
  Stage 0 ingests microbatch t at tick t; the last stage computes the
  masked-CE partial sums for microbatch t - (PP-1) at tick t. Bubble ticks
  compute on zeros and are masked out of the loss — SPMD requires uniform
  compute, so the bubble costs time, not correctness (bubble fraction
  (PP-1)/(M+PP-1): pick M >= 4*PP in practice).
- Loss accumulates as (masked nll sum, mask count) pairs and divides once at
  the end, then psums over pp (only the last stage holds nonzero partials)
  and dp — so the result equals the NON-pipelined ``trainer.lm_loss`` on the
  same batch exactly, which is what the parity tests assert.
- Backward: ``shard_map``/``ppermute``/``scan`` are all differentiable (the
  transpose of a ppermute is the reverse ppermute — backward activations hop
  stage s → s-1 exactly like GPipe's backward phase). ``jax.checkpoint`` on
  the stage body gives the standard remat-per-stage memory profile.

Composition: pp × dp in one mesh (batch microbatches shard over dp). tp/sp
compose with dp/ep via GSPMD in the non-pipelined path (trainer.py); stacking
them inside the pp shard_map would need hand-written collectives per matmul
and is out of scope — at v5e-8 scale, tp×dp covers the model sizes this repo
ships, and pp exists for the depth-bound regime beyond them.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from aws_k8s_ansible_provisioner_tpu.config import ModelConfig
from aws_k8s_ansible_provisioner_tpu.models.layers import (
    _embed_inputs,
    _final_logits,
    decoder_block,
    make_default_attend,
)


def check_pp_divisibility(cfg: ModelConfig, pp: int) -> None:
    if cfg.num_layers % pp != 0:
        raise ValueError(f"pp={pp} does not divide num_layers="
                         f"{cfg.num_layers} for model {cfg.name}")


def to_pipeline_params(params: Any, pp: int) -> Any:
    """Reshape stacked layer leaves [L, ...] → [PP, L/PP, ...] (stage-major)."""
    out = dict(params)
    out["layers"] = jax.tree.map(
        lambda x: x.reshape((pp, x.shape[0] // pp) + x.shape[1:]),
        params["layers"])
    return out


def from_pipeline_params(params: Any) -> Any:
    """Inverse of to_pipeline_params (for checkpoint export / parity tests)."""
    out = dict(params)
    out["layers"] = jax.tree.map(
        lambda x: x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:]),
        params["layers"])
    return out


def pipeline_param_pspecs(cfg: ModelConfig, params: Any) -> Any:
    """Layer leaves shard on the stage axis; everything else replicates."""
    specs = jax.tree.map(lambda _: P(), params)
    specs["layers"] = jax.tree.map(
        lambda x: P("pp", *([None] * (x.ndim - 1))), params["layers"])
    return specs


def make_pipeline_lm_loss(cfg: ModelConfig, mesh: Mesh, n_microbatches: int,
                          remat: bool = True) -> Callable:
    """Build ``loss(params, tokens, loss_mask) -> scalar`` pipelined over the
    mesh's ``pp`` axis (and data-parallel over ``dp`` when present).

    ``params`` must be in pipeline form (to_pipeline_params); tokens/loss_mask
    are the full [B, T] batch — B must split into n_microbatches (times dp).
    """
    M = n_microbatches
    has_dp = "dp" in mesh.axis_names

    # honors cfg.sliding_window — the pipelined loss must match
    # model_forward's mask exactly (the parity tests' whole point)
    attend = make_default_attend(cfg)

    def stage_fwd(p_stage, x, cos, sin):
        """Run this device's layer block over activation x [mb, T, H]."""
        def body(x, p_l):
            x, _ = decoder_block(cfg, p_l, x, cos, sin, attend, None)
            return x, None
        if remat:
            body = jax.checkpoint(body)
        x, _ = jax.lax.scan(body, x, p_stage)
        return x

    def shard_body(params, tokens, loss_mask):
        # tokens: [M, mb, T] (this dp shard's microbatches)
        pp_idx = jax.lax.axis_index("pp")
        # static stage count from the mesh (jax.lax.axis_size only exists on
        # newer jax than the pinned 0.4.x image; PP feeds range()/arange(), so
        # it must be a Python int anyway)
        PP = int(mesh.shape["pp"])
        p_stage = jax.tree.map(lambda x: x[0], params["layers"])  # [Lpp, ...]
        _, mb, T = tokens.shape
        H = cfg.hidden_size

        def tick(carry, t):
            x_in, nll_sum, cnt_sum = carry
            mb_t = jnp.clip(t, 0, M - 1)
            toks_t = tokens[mb_t]                               # [mb, T]
            positions = jnp.broadcast_to(
                jnp.arange(T, dtype=jnp.int32)[None], (mb, T))
            x0, cos, sin = _embed_inputs(params, cfg, toks_t, positions)
            # stage 0 ingests microbatch t; later stages take the permuted
            # activation from their left neighbor (zeros during fill bubbles)
            x = jnp.where(pp_idx == 0, x0.astype(jnp.float32),
                          x_in).astype(x0.dtype)
            y = stage_fwd(p_stage, x, cos, sin)
            # last stage: masked-CE partials for microbatch t - (PP-1).
            # lax.cond, not a mask: the [H, V] head matmul is often the
            # largest matmul per tick and SPMD stages CAN branch on their
            # own axis index — only the last stage pays for it.
            out_mb = t - (PP - 1)
            tgt_toks = tokens[jnp.clip(out_mb, 0, M - 1)]
            tgt_mask = loss_mask[jnp.clip(out_mb, 0, M - 1)]

            def ce_partials(y):
                logits = _final_logits(params, cfg, y).astype(jnp.float32)
                logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
                nll = -jnp.take_along_axis(
                    logp, tgt_toks[:, 1:][..., None], axis=-1)[..., 0]
                m = tgt_mask[:, 1:].astype(jnp.float32)
                return (nll * m).sum(), m.sum()

            valid = (pp_idx == PP - 1) & (out_mb >= 0)
            d_nll, d_cnt = jax.lax.cond(
                valid, ce_partials,
                lambda y: (jnp.float32(0.0), jnp.float32(0.0)), y)
            nll_sum = nll_sum + d_nll
            cnt_sum = cnt_sum + d_cnt
            # hand the activation to the right neighbor for the next tick
            y_next = jax.lax.ppermute(
                y.astype(jnp.float32), "pp",
                [(i, (i + 1) % PP) for i in range(PP)])
            return (y_next, nll_sum, cnt_sum), None

        init = (jnp.zeros((mb, T, H), jnp.float32), jnp.float32(0.0),
                jnp.float32(0.0))
        (_, nll_sum, cnt_sum), _ = jax.lax.scan(
            tick, init, jnp.arange(M + PP - 1))
        # only the last stage holds partials; dp shards hold their slice
        nll_sum = jax.lax.psum(nll_sum, "pp")
        cnt_sum = jax.lax.psum(cnt_sum, "pp")
        if has_dp:
            nll_sum = jax.lax.psum(nll_sum, "dp")
            cnt_sum = jax.lax.psum(cnt_sum, "dp")
        return nll_sum / jnp.maximum(cnt_sum, 1.0)

    def loss(params, tokens, loss_mask):
        B, T = tokens.shape
        dp = mesh.shape.get("dp", 1)
        pp = mesh.shape["pp"]
        stage_dim = jax.tree.leaves(params["layers"])[0].shape[0]
        if stage_dim != pp:
            # A mismatch would silently shard stage_dim over pp devices and
            # shard_body's x[0] would DROP layers — wrong loss, no error.
            raise ValueError(f"params are staged for pp={stage_dim} but the "
                             f"mesh has pp={pp} (to_pipeline_params mismatch)")
        if B % (M * dp):
            raise ValueError(f"batch {B} must split into {M} microbatches "
                             f"x dp={dp}")
        mb = B // M
        tokens_m = tokens.reshape(M, mb, T)
        mask_m = loss_mask.reshape(M, mb, T)
        specs = pipeline_param_pspecs(cfg, params)
        data_spec = P(None, "dp", None) if has_dp else P(None, None, None)
        fn = shard_map(
            shard_body, mesh=mesh,
            in_specs=(specs, data_spec, data_spec),
            out_specs=P(),
            check_rep=False)
        return fn(params, tokens_m, mask_m)

    return loss


def init_pipeline_params(cfg: ModelConfig, mesh: Mesh, pp: int,
                         seed: int = 0, dtype=jnp.float32) -> Any:
    """Init params directly in pipeline form, stage-sharded over the mesh."""
    from aws_k8s_ansible_provisioner_tpu.models.layers import init_params

    check_pp_divisibility(cfg, pp)

    def build():
        return to_pipeline_params(init_params(cfg, jax.random.PRNGKey(seed),
                                              dtype), pp)

    shapes = jax.eval_shape(build)
    specs = pipeline_param_pspecs(cfg, shapes)
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda x: isinstance(x, P))
    return jax.jit(build, out_shardings=shardings)()


def make_pipeline_train_step(cfg: ModelConfig, mesh: Mesh, optimizer,
                             n_microbatches: int, remat: bool = True):
    """(params, opt_state, tokens, mask) -> (params, opt_state, loss), jitted
    with donated state. Params in pipeline form (init_pipeline_params)."""
    loss_fn = make_pipeline_lm_loss(cfg, mesh, n_microbatches, remat)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens, loss_mask
             ) -> Tuple[Any, Any, jnp.ndarray]:
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, loss_mask)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step
