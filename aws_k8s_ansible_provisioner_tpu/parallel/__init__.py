"""Parallelism: device mesh, sharding rules, ring attention, pipeline stages.

The reference implements no parallelism of its own (SURVEY.md §2.3); everything
here is net-new TPU-first design: XLA-collective backend over ICI, Megatron TP
via PartitionSpecs, ring attention for sequence/context parallelism, expert
parallelism for MoE (ops/moe.py + sharding specs), and GPipe-style pipeline
stages over ppermute.
"""

from aws_k8s_ansible_provisioner_tpu.parallel.mesh import (  # noqa: F401
    auto_mesh_config,
    make_mesh,
)
from aws_k8s_ansible_provisioner_tpu.parallel.pipeline import (  # noqa: F401
    check_pp_divisibility,
    from_pipeline_params,
    init_pipeline_params,
    make_pipeline_lm_loss,
    make_pipeline_train_step,
    to_pipeline_params,
)
from aws_k8s_ansible_provisioner_tpu.parallel.ring_attention import (  # noqa: F401
    make_ring_attend,
    ring_attend_local,
)
from aws_k8s_ansible_provisioner_tpu.parallel.sharding import (  # noqa: F401
    cache_pspecs,
    check_tp_divisibility,
    param_pspecs,
    param_shardings,
    shard_params,
    tokens_pspec,
)
