"""Minimal Prometheus metrics registry (text exposition format, no deps).

The scrape contract comes from the reference's observability layer: the OTEL
collector discovers pods by annotation and scrapes ``/metrics`` on port 8000
(``otel-observability-setup.yaml:337-391``), and its printed PromQL cookbook
queries ``vllm_request_total``-style counters and duration histogram buckets
(``:754-761``). We emit the same *shapes* under the ``tpu_serve_`` prefix plus
vllm-compatible aliases so the unchanged dashboards/cookbook keep working
(SURVEY.md §7 capability contract item 6).

Both exposition formats are supported from the same registries: classic
Prometheus text (``text/plain; version=0.0.4``, the default) and OpenMetrics
(``application/openmetrics-text``) when the scraper's Accept header asks for
it. OpenMetrics mode adds exemplars to histogram *bucket* lines only — the
``# {trace_id="..."} v`` tail that lets Grafana jump from a burning latency
bucket straight to the Tempo trace (and from there to the flight dump). The
route handler appends the single ``# EOF`` terminator after concatenating
every registry; ``render()`` never writes it so registries stay composable.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

_LabelKey = Tuple[Tuple[str, str], ...]


class Counter:
    def __init__(self, name: str, help_: str, labelnames: Sequence[str] = ()):
        self.name, self.help = name, help_
        self.labelnames = tuple(labelnames)
        self._values: Dict[_LabelKey, float] = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels):
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def total(self) -> float:
        """Sum over all label combinations (bench/test introspection)."""
        with self._lock:
            return sum(self._values.values())

    def value(self, **labels) -> float:
        """One label combination's count (/healthz tier splits, tests)."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def collect(self, openmetrics: bool = False) -> List[str]:
        # OpenMetrics names the counter FAMILY without the _total suffix
        # (samples keep it); classic text uses the full name everywhere.
        fam = self.name
        if openmetrics and fam.endswith("_total"):
            fam = fam[:-len("_total")]
        out = [f"# HELP {fam} {self.help}", f"# TYPE {fam} counter"]
        for key, val in sorted(self._values.items()):
            out.append(f"{self.name}{_fmt_labels(key)} {val}")
        if not self._values:
            out.append(f"{self.name} 0")
        return out


class Gauge:
    """Gauge, optionally labeled (e.g. tpu_serve_slo_burn_rate{objective,
    window}). The unlabeled form keeps the original single-value behavior:
    it always renders exactly one sample, 0.0 until the first set()."""

    def __init__(self, name: str, help_: str, labelnames: Sequence[str] = ()):
        self.name, self.help = name, help_
        self.labelnames = tuple(labelnames)
        self._values: Dict[_LabelKey, float] = {}
        self._lock = threading.Lock()

    def set(self, v: float, **labels):
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = float(v)

    def add(self, v: float, **labels):
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + v

    def value(self, **labels) -> float:
        """Current value (admission-control wait estimation, tests)."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def collect(self, openmetrics: bool = False) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} gauge"]
        with self._lock:
            for key, val in sorted(self._values.items()):
                out.append(f"{self.name}{_fmt_labels(key)} {val}")
            if not self._values:
                out.append(f"{self.name} 0.0")
        return out


class Histogram:
    """Prometheus histogram with explicit buckets (for request/TTFT latency)."""

    DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                       10.0, 30.0, 60.0)

    def __init__(self, name: str, help_: str,
                 buckets: Optional[Sequence[float]] = None):
        self.name, self.help = name, help_
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self._counts = [0] * (len(self.buckets) + 1)
        # last exemplar per bucket (incl +Inf): (trace_id, observed value).
        # One slot per bucket — "most recent wins", the standard client
        # behavior; rendered only in OpenMetrics mode, on bucket lines only.
        self._exemplars: List[Optional[Tuple[str, float]]] = \
            [None] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._total = 0
        self._lock = threading.Lock()

    def observe(self, v: float, trace_id: Optional[str] = None):
        with self._lock:
            self._sum += v
            self._total += 1
            placed = False
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    if trace_id and not placed:
                        # exemplar lives on the LOWEST bucket containing
                        # the observation (where it "falls")
                        self._exemplars[i] = (str(trace_id), v)
                        placed = True
            self._counts[-1] += 1  # +Inf
            if trace_id and not placed:
                self._exemplars[-1] = (str(trace_id), v)

    def _exemplar_tail(self, i: int, openmetrics: bool) -> str:
        ex = self._exemplars[i]
        if not openmetrics or ex is None:
            return ""
        tid, v = ex
        return f' # {{trace_id="{_escape_label_value(tid)}"}} {v}'

    def collect(self, openmetrics: bool = False) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        for i, b in enumerate(self.buckets):
            out.append(f'{self.name}_bucket{{le="{b}"}} {self._counts[i]}'
                       + self._exemplar_tail(i, openmetrics))
        out.append(f'{self.name}_bucket{{le="+Inf"}} {self._counts[-1]}'
                   + self._exemplar_tail(len(self.buckets), openmetrics))
        out.append(f"{self.name}_sum {self._sum}")
        out.append(f"{self.name}_count {self._total}")
        return out


def _escape_label_value(v) -> str:
    """Exposition-format label-value escaping (shared by both formats):
    backslash, double-quote, and line-feed must be escaped or a crafted
    value (a model name, a trace id) corrupts the whole scrape."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(key: _LabelKey) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in key)
    return "{" + inner + "}"


class Registry:
    def __init__(self):
        self._metrics: List = []
        self._lock = threading.Lock()

    def register(self, m):
        with self._lock:
            self._metrics.append(m)
        return m

    def render(self, openmetrics: bool = False) -> str:
        lines: List[str] = []
        with self._lock:
            for m in self._metrics:
                lines.extend(m.collect(openmetrics))
        return "\n".join(lines) + "\n"


class EngineMetrics:
    """The engine's metric set; names mirror the vLLM ones the reference scrapes."""

    def __init__(self):
        self.registry = Registry()
        r = self.registry
        self.request_total = r.register(Counter(
            "tpu_serve_request_total", "Total requests", ("status",)))
        # vllm-compatible alias so the reference's PromQL cookbook
        # (otel-observability-setup.yaml:758-761) works unchanged.
        self.vllm_request_total = r.register(Counter(
            "vllm_request_total", "Total requests (vllm-compatible alias)",
            ("status",)))
        self.active_requests = r.register(Gauge(
            "tpu_serve_active_requests", "Requests currently in decode slots"))
        self.queue_depth = r.register(Gauge(
            "tpu_serve_queue_depth", "Requests waiting for a slot"))
        self.generated_tokens = r.register(Counter(
            "tpu_serve_generated_tokens_total", "Generated tokens"))
        self.prompt_tokens = r.register(Counter(
            "tpu_serve_prompt_tokens_total", "Prompt tokens prefilled"))
        self.request_duration = r.register(Histogram(
            "tpu_serve_request_duration_seconds", "End-to-end request latency"))
        self.vllm_request_duration = r.register(Histogram(
            "vllm_request_duration_seconds",
            "End-to-end request latency (vllm-compatible alias)"))
        self.ttft = r.register(Histogram(
            "tpu_serve_time_to_first_token_seconds", "Time to first token"))
        self.decode_step_duration = r.register(Histogram(
            "tpu_serve_decode_step_seconds",
            "Per-token decode DEVICE time over all slots (device window / "
            "horizon; wall time includes pipeline overlap and host bubble)",
            buckets=(.001, .0025, .005, .01, .025, .05, .1, .25, .5, 1., 2.5)))
        self.tokens_per_second = r.register(Gauge(
            "tpu_serve_tokens_per_second", "Recent decode throughput"))
        # Decode pipeline (perf_opt r9): bubble = device idle between a
        # dispatch completing with nothing enqueued behind it and the next
        # enqueue (host emit/SSE/scheduling time). Synchronous mode pays it
        # every dispatch; the one-deep pipeline hides it behind device
        # compute, so bubble-rate ~0 is the success signal.
        self.decode_bubble_seconds = r.register(Counter(
            "tpu_serve_decode_bubble_seconds_total",
            "Device idle seconds between decode dispatches (host bubble)"))
        self.pipeline_depth = r.register(Gauge(
            "tpu_serve_pipeline_depth",
            "Decode dispatches currently in flight past the fetched one "
            "(1 = pipelined steady state, 0 = synchronous/drained)"))
        # Wall time spent inside device dispatches (prefill + decode). The
        # node metrics exporter scrapes this across the process boundary and
        # derives tpu_duty_cycle_percent from its rate — the engine process
        # owns the chips, so only it can measure busy time (VERDICT r1
        # missing #5: the exporter published constant zeros in production).
        self.device_busy_seconds = r.register(Counter(
            "tpu_serve_device_busy_seconds_total",
            "Seconds spent in device dispatches (duty-cycle source)"))
        self.prefix_cache_hits = r.register(Counter(
            "tpu_serve_prefix_cache_hits_total",
            "Requests that reused a cached prompt prefix"))
        self.prefix_tokens_reused = r.register(Counter(
            "tpu_serve_prefix_tokens_reused_total",
            "Prompt tokens served from the prefix cache instead of prefill"))
        self.spec_drafted_tokens = r.register(Counter(
            "tpu_serve_spec_drafted_tokens_total",
            "Draft tokens proposed (prompt-lookup or draft-model)"))
        self.spec_accepted_tokens = r.register(Counter(
            "tpu_serve_spec_accepted_tokens_total",
            "Draft tokens accepted by the verify pass"))
        self.spec_acceptance_rate = r.register(Gauge(
            "tpu_serve_spec_acceptance_rate",
            "Cumulative accepted/drafted ratio of speculative decoding"))
        # Paged-KV pool health (vLLM publishes the same trio as
        # vllm:num_preemptions/gpu_cache_usage_perc): preemption spikes or a
        # pinned-high page gauge mean the pool is undersized for the load.
        self.preemptions = r.register(Counter(
            "tpu_serve_preemptions_total",
            "Requests preempted (pages reclaimed; resumed by recompute)"))
        self.kv_pages_total = r.register(Gauge(
            "tpu_serve_kv_pages_total", "Physical KV pages in the pool"))
        self.kv_pages_in_use = r.register(Gauge(
            "tpu_serve_kv_pages_in_use",
            "KV pages currently referenced by live requests"))
        # Free/evictable split (ISSUE 20 satellite): "pool full" and "pool
        # full of reusable prefixes" are different capacity situations —
        # evictable pages reclaim on demand but still serve prefix hits.
        self.kv_pages_free = r.register(Gauge(
            "tpu_serve_kv_pages_free",
            "KV pages on the free list (content meaningless)"))
        self.kv_pages_evictable = r.register(Gauge(
            "tpu_serve_kv_pages_evictable",
            "Refcount-zero KV pages retained for prefix reuse "
            "(reclaimable on demand)"))
        # Tier-2 KV (host-RAM prefix-page store, ISSUE 20): where each
        # admission's prefix lookup resolved, and the PCIe traffic the tier
        # moves. restore_bytes replaces re-prefill FLOPs; dropped counts
        # corrupted/truncated entries that fell back to re-prefill.
        self.prefix_tier_hits = r.register(Counter(
            "tpu_serve_prefix_tier_hits_total",
            "Paged admissions by prefix-lookup outcome tier",
            ("tier",)))
        self.kv_spill_bytes = r.register(Counter(
            "tpu_serve_kv_spill_bytes_total",
            "KV bytes spilled from reclaimed HBM pages to the host tier"))
        self.kv_restore_bytes = r.register(Counter(
            "tpu_serve_kv_restore_bytes_total",
            "KV bytes restored from the host tier instead of re-prefilled"))
        self.kv_restore_dropped = r.register(Counter(
            "tpu_serve_kv_restore_dropped_total",
            "Host-tier entries dropped at restore (corrupt/truncated/raced "
            "away; the span re-prefilled instead)"))
        self.kv_host_tier_used_bytes = r.register(Gauge(
            "tpu_serve_kv_host_tier_used_bytes",
            "Bytes of spilled KV pages resident in the host tier"))
        self.kv_host_tier_entries = r.register(Gauge(
            "tpu_serve_kv_host_tier_entries",
            "Spilled KV pages resident in the host tier"))
        # Batch-block size the decode kernels run with (autotuned at engine
        # start per (batch, page_size, kv_dtype) — see
        # Engine._resolve_decode_bblock). A dashboard seeing 1 on a TPU pod
        # means the autotuner was pinned or guarded off.
        self.decode_bblock = r.register(Gauge(
            "tpu_serve_decode_bblock",
            "Decode kernel batch-block size (slots per grid step)"))
        # Cold-start observability (serving/aot.py): warmup compile wall time
        # and the AOT manifest's per-chip HBM ledger. A restart whose compile
        # counter climbs by minutes is missing its persistent compilation
        # cache / AOT manifest; a zero hbm gauge means no manifest was loaded.
        self.compile_seconds = r.register(Counter(
            "tpu_serve_compile_seconds_total",
            "Wall seconds spent compiling programs at warmup"))
        self.hbm_compiled_bytes = r.register(Gauge(
            "tpu_serve_hbm_compiled_bytes",
            "Per-chip HBM bytes the AOT manifest ledger accounts "
            "(params + KV pool + max program temp)"))
        # Robustness layer (r7): overload shedding, end-to-end deadlines,
        # and the stall watchdog each get an explicit first-class signal —
        # a dashboard must distinguish "we refused work by design" from
        # "work failed" (DeepServe: the overload path is the product).
        self.requests_shed = r.register(Counter(
            "tpu_serve_requests_shed_total",
            "Requests rejected at admission (429), by reason",
            ("reason",)))
        self.deadline_expired = r.register(Counter(
            "tpu_serve_deadline_expired_total",
            "Requests cancelled because their end-to-end deadline passed"))
        self.watchdog_stalls = r.register(Counter(
            "tpu_serve_watchdog_stalls_total",
            "Stalled decode steps the watchdog aborted (requests failed, "
            "process kept alive)"))
        self.admission_preemptions = r.register(Counter(
            "tpu_serve_admission_preemptions_total",
            "Lowest-progress requests preempted to unwedge page-starved "
            "admission"))
        # Replica lifecycle (r8): 1 while the engine is draining (rejecting
        # new admissions, finishing in-flight work) — the readiness signal
        # /readyz and the router's /load poller key off the same state.
        self.draining = r.register(Gauge(
            "tpu_serve_draining",
            "1 while the engine is draining (new admissions shed with "
            "reason=draining)"))

    def mark_request(self, status: str, duration_s: float,
                     trace_id: Optional[str] = None):
        self.request_total.inc(status=status)
        self.vllm_request_total.inc(status=status)
        self.request_duration.observe(duration_s, trace_id=trace_id)
        self.vllm_request_duration.observe(duration_s, trace_id=trace_id)
        # Every terminal edge already funnels through here — feed the SLO
        # burn-rate engine from the same single point (serving/slo.py; the
        # deferred import breaks the metrics <- slo module cycle and costs a
        # cached-module dict lookup per request).
        from aws_k8s_ansible_provisioner_tpu.serving import slo as _slo

        _slo.get().observe_request(status, duration_s)


class PipelineMetrics:
    """Process-wide decode-pipeline health counters, shared by every engine
    in the process and rendered by BOTH /metrics routes (engine server and
    router) — same singleton pattern as flightrec/slo/devmon.

    The decode pipeline's whole value is staying ON under mixed traffic
    (PERF.md): every drain discharges the in-flight dispatch early and the
    next decode pays the full host bubble again. This counter makes the
    ragged-attention win — mixed prefill+decode steps riding the pipeline
    instead of killing it — measurable in production, by reason:

    - ``prefill``: a prefill admission / activation invalidated the carry
      (the legacy per-admission drain the ragged path removes);
    - ``chunk``:   a chunked-prefill walk forced the synchronous branch;
    - ``spec``:    speculative decode needed current host mirrors;
    - ``guided``:  a grammar-guided slot forced per-token dispatch;
    - ``drain``:   engine drain / idle settle (intentional, not a loss);
    - ``fail``:    a failed fetch discarded the in-flight dispatch.
    """

    def __init__(self):
        self.registry = Registry()
        r = self.registry
        self.drains = r.register(Counter(
            "tpu_serve_pipeline_drains_total",
            "Decode-pipeline drains (in-flight dispatch discharged early), "
            "by reason",
            ("reason",)))
        self.dispatches = r.register(Counter(
            "tpu_serve_pipeline_dispatches_total",
            "Decode/mixed dispatches enqueued (drain-rate denominator)"))

    def snapshot(self) -> dict:
        """Drain totals by reason + the drain rate (drains per dispatch) for
        /healthz and tpu-top — the one number that says whether the pipeline
        is actually staying open under the current traffic mix."""
        with self.drains._lock:
            by_reason = {(dict(key).get("reason") or "other"): int(val)
                         for key, val in self.drains._values.items()}
        total = sum(by_reason.values())
        dispatched = self.dispatches.total()
        return {
            "drains_total": total,
            "drains_by_reason": by_reason,
            "dispatches_total": int(dispatched),
            "drain_rate": round(total / dispatched, 4) if dispatched else 0.0,
        }


pipeline = PipelineMetrics()
