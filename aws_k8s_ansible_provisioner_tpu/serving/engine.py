"""Continuous-batching serving engine: the reference's vLLM replacement.

The reference delegates this entire component to the external vLLM container
(SURVEY.md §0 item 4, §2.2 row 1); here it is in-repo and TPU-native:

- **A small fixed set of compiled programs** drives everything:
  ``prefill_step`` (one program per prompt-length bucket),
  ``prefill_batch_step`` (N waiting prompts in one dispatch, N a power of
  two), ``prefill_chunk_step`` (one fixed-size chunk of a long prompt, decode
  interleaved between chunks), and ``decode_steps`` (two programs over all
  slots: fused horizon=N when no prompt waits, horizon=1 otherwise —
  ``n_steps`` is static). Static shapes throughout — XLA's compilation model
  is the design constraint (SURVEY.md §7 hard part #2: "continuous batching
  under XLA's static-shape constraint").
- **Prefill/decode interleaving** with prefill priority: TTFT p50 is the headline
  baseline metric (BASELINE.json), and a waiting prompt hurts TTFT more than one
  decode step hurts per-token latency.
- **Donated KV cache**: the multi-GB cache is donated to each step so XLA updates
  it in place in HBM — no per-token copies.
- **Per-slot sampling params as vectors**: any mix of greedy/temperature/top-p
  requests shares the single decode program.

The host-side scheduler (this file) is deliberately thin: slot bookkeeping,
stop conditions, and streaming queues; everything hot is inside jit. The jit
layer itself — the step functions, bblock autotune, operand construction,
and the warmup plan — lives in ``serving/programs.py`` (the compiled-program
registry, which ``serving/aot.py`` also compiles ahead-of-time); ``Engine``
inherits it as the ``EnginePrograms`` mixin.
"""

from __future__ import annotations

import collections
import itertools
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Deque, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from aws_k8s_ansible_provisioner_tpu.config import ModelConfig, ServingConfig
from aws_k8s_ansible_provisioner_tpu.serving import capacity as _capacity
from aws_k8s_ansible_provisioner_tpu.serving import paged_kv as pkv
from aws_k8s_ansible_provisioner_tpu.serving import chaos as _chaos
from aws_k8s_ansible_provisioner_tpu.serving import devmon as _devmon
from aws_k8s_ansible_provisioner_tpu.serving import flightrec as _flight
from aws_k8s_ansible_provisioner_tpu.serving import metrics as _metrics
from aws_k8s_ansible_provisioner_tpu.serving import slo as _slo
from aws_k8s_ansible_provisioner_tpu.serving.metrics import EngineMetrics
from aws_k8s_ansible_provisioner_tpu.serving.programs import (  # noqa: F401
    BAN_K,
    BBLOCK_CANDIDATES,
    BIAS_K,
    LOGPROB_K,
    _BBLOCK_CACHE,
    EnginePrograms,
    _host_lp,
    decode_steps,
    pick_decode_bblock,
    prefill_batch_step,
    prefill_chunk_step,
    prefill_step,
    spec_decode_step,
)

_REQUEST_IDS = itertools.count()


def _tree_bytes(tree) -> int:
    """HBM bytes of a pytree of device/host arrays, from shape/dtype
    metadata only — never a device transfer (safe on any thread)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        try:
            total += int(leaf.size) * int(np.dtype(leaf.dtype).itemsize)
        except (TypeError, ValueError, AttributeError):
            pass
    return total


class ContextLengthExceeded(ValueError):
    """Prompt does not fit the engine's context window.

    Raised by :meth:`Engine.submit` instead of silently truncating the prompt
    tail — the server maps this to the OpenAI ``400 context_length_exceeded``
    error the reference's vLLM engine returns for the same condition.
    """

    def __init__(self, n_prompt: int, limit: int, max_len: int):
        self.n_prompt, self.limit, self.max_len = n_prompt, limit, max_len
        super().__init__(
            f"This model's maximum prompt length is {limit} tokens "
            f"(context window {max_len}); your prompt has {n_prompt} tokens.")


class EngineOverloaded(RuntimeError):
    """Admission control shed this request (bounded queue / wait estimate).

    Raised by :meth:`Engine.submit` BEFORE the request enters the queue —
    nothing was generated, so the caller may safely retry elsewhere/later.
    The server maps this to ``429`` with a ``Retry-After`` header carrying
    :attr:`retry_after_s`; the router treats that 429 as a routable signal.
    """

    def __init__(self, reason: str, message: str, retry_after_s: float = 1.0):
        self.reason = reason
        self.retry_after_s = max(1.0, float(retry_after_s))
        super().__init__(message)


@dataclass
class Request:
    """One in-flight generation request."""

    prompt_ids: List[int]
    max_tokens: int = 256
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    # OpenAI presence/frequency penalties over the request's generated
    # tokens (0.0 = off; subtractive on logits — ops/sampling.apply_penalties)
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    # vLLM/HF ``repetition_penalty`` (1.0 = off): multiplicative over every
    # token in the prompt OR generated so far — positive logits divide,
    # non-positive multiply (HF RepetitionPenaltyLogitsProcessor semantics).
    repetition_penalty: float = 1.0
    ignore_eos: bool = False
    stream: bool = False
    cancelled: bool = False
    # OpenAI ``logprobs``: None = off; an int N = return the chosen token's
    # logprob plus N top alternatives (N=0 is valid: chosen-only, the OpenAI
    # completions logprobs=0 semantics; capped at LOGPROB_K). Any non-None
    # value switches the slot's dispatches to the logprob program variants.
    logprobs: object = None
    # OpenAI ``seed``: deterministic sampling for this request — same seed +
    # same prompt + same sampling params => same token stream, independent of
    # batch composition (ops/sampling.per_slot_keys). None = a per-engine
    # derived seed (sampling still randomized across requests).
    seed: Optional[int] = None
    # resolved at submit(): seed, or the engine's derived default
    eff_seed: int = 0
    # vLLM ``stop_token_ids``: extra per-request stop tokens (the model's
    # eos set still applies unless ignore_eos).
    stop_token_ids: tuple = ()
    # vLLM ``min_tokens``: suppress ALL stop tokens (eos + stop_token_ids)
    # until this many tokens have been generated (budget still caps).
    min_tokens: int = 0
    # OpenAI ``logit_bias``: ((token_id, bias), ...) pairs added to the
    # logits before every sampling decision (greedy included — ±100 act as
    # force/ban, the documented semantics). Server normalizes the JSON map;
    # () = off. At most BIAS_K entries (submit() validates).
    logit_bias: tuple = ()
    # vLLM ``prompt_logprobs`` (also powers OpenAI legacy echo+logprobs):
    # None = off; int K = per-PROMPT-position logprob of the actual token
    # plus top-K alternatives (position 0 is None). Disables prefix-cache
    # reuse for the request (reused rows skip prefill, which is where these
    # are computed) and rejects prompts that need chunking.
    prompt_logprobs: object = None
    # Multi-LoRA (models/lora.py): name of an adapter registered at Engine
    # construction, or None = base model. Any mix of adapters rides one
    # continuous batch (per-slot index vector on every dispatch).
    lora: Optional[str] = None
    # OpenAI ``response_format`` (serving/guided.py): a TokenGrammar (or
    # GuidedState) constraining every sampled token to the grammar's allowed
    # set. submit() wraps a bare grammar in a fresh per-request GuidedState.
    # Guided slots force horizon-1 decode dispatches (the host FSM must see
    # token N before masking token N+1) and are spec-decode-ineligible.
    guided: object = None
    # End-to-end deadline, RELATIVE seconds from submission (server parses
    # the X-Request-Deadline-Ms header / deadline_ms body field into this).
    # None = the engine's default (serving.request_timeout_s). submit()
    # resolves it into the absolute ``t_deadline``; the engine enforces it
    # between dispatches — expiry cancels the request, releases its slot and
    # pages, and finishes it with finish_reason "timeout" (HTTP 408).
    deadline_s: Optional[float] = None
    # Mid-stream failover continuation (r8): token ids another replica
    # already generated (and relayed to the client) for this exact prompt +
    # sampling params + seed. submit() pre-populates ``generated`` with them
    # and registers a preemption-style resume, so the request re-prefills
    # prompt + resume as pure CACHE REBUILD and the next decode draw uses
    # the seeded key at position len(prompt) + len(resume) — by the
    # cross-resume reproducibility contract (decode_steps' ctr alignment),
    # the continuation is token-identical to the uninterrupted stream.
    # Only the NEW tokens reach out_queue. Paged engines only.
    resume_ids: tuple = ()
    # absolute time.monotonic() deadline, resolved at submit (0.0 = none)
    t_deadline: float = 0.0
    # root-span trace id the server bound to this request (empty = tracing
    # off) — feeds the OpenMetrics exemplars on the ttft/request-duration
    # histogram buckets so a burning bucket links straight to its trace
    trace_id: str = ""
    id: int = field(default_factory=lambda: next(_REQUEST_IDS))
    # Filled in by the engine:
    generated: List[int] = field(default_factory=list)
    # per generated token: (own logprob, [(token_id, logprob) x k])
    logprob_data: List[tuple] = field(default_factory=list)
    # per PROMPT position: None (position 0) or (own logprob,
    # [(token_id, logprob) x k]) — filled at activation when
    # prompt_logprobs is requested
    prompt_logprob_data: List = field(default_factory=list)
    out_queue: "queue.Queue" = field(default_factory=queue.Queue)
    t_submit: float = 0.0
    # first admission out of the queue into a slot (set-if-unset, so a
    # preempt/requeue round-trip keeps the original queue-wait boundary) —
    # splits TTFT into queue-wait vs prefill for the tracing phase spans
    t_prefill_start: float = 0.0
    t_first_token: float = 0.0
    t_done: float = 0.0
    finish_reason: str = ""

    def wait(self, timeout: Optional[float] = None) -> List[int]:
        """Block until completion; returns generated token ids."""
        deadline = time.monotonic() + timeout if timeout else None
        while True:
            remaining = (deadline - time.monotonic()) if deadline else None
            if remaining is not None and remaining <= 0:
                raise TimeoutError(f"request {self.id} timed out")
            item = self.out_queue.get(timeout=remaining)
            if item is None:
                return self.generated


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


class Engine(EnginePrograms):
    """Continuous-batching engine over a fixed set of decode slots."""

    # Single-writer contract (tpulint R5 / LockSan): these attributes are
    # mutated ONLY by the engine-step thread (run_forever -> step and its
    # helpers). Other threads may read them (GIL-atomic snapshots for
    # /health, /load and metrics) but never write. Attributes shared for
    # WRITING across threads (draining, _drain_deadline, _stall_abort,
    # _queued, ...) are NOT listed here — their writes go under self._lock.
    _R5_THREAD_OWNED = (
        "table", "lengths", "cache", "counts", "last_token",
        "slot_req", "temps", "pres_pens", "freq_pens", "rep_pens",
        "ban_until", "bias_ids", "bias_vals", "lora_idx", "_bias_n",
        "_slot_pages", "_slot_tokens", "_chunk",
        "_chunk_yield", "_prefill_streak", "_admission_blocked_since",
        "_tok_times", "_admit_seq", "_seq_counter", "prompt_mask",
        "_inflight", "_pipe_carry", "_carry_gen", "_op_cache",
        "_op_dirty_sampling", "_op_dirty_table", "_last_ready",
        "_busy_watermark", "_allow_dev", "_allow_batch_dev",
        "_restore_pending",
    )

    def __init__(self, cfg: ModelConfig, params, serving: ServingConfig,
                 eos_token_id: Optional[int] = None, mesh=None, draft=None,
                 lora=None):
        self.cfg = cfg
        self.params = params
        self.serving = serving
        # Draft-model speculation (serving/draft.py; VERDICT r4 next #7):
        # ``draft`` is (draft_cfg, draft_params). Requires spec_decode with
        # spec_method="draft"; the DraftModel allocates its own dense cache
        # after max_len resolves below.
        self._draft_src = draft
        self.eos_token_id = cfg.eos_token_id if eos_token_id is None \
            else eos_token_id
        # Any member stops generation (Llama-3 Instruct ships several eos
        # ids; chat turns end with <|eot_id|>, not the primary eos). A
        # constructor override (e.g. the tokenizer's eos) EXTENDS the config's
        # set — replacing it would evict <|end_of_text|> when the tokenizer
        # declares <|eot_id|>.
        self._eos_set = ({self.eos_token_id, cfg.eos_token_id}
                         | set(cfg.extra_eos_token_ids))
        self.num_slots = serving.max_decode_slots
        # Round the cache window up to a 256 multiple: the Pallas decode
        # kernel streams the cache in chunks that must divide the window, and
        # an awkward length (e.g. 509) would degrade its chunk size to the
        # largest divisor — potentially 1. A slightly larger cache is the
        # right trade.
        self.max_len = -(-serving.max_cache_len // 256) * 256 \
            if serving.max_cache_len > 256 else serving.max_cache_len
        # Never exceed the model's position range: RoPE models degrade
        # gracefully, but a learned position table (OPT) silently clamps its
        # gather past max_seq_len — same embedding for every later token.
        self.max_len = min(self.max_len, cfg.max_seq_len)
        self.buckets = tuple(b for b in serving.prefill_buckets
                             if b <= self.max_len)
        # Program-operand construction (quantize/shard/LoRA, paged pool
        # + dense cache) lives with the compiled-program registry:
        # EnginePrograms._init_params_and_cache (serving/programs.py).
        self._init_params_and_cache(mesh, lora)

        self.metrics = EngineMetrics()
        # AOT manifest summary (serving/aot.py), installed by
        # load_aot_manifest; surfaced on /healthz and the hbm gauge.
        self.aot = None
        self._rng = jax.random.PRNGKey(0)
        # Derived sampling seeds for requests that don't set OpenAI `seed`.
        # Default (derived_seed=None): entropy from os.urandom, so engine
        # restarts and sibling replicas draw independently — the vLLM/OpenAI
        # nondeterministic default (ADVICE r3: Random(0) made every restart
        # replay the identical unseeded sample sequence). Harnesses that
        # need two engines to draw identically (dryrun parity, tests) pin an
        # int derived_seed.
        import os as _os
        import random as _random

        self._py_rng = _random.Random(
            int.from_bytes(_os.urandom(8), "little")
            if serving.derived_seed is None else int(serving.derived_seed))
        # Host-side slot state (numpy mirrors of the device vectors).
        self.lengths = np.zeros(self.num_slots, np.int32)
        self.last_token = np.zeros(self.num_slots, np.int32)
        self.temps = np.zeros(self.num_slots, np.float32)
        self.top_ks = np.zeros(self.num_slots, np.int32)
        self.top_ps = np.ones(self.num_slots, np.float32)
        self.seeds = np.zeros(self.num_slots, np.uint32)
        # min_tokens stop suppression: per-slot banned-token lists (padded
        # with an out-of-vocab id — the masking scatter drops them) active
        # while the slot's context length < ban_until (prompt + min_tokens)
        self.ban_ids = np.full((self.num_slots, BAN_K), 2**31 - 1, np.int32)
        self.ban_until = np.zeros(self.num_slots, np.int32)
        # OpenAI logit_bias: per-slot (ids, vals) rows, always-on scatter-add
        # in every sampling step (padding ids are out-of-vocab and drop) —
        # the same no-program-variant mechanism as the ban rows above.
        # _bias_n tracks which slots have live bias (spec eligibility).
        self.bias_ids = np.full((self.num_slots, BIAS_K), 2**31 - 1, np.int32)
        self.bias_vals = np.zeros((self.num_slots, BIAS_K), np.float32)
        self._bias_n = np.zeros(self.num_slots, np.int32)
        # per-slot LoRA adapter index (0 = base); rides every dispatch when
        # adapters are registered. _slot_lora mirrors the adapter whose
        # projections produced each DENSE slot's retained rows — the dense
        # prefix cache must never cross adapters (review r5).
        self.lora_idx = np.zeros(self.num_slots, np.int32)
        self._slot_lora = np.zeros(self.num_slots, np.int32)
        self.pres_pens = np.zeros(self.num_slots, np.float32)
        self.freq_pens = np.zeros(self.num_slots, np.float32)
        self.rep_pens = np.ones(self.num_slots, np.float32)
        # [num_slots, V] generated-token counts, allocated lazily on the
        # first penalized request (78 MB at Qwen3 vocab x 128 slots — only
        # paid when the feature is used); rides decode_steps' donated carry.
        self.counts = None
        # [num_slots, V] bool prompt-token presence, lazily allocated with
        # the first repetition_penalty request (repetition covers PROMPT
        # tokens too — counts track generated only). Stale rows under
        # rep == 1.0 slots are exact no-ops, like stale counts rows.
        self.prompt_mask = None
        self.slot_req: List[Optional[Request]] = [None] * self.num_slots
        # Admission queue + slot lifecycle live in the runtime core (native
        # C++ when built — see native/runtime; Python fallback otherwise).
        # The engine holds only the id -> Request map for queued requests.
        from aws_k8s_ansible_provisioner_tpu.runtime import make_scheduler

        self.sched = make_scheduler(self.num_slots, self.max_len,
                                    serving.page_size,
                                    max_queue=max(0,
                                                  serving.max_queue_depth))
        self._queued: dict = {}
        self._lock = threading.Lock()
        self._work_event = threading.Event()
        self._tok_times: Deque = collections.deque(maxlen=50)
        # Chunked-prefill state: {"req", "slot", "off", "C"} while a prompt
        # (or a prefix-cache suffix) is being prefilled chunk-by-chunk; decode
        # steps interleave between chunks (self._chunk_yield alternates).
        self._chunk: Optional[dict] = None
        self._chunk_yield = False
        # Consecutive prefill dispatches since the last decode — the
        # prefill_fairness floor keys off this (step()).
        self._prefill_streak = 0
        # Prefix cache: token ids whose K/V rows are resident in rows
        # [0, len) of each slot — retained after a request finishes (rows are
        # only ever written at/past a slot's current length, so a freed
        # slot's prompt rows stay intact until the slot is reused).
        self._slot_tokens: List[tuple] = [()] * self.num_slots
        # Batch-block size for the decode kernels (PALLAS_DECODE_BBLOCK
        # promoted to a first-class parameter): explicit config/env override,
        # else a one-shot deterministic startup microbench over
        # BBLOCK_CANDIDATES per (batch, page_size, kv_dtype) — TPU-only (the
        # guard keeps CPU tests and the tier-1 gate free of it). Reported on
        # /healthz and as the tpu_serve_decode_bblock gauge.
        self.decode_bblock = self._resolve_decode_bblock()
        self.metrics.decode_bblock.set(self.decode_bblock)
        # Robustness layer (r7): stall watchdog + paged-admission pressure
        # relief. STALL_AFTER_S becomes an instance knob (the class default
        # stays as documentation/back-compat); _stall_abort is the watchdog's
        # signal to a chaos-observable stalled step; _admission_blocked_since
        # tracks how long the queue head has been page-starved while a slot
        # sat free (the preempt-under-pressure trigger).
        if serving.watchdog_stall_s > 0:
            self.STALL_AFTER_S = float(serving.watchdog_stall_s)
        self._stall_abort = False
        self._admission_blocked_since = 0.0
        # Graceful drain (r8): while draining, submit() sheds everything with
        # the structured "draining" reason (503 at the HTTP layer — the
        # router re-routes it like a connect failure); in-flight requests run
        # to completion until _drain_deadline, past which _reap_expired
        # cancels stragglers through the existing deadline path.
        self.draining = False
        self._drain_deadline = 0.0
        # One-deep asynchronous decode pipeline (perf_opt r9): the engine
        # enqueues decode N+1 before fetching N's tokens, so the host
        # emit/SSE/scheduling gap overlaps device compute.
        # _inflight: the dispatched-but-unfetched decode record (see
        # EnginePrograms._decode_dispatch); _pipe_carry: its device-resident
        # (last_token, lengths, carry_gen) end state, consumed by the next
        # dispatch when _carry_gen still matches; _carry_gen bumps on every
        # slot-lifecycle transition that rewrites state out of band of the
        # carry (activate/preempt/chunk start).
        self._inflight: Optional[dict] = None
        self._pipe_carry = None
        self._carry_gen = 0
        # Device operand-upload cache (seeds/ban/bias/penalties/table...):
        # re-uploaded only when the dirty flags say the host mirrors
        # changed, instead of per dispatch (EnginePrograms._decode_operands)
        self._op_cache: dict = {}
        self._op_dirty_sampling = True
        self._op_dirty_table = True
        # Guided allow-mask device caches (ISSUE 16): one-entry
        # (key, device array) pairs keyed on FSM fingerprints, so a mask
        # whose grammar state did not advance between dispatches (a guided
        # chunk walk, decode steps around a neighbor's admission) is
        # re-dispatched without a rebuild or re-upload
        # (EnginePrograms._allow_row / _allow_words).
        self._allow_dev = None
        self._allow_batch_dev = None
        # Bubble accounting: _last_ready marks a fetch completing with
        # nothing enqueued behind it (device going idle); the next dispatch
        # books the gap on decode_bubble_seconds. _busy_watermark is the
        # device-time high-water mark so overlapped dispatches never
        # double-count device_busy_seconds.
        self._last_ready = 0.0
        self._busy_watermark = 0.0
        # Device telemetry (serving/devmon.py): hand the monitor the
        # analytical cost model and the host-metadata HBM samplers. Pure
        # wiring — recording happens at the programs.py busy sites, and the
        # samplers never touch the device (sizes/dtypes are host metadata).
        self._install_devmon()
        self._install_capacity()

    def _install_devmon(self):
        mon = _devmon.get()
        params_bytes = _tree_bytes(self.params)
        mon.install_cost_model(_devmon.CostModel.from_config(
            self.cfg, kv_dtype=self.serving.kv_dtype,
            weight_bytes=params_bytes))
        cache_bytes = _tree_bytes(self.cache)

        def _live() -> dict:
            comp = {"params": float(params_bytes)}
            if self.paged:
                sts = [a.stats() for a in self.allocators]
                total = sum(s["pages_total"] for s in sts) or 1
                live = sum(s["pages_live"] for s in sts)
                comp["kv_pages"] = cache_bytes * (live / total)
                # evictable pages hold reusable prefixes but yield to the
                # allocator on demand — ledger them as their own component
                # so "pool full" and "pool full of reclaimable prefixes"
                # read differently (ISSUE 20 satellite)
                evict = sum(s["pages_evictable"] for s in sts)
                comp["kv_pages_evictable"] = cache_bytes * (evict / total)
            else:
                comp["kv_cache"] = float(cache_bytes)
            carry = self._pipe_carry
            if carry is not None:
                comp["sampler_carry"] = float(
                    _tree_bytes((carry[0], carry[1])))
            if self._op_cache:
                comp["operand_cache"] = float(
                    _tree_bytes(tuple(self._op_cache.values())))
            return comp

        def _compiled() -> float:
            aot = self.aot
            return float(aot["hbm_total_bytes"]) if aot else 0.0

        mon.install_hbm(_live, _compiled)

    def _install_capacity(self):
        """Hand the capacity estimator (serving/capacity.py) its engine
        closures: live queue depth for the Little's-law delay, and the
        throughput gauge as the ceiling fallback while devmon's decode
        window is still empty. Pure wiring — offered-load recording
        happens at the submit()/shed edges."""
        _capacity.get().install_engine(
            lambda: self.sched.stats().queue_depth,
            lambda: self.metrics.tokens_per_second.value())

    @staticmethod
    def _build_mesh(serving: ServingConfig):
        """Build the serving mesh from config (None for single-device).

        All three axes serve: ``dp`` shards slots, ``tp`` shards heads
        (Megatron), ``sp`` shards the KV cache's sequence axis — the
        long-context axis, letting the cache window scale with the sp group's
        aggregate HBM (decode merges per-shard flash partials; see
        ops/attention.make_decode_attend_carry).
        """
        mc = serving.mesh
        if mc.num_devices <= 1:
            return None
        from aws_k8s_ansible_provisioner_tpu.parallel.mesh import make_mesh

        return make_mesh(mc)

    @property
    def pending(self):
        """Back-compat view of the scheduler queue (len / truthiness)."""
        with self._lock:
            return list(self._queued.values())

    # -- submission ---------------------------------------------------------

    @property
    def prompt_limit(self) -> int:
        """Longest prompt a slot can hold.

        Whole-prompt prefill is bound by the largest bucket; chunked prefill
        (serving.prefill_chunk > 0) lifts that to the cache window itself —
        any prompt that physically fits the slot is servable in chunks.
        """
        if self.serving.prefill_chunk > 0:
            return self.max_len - 2
        return min(self.buckets[-1], self.max_len - 2)

    def _should_chunk(self, req: Request) -> bool:
        if self.serving.prefill_chunk <= 0:
            return False
        n = len(req.prompt_ids)
        # Chunk when the prompt exceeds the chunk size OR the largest bucket:
        # with chunking enabled, prompt_limit is lifted past the buckets, so a
        # prompt in (buckets[-1], prefill_chunk] must take the chunked path
        # too — the whole-prompt path cannot represent it (review r2 #2).
        return n > self.serving.prefill_chunk or n > self.buckets[-1]

    @property
    def _chunk_size(self) -> int:
        """Chunk program width: the configured chunk, else the largest bucket
        (the prefix-cache suffix path needs a chunk program even when plain
        chunked prefill is disabled)."""
        if self.serving.prefill_chunk > 0:
            return self.serving.prefill_chunk
        return self.buckets[-1]

    def _find_prefix(self, req: Request, slot: int):
        """Longest resident prompt prefix for ``req`` → (src_slot, n) or None.

        Scans the per-slot retained prompt tokens (host-side; <= num_slots
        short tuple comparisons). The reuse is capped one token short of the
        prompt — the final token must run through prefill to produce the
        request's first sampled token. ``slot`` is the slot just assigned to
        the request (for the dispatch-economics gate; matching it means the
        rows are already in place and reuse is free).
        """
        if not self.serving.prefix_cache or req.prompt_logprobs is not None:
            return None
        ids = req.prompt_ids
        cap = len(ids) - 1
        req_lidx = (self.lora_names.index(req.lora) + 1
                    if req.lora is not None else 0)
        best_n, best_s = 0, -1
        for s, toks in enumerate(self._slot_tokens):
            if self._slot_lora[s] != req_lidx:
                # rows were projected under a different adapter (review r5)
                continue
            m = min(len(toks), cap)
            if m <= best_n:
                continue
            n = 0
            while n < m and toks[n] == ids[n]:
                n += 1
            if n > best_n:
                best_n, best_s = n, s
        if best_n < max(1, self.serving.prefix_cache_min_len):
            return None
        if not self._hit_pays(req, best_s, slot, best_n):
            return None
        return best_s, best_n

    def _hit_pays(self, req: Request, src: int, slot: int, n: int) -> bool:
        """Dispatch-economics gate on a prefix hit.

        The hit path costs one slot-copy dispatch (zero when the request got
        its own previous slot back) plus ceil(suffix/C) chunk dispatches; the
        miss path costs one bucket dispatch (or ceil(len/C) chunks for a
        prompt that chunks anyway). Each dispatch is ~an RTT on a
        network-attached chip, so a hit that ADDS dispatches only pays once
        the reused rows save enough prefill FLOPs to beat the added latency —
        ``prefix_cache_payback_rows`` calibrates that crossover (lower it for
        big models, where recompute dominates sooner)."""
        C = self._chunk_size
        ln = len(req.prompt_ids)
        hit_disp = (0 if src == slot else 1) + max(1, -(-(ln - n) // C))
        miss_disp = -(-ln // C) if self._should_chunk(req) else 1
        if hit_disp <= miss_disp:
            return True
        return n >= max(1, self.serving.prefix_cache_payback_rows)

    # -- paged-KV lifecycle -------------------------------------------------
    # Slots map to dp groups contiguously (slot // slots_per_group); each
    # group's allocator works in LOCAL page ids (0 = its scratch page) and
    # the device table stores GLOBAL ids = local + group * _group_pages.
    # Single-device (dp_groups == 1) degenerates to the original layout.

    def _group(self, slot: int) -> int:
        return slot // self._slots_per_group

    def _alloc(self, slot: int):
        """The allocator owning this slot's dp group's pool partition."""
        return self.allocators[self._group(slot)]

    def _gbase(self, slot: int) -> int:
        """Global page id of this slot's group's partition base."""
        return self._group(slot) * self._group_pages

    def _paged_admit(self, req: Request, slot: int, isolated: bool):
        """Assign pages to an admitted request: page-level prefix reuse
        (hash-chain lookup, refcounted sharing — no row copies) + fresh
        allocation for the tail. Returns (ids, reuse_off, resumed), or None
        if the allocator cannot cover the tail right now (the caller
        requeues; the admission gate makes this rare — it means evictable
        pages vanished between the gate and here).

        ``isolated`` mirrors the dense path's dispatch-economics gate: a
        prefix hit forces the serialized chunk path, so under a burst the
        batched prefill wins — unless the request would chunk anyway, or the
        match spans >= prefix_reuse_min_pages whole pages, where skipping
        the shared-prefix compute (and refcount-sharing the pages instead
        of writing duplicates) beats the batch slot (ROUTER_BENCH round 5:
        the isolation-only gate left affinity-routed conversation load at a
        ~12% hit rate because bursts never consulted the index).
        """
        if self.host_tier is not None:
            # land spill copies issued on earlier steps (the
            # copy_to_host_async has normally completed by now), releasing
            # their staging HBM before this admission allocates
            self.host_tier.flush_to_host()
        ctx = self._resume_ctx.get(req.id)
        resumed = ctx is not None
        ids = list(ctx) if resumed else list(req.prompt_ids)
        ps = self.serving.page_size
        allocator = self._alloc(slot)
        matched: List[int] = []
        n = 0
        host_keys: List[tuple] = []
        if self.serving.prefix_cache and req.prompt_logprobs is None:
            req_lidx = (self.lora_names.index(req.lora) + 1
                        if req.lora is not None else 0)
            matched, n, host_keys = allocator.lookup_prefix(
                ids, salt=self._lora_salt(req_lidx))
            # the final token must run through prefill to produce the first
            # sampled token — cap reuse one token short of the prompt
            while host_keys and n + len(host_keys) * ps > len(ids) - 1:
                host_keys.pop()
            while n > len(ids) - 1:
                matched.pop()
                n -= ps
            # host-restorable pages count toward the burst-economics gate:
            # a restore replaces the same prefill compute a resident share
            # does, at PCIe cost instead of zero
            if not (isolated or resumed or self._should_chunk(req)
                    or n + len(host_keys) * ps
                    >= ps * max(1, self.serving.prefix_reuse_min_pages)):
                matched, n, host_keys = [], 0, []
        restore = self._host_entries(allocator, ids, n, host_keys)
        for pid in matched:
            allocator.retain(pid)
        need = -(-len(ids) // ps) - len(matched)
        fresh = allocator.alloc(need) if need > 0 else []
        if fresh is None:
            allocator.release_all(matched)
            return None
        # gather any content this alloc just reclaimed BEFORE the restore
        # scatter (or the upcoming prefill) can overwrite those pages —
        # enqueue order is what makes the spill read pre-reclaim bytes
        self._spill_reclaimed(slot)
        self._resume_ctx.pop(req.id, None)
        pages = matched + list(fresh)
        self._slot_pages[slot] = pages
        self._op_dirty_table = True
        self.table[slot, :] = self._scratch[slot]
        self.table[slot, :len(pages)] = \
            np.asarray(pages, np.int32) + self._gbase(slot)
        self._seq_counter += 1
        self._admit_seq[slot] = self._seq_counter
        off = n
        if restore:
            # the restored span begins at the first fresh page: pages[p] for
            # p in [len(matched), len(matched)+len(restore)) — exactly the
            # logical positions the host chain extends
            self._schedule_restore(slot, fresh[:len(restore)], restore)
            off = n + len(restore) * ps
        if off > 0:
            self.metrics.prefix_cache_hits.inc()
            self.metrics.prefix_tokens_reused.inc(off)
        self.metrics.prefix_tier_hits.inc(
            tier="host" if restore else ("hbm" if n > 0 else "miss"))
        self._pages_gauges()
        return ids, off, resumed

    def _host_entries(self, allocator, ids: List[int], n: int,
                      host_keys: List[tuple]) -> List[dict]:
        """Fetch + verify the host-tier payloads extending a resident match.

        Walks ``host_keys`` in chain order, verifying each entry's tokens and
        per-leaf shapes against the pool's layout. The first failure —
        corrupted/truncated payload (chaos ``kv_offload_error``) or an entry
        that raced away — truncates the restorable extension there: the
        suffix re-prefills, tokens are never wrong (drop, not corrupt).
        """
        tier = allocator.host_tier
        if tier is None or not host_keys:
            return []
        ch = _chaos.get()
        if ch.enabled:
            ch.on_kv_restore(tier, host_keys)
        ps = self.serving.page_size
        entries: List[dict] = []
        p0 = n // ps
        for i, key in enumerate(host_keys):
            toks = tuple(ids[(p0 + i) * ps:(p0 + i + 1) * ps])
            data = tier.fetch(key, toks, self._page_shapes)
            if data is None:
                self.metrics.kv_restore_dropped.inc()
                break
            entries.append(data)
        return entries

    def _schedule_restore(self, slot: int, pids: List[int],
                          entries: List[dict]):
        """Enqueue the batched host->HBM restore of spilled pages into the
        slot's freshly allocated pages. Async-only (tpulint R8): stacks the
        payloads per leaf, device-puts them and scatters in place (donated
        pool, same per-page layout as write_prompts_paged_layer). XLA data
        dependencies order the scatter ahead of every later program reading
        these pages — nothing blocks here and no pipeline drains; timing and
        byte accounting settle in _settle_restore at chunk start."""
        gbase = self._gbase(slot)
        data = {name: jnp.stack([e[name] for e in entries], axis=1)
                for name in entries[0]}
        self.cache = pkv.restore_pages(
            self.cache, [int(p) + gbase for p in pids], data)
        nbytes = len(entries) * self._page_bytes
        self._alloc(slot).host_tier.note_restored(len(entries), nbytes)
        self._restore_pending[slot] = {
            "pages": len(entries),
            "tokens": len(entries) * self.serving.page_size,
            "bytes": nbytes, "t0": time.monotonic()}

    def _settle_restore(self, slot: int):
        """Settle a scheduled restore before the slot's first suffix chunk:
        the paged analogue of the dense prefix-copy sync. The block is
        sanctioned (R8) — the wait IS the PCIe DMA this feature trades for
        the prefix re-prefill FLOPs, and devmon's kv_restore cost term needs
        the real wall time."""
        pend = self._restore_pending.pop(slot, None)
        if pend is None:
            return
        jax.block_until_ready(self.cache["k"])
        dt = time.monotonic() - pend["t0"]
        _devmon.note("kv_restore", dt, tokens=pend["tokens"])
        self.metrics.kv_restore_bytes.inc(pend["bytes"])
        if self.host_tier is not None:
            self.host_tier.flush_to_host()

    def _spill_reclaimed(self, slot: int):
        """Drain the slot's allocator reclaim log into the host tier:
        one batched device-side gather per burst, per-page slices handed to
        the tier with their PCIe copy started. Async-only (tpulint R8) —
        runs right after the allocation that reclaimed the pages, on the
        admission/growth path, and never blocks; the numpy conversion
        happens later in HostTier.flush_to_host at a sanctioned point."""
        allocator = self._alloc(slot)
        tier = allocator.host_tier
        log = allocator.evicted_log
        if tier is None or not log:
            return
        allocator.evicted_log = []
        gbase = self._gbase(slot)
        data = pkv.gather_pages(self.cache,
                                [pid + gbase for pid, _, _ in log])
        for i, (_, key, toks) in enumerate(log):
            entry = {name: arr[:, i] for name, arr in data.items()}
            for a in entry.values():
                start = getattr(a, "copy_to_host_async", None)
                if start is not None:
                    start()
            tier.put(key, toks, entry, self._page_bytes)
        self.metrics.kv_spill_bytes.inc(len(log) * self._page_bytes)

    def _index_prompt_pages(self, slot: int, ids: List[int],
                            n_valid: Optional[int] = None):
        """Register the slot's FULL pages over ``ids`` in the allocator's
        hash-chain index so later prompts (and preemption resumes) can share
        them. Partial tail pages are never indexed — their rows past the
        content are scratch garbage. ``n_valid`` caps indexing to pages whose
        rows are all WRITTEN: at preemption the last generated token's K/V
        row is still pending the next dispatch, so indexing past
        len(ids) - 1 would publish a page with one garbage row to every
        future prefix hit (review r3)."""
        if not self.serving.prefix_cache:
            # no lookup side -> indexing would be pure overhead, and
            # unindexed pages go straight back to the free list at release
            return
        ps = self.serving.page_size
        allocator = self._alloc(slot)
        pages = self._slot_pages[slot]
        n_valid = len(ids) if n_valid is None else n_valid
        key = self._lora_salt(self.lora_idx[slot])
        for p in range(min(n_valid // ps, len(pages))):
            key = allocator.index_page(
                pages[p], key, tuple(ids[p * ps:(p + 1) * ps]))

    def _release_slot_pages(self, slot: int):
        """Return a slot's pages to the allocator (indexed ones go to the
        evictable LRU, still prefix-matchable) and point its table at the
        scratch page — idle slots' garbage decode writes must never land in
        pages another request now owns."""
        if not self.paged:
            return
        self._alloc(slot).release_all(self._slot_pages[slot])
        self._slot_pages[slot] = []
        # a restore scheduled for a slot torn down before its chunk started
        # (deadline/cancel between admission and dispatch) must not settle
        # against a later tenant's chunk
        self._restore_pending.pop(slot, None)
        self._op_dirty_table = True
        self.table[slot, :] = self._scratch[slot]
        self.lengths[slot] = 0
        self._pages_gauges()

    def _pages_gauges(self):
        sts = [a.stats() for a in self.allocators]
        self.metrics.kv_pages_total.set(sum(s["pages_total"] for s in sts))
        self.metrics.kv_pages_in_use.set(sum(s["pages_live"] for s in sts))
        self.metrics.kv_pages_free.set(sum(s["pages_free"] for s in sts))
        self.metrics.kv_pages_evictable.set(
            sum(s["pages_evictable"] for s in sts))
        if self.host_tier is not None:
            self.metrics.kv_host_tier_used_bytes.set(
                self.host_tier.used_bytes)
            self.metrics.kv_host_tier_entries.set(len(self.host_tier))

    def _ensure_pages(self, new_rows: int) -> bool:
        """Grow every active slot's page run to cover rows
        [0, min(len + new_rows, window)) before a decode/spec dispatch — the
        device cannot allocate, and surplus mid-horizon writes must land in
        pages the slot OWNS (never scratch aliased with another slot's
        table). When the pool runs dry, preempt the newest-admitted request
        (vLLM-style recompute: pages freed, request resubmitted at the queue
        front) until allocation succeeds. Returns whether any slot is still
        active."""
        if not self.paged:
            return bool(self._active_slots())
        ps = self.serving.page_size
        # oldest first: under pressure the newest admissions yield their
        # pages (and their slots) to the oldest — FCFS fairness
        order = sorted(self._active_slots(), key=lambda s: self._admit_seq[s])
        for slot in order:
            if self.slot_req[slot] is None:   # preempted below this round
                continue
            rows = min(int(self.lengths[slot]) + new_rows,
                       self.pages_per_slot * ps)
            pages = self._slot_pages[slot]
            while len(pages) < -(-rows // ps):
                need = -(-rows // ps) - len(pages)
                got = self._alloc(slot).alloc(need)
                if got is not None:
                    # spill whatever this alloc reclaimed before the decode
                    # dispatch can write the pages (async gather only —
                    # this is the hot path)
                    self._spill_reclaimed(slot)
                    self._op_dirty_table = True
                    self.table[slot, len(pages):len(pages) + need] = \
                        np.asarray(got, np.int32) + self._gbase(slot)
                    pages.extend(got)
                    break
                # newest admission IN THIS SLOT'S GROUP yields — pages are
                # group-local, so preempting another group frees nothing for
                # this slot. When the victim is this slot itself (youngest in
                # its group and still starving), it gets requeued rather than
                # taking pages from older requests.
                victim = max((s for s in self._active_slots()
                              if self._group(s) == self._group(slot)),
                             default=None,
                             key=lambda s: self._admit_seq[s])
                if victim is None:
                    break
                self._preempt(victim)
                if victim == slot:
                    break
        self._pages_gauges()
        return bool(self._active_slots())

    def _preempt(self, slot: int, front: bool = True):
        """Reclaim a running request's pages; it resumes later by
        re-prefilling prompt + generated-so-far (the full pages of that
        context stay in the evictable index, so the resume usually hash-hits
        everything but the tail). The vLLM scheduler's RECOMPUTE preemption,
        paged-TPU edition. ``front=False`` (admission pressure relief)
        requeues at the BACK so the starved queue head admits first."""
        req = self.slot_req[slot]
        ids = req.prompt_ids + req.generated
        # make the resume a prefix hit — but only over fully-WRITTEN pages
        # (the last generated token's row is pending the next dispatch)
        self._index_prompt_pages(slot, ids, n_valid=len(ids) - 1)
        self._resume_ctx[req.id] = ids
        self.slot_req[slot] = None
        # the preempted slot's host state diverges from any in-flight
        # dispatch's device carry, and its sampling rows are rewritten
        self._carry_gen += 1
        self._op_dirty_sampling = True
        self.temps[slot] = 0.0
        self.pres_pens[slot] = 0.0
        self.freq_pens[slot] = 0.0
        self.rep_pens[slot] = 1.0
        self.ban_until[slot] = 0
        self.bias_ids[slot, :] = 2**31 - 1
        self.bias_vals[slot, :] = 0.0
        self.lora_idx[slot] = 0
        self._bias_n[slot] = 0
        self._release_slot_pages(slot)
        self.sched.release(slot)
        remaining = max(1, req.max_tokens - len(req.generated))
        with self._lock:
            self._queued[req.id] = req
        if front:
            self.sched.submit_front(req.id, len(ids), remaining)
        else:
            # bound-exempt: already-admitted work must never shed on requeue
            self.sched.requeue(req.id, len(ids), remaining)
        self.metrics.preemptions.inc()
        _flight.record("preempt", req.id, slot=slot,
                       n_generated=len(req.generated), front=front)
        self.metrics.active_requests.set(len(self._active_slots()))
        self.metrics.queue_depth.set(self.sched.stats().queue_depth)

    def submit(self, req: Request) -> Request:
        req.t_submit = time.monotonic()
        # Graceful drain (r8): a draining engine admits NOTHING — shed with
        # the structured "draining" reason before any other validation.
        # Nothing was generated, so the caller (router) may always re-route.
        if self.draining:
            self.metrics.requests_shed.inc(reason="draining")
            _slo.get().observe_admission(shed=True)
            _capacity.get().observe_submit(tokens=max(1, req.max_tokens),
                                           shed=True)
            _flight.record("shed", req.id, reason="draining")
            _flight.finish(req.id, "shed", ok=False)
            raise EngineOverloaded(
                "draining", "engine is draining; not admitting new requests",
                retry_after_s=max(1.0, self._drain_deadline
                                  - time.monotonic()))
        # A prompt that doesn't fit is an ERROR, not a truncation: serving the
        # tail of a too-long prompt silently answers a different question
        # (the reference's vLLM rejects with 400 context_length_exceeded).
        # max_tokens, by contrast, is a *budget* and clamps to what's left.
        if len(req.prompt_ids) > self.prompt_limit:
            raise ContextLengthExceeded(len(req.prompt_ids), self.prompt_limit,
                                        self.max_len)
        if req.resume_ids:
            # Failover continuation: rides the preemption-resume machinery,
            # which is paged-only (_paged_admit consults _resume_ctx).
            if not self.paged:
                raise ValueError("continuation (resume_ids) requires the "
                                 "paged engine")
            if len(req.prompt_ids) + len(req.resume_ids) > self.max_len - 2:
                raise ContextLengthExceeded(
                    len(req.prompt_ids) + len(req.resume_ids),
                    self.max_len - 2, self.max_len)
            if req.prompt_logprobs is not None:
                raise ValueError("continuation cannot carry prompt_logprobs "
                                 "(computed at first prefill only)")
        if req.min_tokens > 0:
            n_ban = len(self._ban_set(req))
            if n_ban > BAN_K:
                raise ValueError(
                    f"min_tokens suppression supports at most {BAN_K} stop "
                    f"tokens (eos set + stop_token_ids = {n_ban})")
        if len(req.logit_bias) > BIAS_K:
            raise ValueError(f"logit_bias supports at most {BIAS_K} entries "
                             f"(got {len(req.logit_bias)})")
        if req.repetition_penalty is not None and req.repetition_penalty <= 0:
            # The where(out>0, out/r, out*r) kernels flip logit signs for
            # r <= 0 — silently nonsensical sampling for a direct engine
            # user the HTTP layer's (0, 10] check never sees.
            raise ValueError(f"repetition_penalty must be > 0 "
                             f"(got {req.repetition_penalty})")
        if req.guided is not None:
            from aws_k8s_ansible_provisioner_tpu.serving.guided import (
                GuidedState, TokenGrammar)

            if isinstance(req.guided, TokenGrammar):
                req.guided = GuidedState(req.guided)
            elif not isinstance(req.guided, GuidedState):
                raise ValueError("guided must be a TokenGrammar or "
                                 "GuidedState (serving/guided.py)")
            if req.guided.grammar.vocab_size > self.cfg.vocab_size:
                raise ValueError(
                    f"guided grammar vocab ({req.guided.grammar.vocab_size}) "
                    f"exceeds model vocab ({self.cfg.vocab_size})")
            if req.min_tokens > 0 and req.guided.grammar.exact:
                # an exact-match grammar's final accepting state allows ONLY
                # eos; the min_tokens device ban would mask that too,
                # leaving an all--inf logits row (review r5)
                raise ValueError(
                    "min_tokens cannot combine with exact-match guided "
                    "decoding (guided_regex / guided_choice)")
        if req.prompt_logprobs is not None:
            if not (0 <= int(req.prompt_logprobs) <= LOGPROB_K):
                raise ValueError(f"prompt_logprobs must be in "
                                 f"[0, {LOGPROB_K}]")
            if self._should_chunk(req):
                raise ValueError(
                    "prompt_logprobs is not supported for prompts that "
                    "need chunked prefill (fits-in-bucket prompts only)")
        if req.lora is not None and req.lora not in self.lora_names:
            raise ValueError(f"unknown LoRA adapter {req.lora!r} "
                             f"(registered: {self.lora_names})")
        budget = self.max_len - len(req.prompt_ids) - 1
        if req.max_tokens > budget:
            req.max_tokens = max(1, budget)
        # OpenAI `seed`: the request's own seed wins; otherwise a derived
        # per-engine seed keeps unseeded sampling randomized across requests
        # while identical submission orders stay reproducible.
        req.eff_seed = (int(req.seed) & 0xffffffff) if req.seed is not None \
            else self._py_rng.getrandbits(32)
        # End-to-end deadline: the client's (capped by the server default)
        # or the server default alone; request_timeout_s <= 0 means no cap
        # and no default. Resolved to an ABSOLUTE monotonic time here so
        # queue wait counts against it — a deadline covers the request, not
        # just its decode.
        cap = float(self.serving.request_timeout_s or 0)
        d = req.deadline_s
        if d is not None and d <= 0:
            raise ValueError(f"deadline must be > 0 seconds (got {d})")
        if d is None:
            d = cap if cap > 0 else None
        elif cap > 0:
            d = min(float(d), cap)
        req.t_deadline = (req.t_submit + d) if d else 0.0
        # Admission control (r7): shed over-limit work with a structured
        # overload error BEFORE it queues — bounded queue depth first, then
        # the estimated-wait gate. Nothing was generated, so shedding is
        # always retry-safe for the caller.
        st = self.sched.stats()
        mw = float(self.serving.admission_max_wait_s or 0)
        if mw > 0:
            est = self._estimated_wait_s(st)
            if est > mw:
                self.metrics.requests_shed.inc(reason="est_wait")
                _slo.get().observe_admission(shed=True)
                _capacity.get().observe_submit(
                    tokens=max(1, req.max_tokens), shed=True)
                _flight.record("shed", req.id, reason="est_wait",
                               est_wait_s=round(est, 3))
                _flight.finish(req.id, "shed", ok=False)
                raise EngineOverloaded(
                    "est_wait",
                    f"estimated queue wait {est:.1f}s exceeds the "
                    f"admission limit {mw:.1f}s", retry_after_s=est - mw + 1)
        ctx_len = len(req.prompt_ids)
        if req.resume_ids:
            # Continuation admission: pre-populate ``generated`` with the
            # already-relayed tokens and register a preemption-style resume —
            # _paged_admit sees the ctx and the chunk walk re-prefills
            # prompt + resume as a cache rebuild (_activate(resumed=True)
            # discards the prefill draw; the next decode draw's seeded key
            # lands at the exact position the dead replica would have used).
            # All of this is installed BEFORE sched.submit publishes the id:
            # the engine thread may admit the instant it does.
            req.generated = list(req.resume_ids)
            if req.guided is not None:
                # the FSM must stand where the dead replica's stood: past
                # every already-emitted token
                for t in req.resume_ids:
                    req.guided.advance(int(t))
            ctx = list(req.prompt_ids) + list(req.resume_ids)
            ctx_len = len(ctx)
            # tpulint: disable=R5 per-key happens-before — submit() installs a key BEFORE sched.submit publishes the id, the step thread touches it only after; dict ops are GIL-atomic
            self._resume_ctx[req.id] = ctx
        with self._lock:
            self._queued[req.id] = req
            # paged admission gates on the FULL context a resume re-prefills
            ok = self.sched.submit(req.id, ctx_len,
                                   max(1, req.max_tokens
                                       - len(req.resume_ids)))
            if not ok:
                # bounded queue (scheduler-enforced so the native core and
                # Python fallback shed identically under racing submitters)
                del self._queued[req.id]
            self.metrics.queue_depth.set(self.sched.stats().queue_depth)
        if not ok:
            if req.resume_ids:
                self._resume_ctx.pop(req.id, None)
            self.metrics.requests_shed.inc(reason="queue_full")
            _slo.get().observe_admission(shed=True)
            _capacity.get().observe_submit(tokens=max(1, req.max_tokens),
                                           shed=True)
            _flight.record("shed", req.id, reason="queue_full",
                           queue_depth=st.queue_depth)
            _flight.finish(req.id, "shed", ok=False)
            raise EngineOverloaded(
                "queue_full",
                f"engine queue is full ({st.queue_depth} waiting, "
                f"limit {self.serving.max_queue_depth})",
                retry_after_s=self._estimated_wait_s(st) or 1.0)
        _slo.get().observe_admission(shed=False)
        _capacity.get().observe_submit(tokens=max(1, req.max_tokens),
                                       shed=False)
        _flight.record("queue", req.id, n_prompt=len(req.prompt_ids),
                       max_tokens=req.max_tokens)
        if req.resume_ids:
            _flight.record("failover_resume", req.id,
                           n_resume=len(req.resume_ids))
        self._work_event.set()
        return req

    def _estimated_wait_s(self, st) -> float:
        """Coarse queue-wait estimate: queued requests x recent average
        tokens per finished request / recent decode throughput. 0.0 when
        there is no throughput history yet (cold engines never shed on an
        estimate)."""
        tps = self.metrics.tokens_per_second.value()
        if tps <= 0 or st.queue_depth <= 0:
            return 0.0
        done = max(1, st.finished_total)
        avg_tokens = self.metrics.generated_tokens.total() / done
        return st.queue_depth * max(1.0, avg_tokens) / tps

    def generate(self, prompt_ids: List[int], **kw) -> Request:
        req = Request(prompt_ids=list(prompt_ids), **kw)
        return self.submit(req)

    def cancel(self, req: Request):
        """Mark a request cancelled; its slot frees on the next engine step."""
        req.cancelled = True
        self.sched.cancel(req.id)
        self._work_event.set()

    # -- graceful drain (r8) -------------------------------------------------

    def begin_drain(self, timeout_s: Optional[float] = None) -> float:
        """Stop admitting (submit sheds with reason "draining") and give
        in-flight requests until ``timeout_s`` (default
        serving.drain_timeout_s) to finish; past that, _reap_expired cancels
        stragglers through the existing deadline path — slot/pages released
        exactly once, streams finish "timeout". Idempotent: a second call
        while draining keeps the FIRST deadline (preStop + SIGTERM both
        trigger it). Returns seconds until the drain deadline."""
        now = time.monotonic()
        # begin_drain races preStop vs SIGTERM (two server threads): the
        # check-then-set below must be atomic or the second caller could
        # replace the first deadline.
        with self._lock:
            if self.draining:
                return max(0.0, self._drain_deadline - now)
            t = float(self.serving.drain_timeout_s
                      if timeout_s is None else timeout_s)
            t = max(0.0, t)
            self.draining = True
            self._drain_deadline = now + t
        self.metrics.draining.set(1)
        _flight.record("drain", None, state="begin", timeout_s=t)
        self._work_event.set()
        return t

    def end_drain(self):
        """Cancel a drain: admissions resume (operator abort / rollback)."""
        with self._lock:
            self.draining = False
            self._drain_deadline = 0.0
        self.metrics.draining.set(0)
        _flight.record("drain", None, state="end")
        self._work_event.set()

    def _effective_deadline(self, req: Request) -> float:
        """The request's own deadline tightened by the drain deadline
        (0.0 = none): drain stragglers expire through the SAME path as any
        deadline — one cancel site, exactly-once accounting."""
        d = req.t_deadline or 0.0
        if self.draining and self._drain_deadline:
            d = min(d or self._drain_deadline, self._drain_deadline)
        return d

    def _reap_expired(self):
        """Cancel every request whose end-to-end deadline has passed:
        running slots finish with "timeout" (slot + pages released through
        the one _finish path — exactly-once), the in-flight chunk walk is
        torn down, and queued requests are notified immediately instead of
        waiting to surface through admission. The drain deadline
        (begin_drain) tightens every deadline through the same path."""
        now = time.monotonic()
        for slot, r in enumerate(self.slot_req):
            if r is not None and 0 < self._effective_deadline(r) <= now:
                r.finish_reason = "timeout"
                self.metrics.deadline_expired.inc()
                _flight.record("deadline_reap", r.id, slot=slot,
                               phase="decode")
                self._finish(slot)
        st = self._chunk
        if st is not None \
                and 0 < self._effective_deadline(st["req"]) <= now:
            self._chunk = None
            req, slot = st["req"], st["slot"]
            self._release_slot_pages(slot)
            self.sched.release(slot)
            req.finish_reason = "timeout"
            self.metrics.deadline_expired.inc()
            self.metrics.mark_request("timeout", now - req.t_submit)
            _flight.record("deadline_reap", req.id, slot=slot,
                           phase="prefill_chunk")
            _flight.finish(req.id, "timeout", ok=False)
            req.out_queue.put(None)
        expired = []
        with self._lock:
            for rid, r in list(self._queued.items()):
                if 0 < self._effective_deadline(r) <= now:
                    expired.append(r)
                    del self._queued[rid]
        for r in expired:
            # the scheduler entry drains later as a "cancelled" pop; the
            # client is answered NOW with the real reason
            self.sched.cancel(r.id)
            if self.paged:
                self._resume_ctx.pop(r.id, None)
            r.finish_reason = "timeout"
            self.metrics.deadline_expired.inc()
            self.metrics.mark_request("timeout", now - r.t_submit)
            _flight.record("deadline_reap", r.id, phase="queued")
            _flight.finish(r.id, "timeout", ok=False)
            r.out_queue.put(None)
        if expired:
            self.metrics.queue_depth.set(self.sched.stats().queue_depth)

    def _relieve_admission_pressure(self) -> bool:
        """Paged admission wedged on page starvation (queue head can't be
        placed although a slot is free): after admission_preempt_after_s,
        preempt the LOWEST-progress running request — least recompute lost,
        requeued at the BACK so the starved head takes the freed pages —
        instead of letting admission hang on requests that may hold their
        pages for minutes. Returns whether a victim was preempted."""
        wait = float(self.serving.admission_preempt_after_s or 0)
        st = self.sched.stats()
        active = self._active_slots()
        if (wait <= 0 or st.queue_depth == 0
                or st.active_slots >= st.num_slots or not active):
            self._admission_blocked_since = 0.0
            return False
        now = time.monotonic()
        if not self._admission_blocked_since:
            self._admission_blocked_since = now
            return False
        if now - self._admission_blocked_since < wait:
            return False
        victim = min(active, key=lambda s: (len(self.slot_req[s].generated),
                                            -self._admit_seq[s]))
        self.metrics.admission_preemptions.inc()
        self._preempt(victim, front=False)
        self._admission_blocked_since = now
        return True

    def step(self) -> bool:
        """One scheduling step. Priority: advance a chunked prefill (with one
        decode step interleaved between chunks), else admit waiting prompts
        (batched into one dispatch), else decode. Returns whether any work was
        done."""
        ch = _chaos.get()
        if ch.enabled:
            ch.on_engine_step(self)
        # reap cancelled slots first so disconnected clients free capacity
        for slot, r in enumerate(self.slot_req):
            if r is not None and r.cancelled:
                r.finish_reason = "cancelled"
                _flight.record("cancel_reap", r.id, slot=slot)
                self._finish(slot)
        # then expired deadlines — every blocking wait in the pipeline keys
        # off the same t_deadline, so enforcement here (between dispatches)
        # is what turns a deadline into released capacity
        self._reap_expired()
        # A long prompt mid-chunking: alternate chunk and decode dispatches so
        # in-flight streams keep progressing during the prefill (the whole
        # point of chunking — VERDICT r1 missing #4).
        if self._chunk is not None:
            if self._chunk.get("mixed"):
                # Ragged mixed walk: every chunk dispatch IS a decode
                # dispatch for the whole batch (one program serves both),
                # so the chunk/decode alternation — and the horizon-1
                # garbage-row caveat it exists for — doesn't apply.
                self._advance_chunk()
                return True
            if self._chunk_yield and self._active_slots():
                self._chunk_yield = False
                # horizon must be 1 while chunking: the decode program writes
                # a k/v row for EVERY slot at its current length — for the
                # chunking slot that row is garbage at offset `off`, which the
                # next chunk overwrites only if the write stays within the
                # next chunk's span.
                self._do_decode(max_horizon=1)
                return True
            self._advance_chunk()
            self._chunk_yield = True
            return True
        # Prefill/decode fairness floor (VERDICT r3 weak #5): prefill
        # priority means decode runs only when nothing can be admitted, so a
        # sustained admission stream can hold in-flight streams at a token
        # trickle indefinitely. After prefill_fairness consecutive prefill
        # dispatches with decode work pending, force ONE full-horizon decode
        # dispatch before admitting more.
        fair = max(0, self.serving.prefill_fairness)
        if (fair and self._prefill_streak >= fair and self._active_slots()
                and self.sched.stats().queue_depth > 0):
            self._prefill_streak = 0
            self._do_decode(fair_horizon=True)
            return True
        # Pipelined decode: settle the in-flight dispatch (its deferred
        # emits, possible finishes) BEFORE admission can reuse a freed slot
        # or start a chunk — slot reuse under unfetched tokens would
        # mis-route the deferred emits to the new request. With the ragged
        # mixed path on, admission under an in-flight dispatch is forced
        # onto the chunk walk (below), which keeps the carry valid and
        # never activates a slot before the dispatch settles — so the
        # pipeline stays open across admissions (the whole point of the
        # ragged program; deferred emits for a freed slot are discarded by
        # the slot_req-is-None guard in _decode_fetch, never mis-routed,
        # because _activate only runs after the in-flight fetch).
        if (self._inflight is not None
                and self.sched.stats().queue_depth > 0
                and not self._ragged_on()):
            self._drain_decode_pipeline("prefill")
        # Admission decisions come from the runtime core (FCFS; skips
        # cancelled-in-queue requests, surfacing them for client notification).
        # Bucket-fitting prompts batch into one dispatch; a chunk-needing
        # prompt ends the batch and starts the chunked path.
        batch: List = []
        chunk_next = None
        while len(batch) < max(1, self.serving.max_prefill_batch):
            # Paged admission is gated by the allocators' headroom (free +
            # evictable pages) — capacity scales with ACTUAL lengths, the
            # vLLM on-demand-block behavior (VERDICT r2 missing #2). With dp
            # groups the gate is the BEST group's headroom (the scheduler
            # picks the slot, not the group): when it hands a slot from a
            # fuller group, _paged_admit fails and the requeue below retries
            # — the freed slot rotates to the back of the free deque, so
            # retries walk onto other groups' slots.
            action = self.sched.pop_admission(
                max(a.free_pages for a in self.allocators)
                if self.paged else None)
            if action is None:
                break
            if action[0] == "cancelled":
                with self._lock:
                    cand = self._queued.pop(action[1], None)
                if self.paged:
                    self._resume_ctx.pop(action[1], None)
                self.metrics.queue_depth.set(self.sched.stats().queue_depth)
                if cand is not None:
                    cand.finish_reason = "cancelled"
                    _flight.record("cancel_reap", cand.id, phase="queued")
                    _flight.finish(cand.id, "cancelled", ok=False)
                    cand.out_queue.put(None)
                continue
            _, rid, slot = action
            with self._lock:
                req = self._queued.pop(rid, None)
            self.metrics.queue_depth.set(self.sched.stats().queue_depth)
            if req is None:  # should not happen; free the slot defensively
                self.sched.release(slot)
                continue
            if not req.t_prefill_start:
                req.t_prefill_start = time.monotonic()
            if self.paged:
                isolated = (not batch
                            and self.sched.stats().queue_depth == 0)
                prep = self._paged_admit(req, slot, isolated)
                if prep is None:
                    # evictable pages vanished between the admission gate
                    # and allocation (another admit this round took them):
                    # requeue at the front and stop admitting this step
                    self.sched.release(slot)
                    with self._lock:
                        self._queued[rid] = req
                    ids_q = self._resume_ctx.get(rid, req.prompt_ids)
                    self.sched.submit_front(
                        rid, len(ids_q),
                        max(1, req.max_tokens - len(req.generated)))
                    break
                ids, off, resumed = prep
                # prefix reuse and resumes walk the chunk program from the
                # reuse offset; fresh bucket-sized prompts join the batch.
                # With a dispatch in flight on the ragged path, EVERY
                # admission takes the chunk walk: the mixed program prefills
                # it without draining the pipeline, where a batch prefill
                # would activate slots under the in-flight carry.
                if (off > 0 or resumed or self._should_chunk(req)
                        or (self._ragged_on()
                            and self._inflight is not None)):
                    chunk_next = (req, slot, ("paged", ids, off, resumed))
                    break
                batch.append((req, slot))
                continue
            # Prefix reuse goes through the (serialized) chunk program, so
            # only consult the cache for an ISOLATED arrival — empty batch
            # and nothing else waiting. Under a burst, batched prefill wins:
            # taking the chunk path per request would serialize the whole
            # burst into one ~RTT dispatch each, costing far more than the
            # prefix recompute it saves at bucket sizes (the isolated case —
            # a follow-up chat turn re-sending its history — is where the
            # rows are long and reuse pays). The consult happens BEFORE this
            # slot's retained tokens are cleared so the request may match its
            # own just-freed slot (the saturated-engine follow-up-turn case:
            # rows already in place, reuse is free).
            pref = None
            if not batch and self.sched.stats().queue_depth == 0:
                pref = self._find_prefix(req, slot)
            # The slot just assigned will be overwritten by this admission
            # round's prefill — its retained rows must stop matching as a
            # prefix source from here on, or a later request in this same
            # loop could copy rows the batch prefill is about to clobber.
            self._slot_tokens[slot] = ()
            if self._should_chunk(req) or pref is not None:
                chunk_next = (req, slot, pref)
                break
            batch.append((req, slot))
        if batch or chunk_next is not None:
            self._admission_blocked_since = 0.0
        elif self.paged:
            # nothing admitted although work waits: if a slot is free, the
            # head is page-starved — degrade by policy, don't wedge
            if self._relieve_admission_pressure():
                # The preemption IS this step's work: when the victim was the
                # only active slot, falling through would return False with
                # the queue non-empty, and every caller that treats a False
                # step as quiescence (run_forever's idle sleep, test drivers)
                # would strand the requeued request. The freed pages let the
                # NEXT step admit the starved head.
                return True
        if batch:
            self._prefill_streak += 1
            try:
                if len(batch) == 1:
                    self._do_prefill(*batch[0])
                else:
                    self._do_prefill_batch(batch)
            except Exception:
                # Slots were assigned by the scheduler but slot_req[slot] is
                # only set on success — release them (and their pages) and
                # notify the clients here, or the capacity leaks and the
                # waiters hang (run_forever's _fail_all can't see either).
                for req, slot in batch:
                    self._release_slot_pages(slot)
                    self.sched.release(slot)
                    req.finish_reason = "error"
                    self.metrics.mark_request("error", 0.0)
                    _flight.finish(req.id, "error", ok=False,
                                   phase="prefill_batch")
                    req.out_queue.put(None)
                if chunk_next is not None:
                    req, slot, _ = chunk_next
                    self._release_slot_pages(slot)
                    self.sched.release(slot)
                    req.finish_reason = "error"
                    self.metrics.mark_request("error", 0.0)
                    _flight.finish(req.id, "error", ok=False,
                                   phase="prefill_batch")
                    req.out_queue.put(None)
                raise
            if chunk_next is not None:  # chunking starts next step
                self._start_chunk(*chunk_next)
                self._chunk_yield = False
            return True
        if chunk_next is not None:
            self._start_chunk(*chunk_next)
            self._advance_chunk()
            self._chunk_yield = True
            return True
        if self._active_slots():
            self._do_decode()
            return True
        if self._inflight is not None:
            # cancel/deadline reaps emptied the batch with a dispatch still
            # in flight: settle it (all its emits discard) so nothing stays
            # enqueued on the device across idle or drain periods
            self._drain_decode_pipeline()
            return True
        return False

    def _emit(self, slot: int, token: int, lp=None):
        """Record one generated token for a slot; handle stop conditions."""
        req = self.slot_req[slot]
        if req.guided is not None:
            # advance the grammar FSM past the emitted token; the NEXT
            # dispatch's mask comes from the new state. A rejection (only
            # possible when the vocab can't spell any continuation) flips
            # the state to dead = eos/ws-only, forcing a clean finish.
            req.guided.advance(token)
        req.generated.append(token)
        if req.logprobs is not None:
            # pad with None if a path couldn't supply logprobs (spec decode
            # is gated off for logprob requests, so this stays aligned)
            req.logprob_data.append(lp)
        self.last_token[slot] = token
        self.metrics.generated_tokens.inc()
        if req.stream:
            req.out_queue.put(token)

        hit_eos = ((token in self._eos_set and not req.ignore_eos)
                   or token in req.stop_token_ids) \
            and len(req.generated) > req.min_tokens
        out_of_budget = (len(req.generated) >= req.max_tokens
                         or self.lengths[slot] + 1 >= self.max_len)
        if hit_eos or out_of_budget:
            req.finish_reason = "stop" if hit_eos else "length"
            self._finish(slot)

    def _finish(self, slot: int):
        req = self.slot_req[slot]
        req.t_done = time.monotonic()
        status = ("success" if req.finish_reason in ("stop", "length")
                  else req.finish_reason or "success")
        self.metrics.mark_request(status, req.t_done - req.t_submit,
                                  trace_id=req.trace_id or None)
        # Terminal flight event: OK finishes free the timeline; anomalous
        # ones (timeout/error/cancelled) snapshot it for /debug/flight and
        # the spool (drop-on-overflow — never blocks this thread).
        _flight.finish(req.id, reason=req.finish_reason or "stop",
                       ok=(status == "success"), slot=slot,
                       n_generated=len(req.generated))
        if self.paged:
            # Index the GENERATED pages too, so a follow-up turn whose prompt
            # contains this response prefix-hits past the original prompt
            # (ADVICE r3: only _activate indexed pages, so the generated
            # region always re-prefilled). Same pending-row cap as
            # preemption: the last emitted token's K/V row is written by the
            # NEXT dispatch, which never came — cap at len(ids) - 1.
            ids = req.prompt_ids + req.generated
            self._index_prompt_pages(slot, ids, n_valid=len(ids) - 1)
        self.slot_req[slot] = None
        # Dense: keep the freed slot's length — decode dispatches write a
        # scratch K/V row for EVERY slot at its current length, so a zeroed
        # length would let that garbage land on row 0, corrupting the
        # retained prompt rows the prefix cache reuses. (Paged: pages are
        # RELEASED below — indexed ones stay prefix-matchable in the
        # evictable LRU — and the zeroed table points idle writes at the
        # scratch page, so the length resets to 0 there.)
        # NOTE: a finish does NOT bump _carry_gen — an in-flight pipelined
        # dispatch keeps decoding the freed slot as discardable garbage
        # (scratch-table writes, emits skipped); only a REUSE (_activate)
        # invalidates the device carry. The cleared sampling rows do dirty
        # the operand cache for the next upload.
        self._op_dirty_sampling = True
        self.temps[slot] = 0.0
        self.pres_pens[slot] = 0.0
        self.freq_pens[slot] = 0.0
        self.rep_pens[slot] = 1.0
        self.ban_until[slot] = 0
        self.bias_ids[slot, :] = 2**31 - 1
        self.bias_vals[slot, :] = 0.0
        self._bias_n[slot] = 0
        self.lora_idx[slot] = 0
        self._release_slot_pages(slot)
        self.sched.release(slot)
        self.metrics.active_requests.set(len(self._active_slots()))
        req.out_queue.put(None)  # sentinel: done

    # -- loop ---------------------------------------------------------------

    def run_forever(self, stop: threading.Event):
        """Engine thread body: step until stopped, sleeping when idle.

        A step failure (XLA error, OOM) must not silently kill the loop: every
        in-flight and queued request is failed loudly (clients get their
        sentinel instead of hanging), the error is recorded for /health, and
        the loop keeps serving subsequent requests.
        """
        import logging

        log = logging.getLogger(__name__)
        wd = threading.Thread(target=self._watchdog_loop, args=(stop,),
                              daemon=True, name="engine-watchdog")
        wd.start()
        while not stop.is_set():
            self.last_step_start = time.monotonic()
            try:
                did_work = self.step()
            # tpulint: disable=R3 fail-loud catch-all — _fail_all fails every in-flight request with its sentinel, /health records the error, loop keeps serving
            except Exception as e:
                log.exception("engine step failed; failing in-flight requests")
                self.last_error = f"{type(e).__name__}: {e}"
                self._fail_all(self.last_error)
                did_work = False
            self.last_step_start = 0.0
            with self._lock:
                self._stall_abort = False   # the aborted step has unwound
            if not did_work:
                self._work_event.wait(timeout=0.05)
                self._work_event.clear()

    def _watchdog_loop(self, stop: threading.Event):
        """Stall watchdog (r7): when a step executes past STALL_AFTER_S,
        arm the abort flag a host-observable stall (chaos-injected or any
        cooperative wait) checks — the step raises, run_forever fails the
        AFFECTED requests, and the process keeps serving. A truly wedged
        device call never sees the flag; for that class /healthz stays 503
        "stalled" until the K8s liveness restart (the pre-r7 behavior)."""
        while not stop.is_set():
            if self.stalled_for_s > 0:
                with self._lock:
                    armed = not self._stall_abort
                    self._stall_abort = True
                if armed:
                    self.metrics.watchdog_stalls.inc()
                    _flight.record("watchdog_stall", None,
                                   stalled_for_s=round(self.stalled_for_s, 3))
            stop.wait(min(1.0, max(0.05, self.STALL_AFTER_S / 4)))

    last_error: str = ""
    # monotonic timestamp of the step currently executing (0.0 = idle):
    # /health derives a "stalled" status from it — a wedged device dispatch
    # (hung tunnel, driver fault) hangs INSIDE step() and would otherwise
    # look healthy forever, since run_forever never returns to record an
    # error (failure-detection beyond the reference's set -e, SURVEY.md §5).
    last_step_start: float = 0.0
    STALL_AFTER_S: float = 120.0

    @property
    def stalled_for_s(self) -> float:
        """Seconds the current step has been executing past the stall
        threshold (0.0 = healthy/idle)."""
        t0 = self.last_step_start
        if not t0:
            return 0.0
        dt = time.monotonic() - t0
        return dt if dt >= self.STALL_AFTER_S else 0.0

    def _fail_all(self, reason: str):
        _flight.record("fail_all", None, reason=reason)
        # Discard the in-flight pipelined decode outright: its requests are
        # failed below through the normal slot teardown (exactly-once page/
        # slot release via _finish), and fetching a dispatch that may BE the
        # failure (pipeline_fetch_error, transfer fault) would re-raise.
        if self._inflight is not None:
            _metrics.pipeline.drains.inc(reason="fail")
        self._inflight = None
        self._pipe_carry = None
        self.metrics.pipeline_depth.set(0.0)
        if self._chunk is not None:  # fail the half-prefilled request too
            st, self._chunk = self._chunk, None
            self._release_slot_pages(st["slot"])
            self.sched.release(st["slot"])
            st["req"].finish_reason = "error"
            self.metrics.mark_request("error", 0.0)
            _flight.finish(st["req"].id, "error", ok=False, detail=reason)
            st["req"].out_queue.put(None)
        if self.paged:
            self._resume_ctx.clear()   # queued resumes are failed below
        for slot, r in enumerate(self.slot_req):
            if r is not None:
                r.finish_reason = "error"
                self._finish(slot)
        with self._lock:
            queued, self._queued = self._queued, {}
        for r in queued.values():
            self.sched.cancel(r.id)
            r.finish_reason = "error"
            self.metrics.mark_request("error", 0.0)
            _flight.finish(r.id, "error", ok=False, detail=reason)
            r.out_queue.put(None)
        # Drain the scheduler's cancelled-in-queue notifications so its queue
        # empties (the Request objects were already notified above). A request
        # submitted AFTER the failure may interleave here and surface as an
        # admission: it is healthy work, not part of the failure — requeue it
        # for the next step and stop draining (everything behind it is new).
        while True:
            action = self.sched.pop_admission()
            if action is None:
                break
            if action[0] == "admit":
                _, rid, slot = action
                self.sched.release(slot)
                with self._lock:
                    r = self._queued.get(rid)
                if r is not None:
                    self.sched.submit(rid, len(r.prompt_ids), r.max_tokens)
                break
        self.metrics.queue_depth.set(self.sched.stats().queue_depth)
