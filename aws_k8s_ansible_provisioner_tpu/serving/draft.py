"""Draft-model speculative decoding: a small LM proposes, the target verifies.

The vLLM draft-worker equivalent (SURVEY.md §2.2 row 1; VERDICT r4 next #7):
prompt-lookup speculation (engine.py `_propose_drafts`) only fires on
repetitive continuations, while a draft model proposes on EVERY step — the
standard small-model/large-model pairing (e.g. Qwen3-0.6B drafting for
Qwen3-8B). TPU-first economics: decode is HBM-bandwidth-bound, so a draft at
~1/10 the target's bytes adds ~10% bandwidth per round while the multi-query
verify answers all K drafts from ONE target cache stream — accepted drafts
are nearly free tokens.

No new jitted programs: the draft REUSES the engine's compiled step family —
``decode_steps`` (greedy, horizon=spec_k) for the autoregressive rollout and
``spec_decode_step`` (R=spec_k+1, argmax side only) for multi-token
catch-up after plain-path dispatches advanced the target past the draft.

Cache-coherence design (the part draft speculation usually gets wrong):

- ``lens[slot]`` counts rows of the draft cache holding TRUE context K/V —
  the next write position. Steady state is ``engine.lengths - lens == 1``
  (the newest emitted token's K/V rides the next draft dispatch, exactly
  like the target's own cache).
- A proposal dispatch feeds the newest emitted token (``engine.last_token``)
  at position ``lens`` and greedily rolls K tokens, writing K rows. The
  accepted prefix of those rows is ALREADY-correct context (greedy draft
  rows are the drafts' own K/V), so after the verify emits m drafts + 1
  correction the sync is just ``lens += emitted`` — no rollback copies.
- Rejected-draft rows and catch-up padding rows are garbage BEYOND ``lens``;
  every position is rewritten when its true token is processed before any
  query can attend it (the engine's standard surplus-write invariant,
  engine.py `decode_steps` docstring).
- Slots the draft cannot cheaply track (chunked prefills, preemption
  resumes) turn ``stale`` and simply stop proposing — per-slot degradation,
  never engine-wide (VERDICT r3 weak #4 precedent).

The engine caps plain-path horizons at spec_k + 1 while a draft is attached
so the catch-up gap always fits one R-wide dispatch.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from aws_k8s_ansible_provisioner_tpu.serving import kv_cache as kvc


class DraftModel:
    """Holds the draft network + its per-slot KV cache and sync state."""

    def __init__(self, cfg, params, num_slots: int, max_len: int, dtype):
        self.cfg = cfg
        self.params = params
        self.cache = kvc.init_cache(cfg, num_slots, max_len, dtype)
        self.num_slots = num_slots
        self.max_len = max_len
        # rows of TRUE context K/V per slot (== next write position)
        self.lens = np.zeros(num_slots, np.int32)
        # chunked/resumed slots: cache can't be cheaply rebuilt -> no drafts
        self.stale = np.zeros(num_slots, bool)

    # -- admission sync ------------------------------------------------------

    def prefill(self, engine, tokens: np.ndarray, true_lens: np.ndarray,
                slots: np.ndarray) -> None:
        """Mirror a (batched) target prefill into the draft cache.

        Reuses the engine's already-built padded token arrays, so the draft
        costs ONE extra dispatch per admission batch. The sampled tokens are
        discarded — only the K/V writes matter."""
        from aws_k8s_ansible_provisioner_tpu.serving.engine import (
            prefill_batch_step)

        n = tokens.shape[0]
        out = prefill_batch_step(
            self.cfg, self.params, self.cache, jnp.asarray(tokens),
            jnp.asarray(true_lens), jnp.asarray(slots), engine._next_rng(),
            jnp.zeros(n, jnp.float32), jnp.zeros(n, jnp.int32),
            jnp.ones(n, jnp.float32))
        self.cache = out[0]
        for i in range(n):
            s = int(slots[i])
            if s < self.num_slots:
                self.lens[s] = int(true_lens[i])
                self.stale[s] = False

    def mark_stale(self, slot: int) -> None:
        self.stale[slot] = True

    # -- per-round proposal --------------------------------------------------

    def propose(self, engine, eligible: List[int],
                K: int) -> Optional[Tuple[np.ndarray, dict]]:
        """Return (drafts [num_slots, K], {slot: K}) or None.

        1. catch-up: slots whose gap to the target exceeds 1 (a plain-path
           dispatch advanced them) teacher-force the missed tokens through
           the draft via one R-wide argmax dispatch; they propose NEXT round.
        2. rollout: one fused greedy ``decode_steps`` over the whole slot
           axis proposes K tokens for every up-to-date slot.

        Carry-generation handoff contract (ISSUE 16): the engine reaches a
        spec round by SETTLING any in-flight pipelined dispatch (fetch +
        emit, no drain) rather than draining it, so by the time propose()
        reads the host mirrors (``engine.lengths``, ``engine.last_token``)
        they are exact — lazily synced, never stale. The assert makes a
        violated handoff fail loudly at the proposal site instead of as a
        silent off-by-one in the draft cache.
        """
        from aws_k8s_ansible_provisioner_tpu.serving.engine import (
            decode_steps, spec_decode_step)

        assert getattr(engine, "_inflight", None) is None, (
            "draft.propose() with a dispatch still in flight — the engine "
            "must settle the pipeline before a spec round (host mirrors "
            "would be stale)")

        R = K + 1
        gaps = {s: int(engine.lengths[s]) - int(self.lens[s])
                for s in eligible if not self.stale[s]}
        behind = [s for s, g in gaps.items() if 1 < g <= self.max_len]
        if behind:
            self._catch_up(engine, behind, R)
            gaps = {s: int(engine.lengths[s]) - int(self.lens[s])
                    for s in gaps}
        ready = [s for s, g in gaps.items()
                 if g == 1 and int(self.lens[s]) + K < self.max_len]
        if not ready:
            return None
        self.cache, _, out, _, _ = decode_steps(
            self.cfg, K, self.params, self.cache,
            jnp.asarray(engine.last_token), jnp.asarray(self.lens),
            engine._next_rng(),
            jnp.zeros(self.num_slots, jnp.float32),       # greedy rollout
            jnp.zeros(self.num_slots, jnp.int32),
            jnp.ones(self.num_slots, jnp.float32))
        out = np.asarray(out)                              # [K, B]
        drafts = np.zeros((self.num_slots, K), np.int32)
        proposed = {}
        for s in ready:
            drafts[s] = out[:, s]
            proposed[s] = K
        # non-ready rows wrote garbage K/V at THEIR lens..lens+K-1: future
        # positions, rewritten before any query attends them (surplus-write
        # invariant) — their lens stays put, so nothing is lost.
        return drafts, proposed

    def _catch_up(self, engine, slots: List[int], R: int) -> None:
        """Teacher-force up to R tokens of target-emitted context the draft
        missed. Uses the draft-model spec program purely for its multi-row
        K/V writes (argmax output discarded)."""
        from aws_k8s_ansible_provisioner_tpu.serving.engine import (
            spec_decode_step)

        tokens = np.zeros((self.num_slots, R), np.int32)
        adv = np.zeros(self.num_slots, np.int32)
        for s in slots:
            req = engine.slot_req[s]
            if req is None:
                continue
            ctx = req.prompt_ids + req.generated
            lo = int(self.lens[s])
            # leave the newest token for the proposal dispatch (gap -> 1)
            cu = ctx[lo:int(engine.lengths[s]) - 1][:R]
            if not cu:
                continue
            tokens[s, :len(cu)] = cu
            tokens[s, len(cu):] = cu[-1]                  # pad: surplus rows
            adv[s] = len(cu)
        if not adv.any():
            return
        out = spec_decode_step(
            self.cfg, R, self.params, self.cache, jnp.asarray(tokens),
            jnp.asarray(self.lens), engine._next_rng(),
            jnp.zeros(self.num_slots, jnp.float32),
            jnp.zeros(self.num_slots, jnp.int32),
            jnp.ones(self.num_slots, jnp.float32))
        self.cache = out[0]
        self.lens += adv

    # -- post-verify sync ----------------------------------------------------

    def note_emitted(self, slot: int, n: int) -> None:
        """After a verify emitted ``n`` tokens for a drafted slot: the first
        n of this round's rollout rows (newest token + accepted drafts) are
        now true context."""
        self.lens[slot] = min(self.lens[slot] + n, self.max_len)
