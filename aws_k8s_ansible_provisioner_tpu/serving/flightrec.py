"""Black-box flight recorder for the serving path (per-request timelines).

When a request dies today — deadline expiry, watchdog stall, shed, failover —
all the stack keeps is a counter increment; the *why* is gone. This module is
the serving path's black box: a lock-light, bounded ring of structured events
(admit, queue, prefill-chunk, pipeline dispatch/fetch, preempt, drain, shed,
deadline-reap, failover-resume, chaos-fault) stamped with ``monotonic_ns``
plus the request's trace/span ids, and — on any anomalous terminal edge — a
snapshot of that request's complete timeline into a capped on-disk JSONL
spool. ``/debug/flight/<request_id>`` and ``/debug/events?last=N`` serve the
snapshots and the live ring.

The contract is the PR 5 span exporter's, verbatim: recording is
drop-on-overflow and can NEVER block or fail a request. The request path only
ever appends to a bounded deque / dict under a short lock and ``put_nowait``s
snapshots onto a bounded queue; everything that can block (the spool write, a
chaos-injected disk fault) happens on the background writer thread, and every
failure converts to ``tpu_serve_flight_drops_total`` instead of backpressure.

Event timestamps are ``time.monotonic_ns()`` (tpulint R1: duration math never
touches the wall clock); dumps add a ``t_unix_ns`` per event through
``tracing.mono_ns`` so timelines line up with the PR 5 spans in Tempo.
"""

from __future__ import annotations

import collections
import json
import os
import queue
import threading
import time
from typing import Deque, Dict, List, Optional

from aws_k8s_ansible_provisioner_tpu.serving import chaos as _chaos
from aws_k8s_ansible_provisioner_tpu.serving import tracing as _tracing
from aws_k8s_ansible_provisioner_tpu.serving.metrics import Counter, Registry

# Terminal reasons that do NOT trigger a dump ("" = still unset at finish).
OK_REASONS = ("stop", "length", "")


class FlightMetrics:
    """The recorder's own counters, rendered by BOTH the engine's and the
    router's /metrics routes (the subsystem is shared; the drop counter is
    the one signal that distinguishes 'spool outage' from 'recorder off')."""

    def __init__(self):
        self.registry = Registry()
        r = self.registry
        self.events = r.register(Counter(
            "tpu_serve_flight_events_total",
            "Flight-recorder events appended to the ring"))
        self.drops = r.register(Counter(
            "tpu_serve_flight_drops_total",
            "Flight-recorder events/dumps dropped instead of recorded, by "
            "reason (timeline_overflow / request_overflow = per-request "
            "bounds; spool_queue_full = writer backlog; dump_error = spool "
            "write failed — requests are never stalled either way)",
            ("reason",)))
        self.dumps = r.register(Counter(
            "tpu_serve_flight_dumps_total",
            "Anomaly timelines snapshotted (in-memory + spool attempt)"))
        self.dump_failures = r.register(Counter(
            "tpu_serve_flight_dump_failures_total",
            "Failed spool writes (each drops its dump, counted above)"))


# Process-wide: the recorder(s) and both /metrics routes share these.
metrics = FlightMetrics()


def _evt_dict(evt: tuple) -> dict:
    """Render one ring event tuple as a JSON-safe dict."""
    t_ns, etype, rid, data = evt
    d = {"t_mono_ns": t_ns,
         "t_unix_ns": _tracing.mono_ns(t_ns / 1e9),
         "type": etype}
    if rid is not None:
        d["request_id"] = rid
    if data:
        d.update(data)
    return d


class FlightRecorder:
    """Bounded ring + per-request timelines + background JSONL spool writer.

    Single instance per process (module singleton below); the engine thread
    and server handler threads all record through it. The ring is a plain
    ``deque(maxlen=...)`` (GIL-atomic appends); the per-request timeline map
    takes a short lock because two threads (engine + server) may touch the
    same request's timeline.
    """

    def __init__(self, spool_dir: str = "", enabled: bool = True,
                 ring_cap: int = 4096, max_requests: int = 512,
                 max_events_per_request: int = 256, max_snapshots: int = 64,
                 spool_max_bytes: int = 16 * 1024 * 1024,
                 queue_max: int = 256):
        self.enabled = bool(enabled)
        self.spool_dir = str(spool_dir or "")
        self.spool_max_bytes = int(spool_max_bytes)
        self.max_requests = int(max_requests)
        self.max_events_per_request = int(max_events_per_request)
        self.max_snapshots = int(max_snapshots)
        self._ring: Deque[tuple] = collections.deque(maxlen=max(16, ring_cap))
        self._lock = threading.Lock()
        # rid -> [event, ...] for requests not yet finished (lock-guarded:
        # the engine thread and a server handler thread may append to the
        # same request's timeline)
        self._timelines: Dict[object, List[tuple]] = {}
        # rid -> dump dict for the last max_snapshots anomalies (lock-guarded)
        self._snapshots: "collections.OrderedDict" = collections.OrderedDict()
        self._last_anomaly: Optional[dict] = None
        self._q: "queue.Queue[Optional[dict]]" = queue.Queue(
            maxsize=max(1, queue_max))
        self._stop = threading.Event()
        self._busy = False          # writer holds a dump (flush() polls)
        self._thread: Optional[threading.Thread] = None
        if self.enabled:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="flight-spool")
            self._thread.start()

    # -- request-path side ---------------------------------------------------

    def record(self, etype: str, rid=None, **data):
        """Append one event. Never blocks, never raises out of bounds —
        overflow drops the event and counts it."""
        if not self.enabled:
            return
        evt = (time.monotonic_ns(), etype, rid, data or None)
        self._ring.append(evt)
        metrics.events.inc()
        if rid is None:
            return
        with self._lock:
            tl = self._timelines.get(rid)
            if tl is None:
                if len(self._timelines) >= self.max_requests:
                    metrics.drops.inc(reason="request_overflow")
                    return
                tl = []
                self._timelines[rid] = tl
            if len(tl) >= self.max_events_per_request:
                metrics.drops.inc(reason="timeline_overflow")
                return
            tl.append(evt)

    def finish(self, rid, reason: str = "", ok: Optional[bool] = None,
               **data):
        """Terminal edge for ``rid``: records the final event and — when the
        edge is anomalous (``ok=False``, or ``reason`` outside OK_REASONS) —
        snapshots the request's complete timeline for /debug/flight and the
        spool. OK finishes just free the timeline."""
        if not self.enabled:
            return
        if ok is None:
            ok = reason in OK_REASONS
        self.record("finish", rid, reason=reason or "stop", ok=bool(ok),
                    **data)
        with self._lock:
            tl = self._timelines.pop(rid, None)
        if ok:
            return
        dump = {
            "request_id": rid,
            "reason": reason,
            "t_unix_ns": _tracing.wall_clock_ns(),
            "events": [_evt_dict(e) for e in (tl or [])],
        }
        for e in dump["events"]:    # hoist trace ids to the top level
            if "trace_id" in e:
                dump["trace_id"] = e["trace_id"]
                dump["span_id"] = e.get("span_id", "")
                break
        metrics.dumps.inc()
        with self._lock:
            self._snapshots[rid] = dump
            while len(self._snapshots) > self.max_snapshots:
                self._snapshots.popitem(last=False)
            self._last_anomaly = {"request_id": rid, "reason": reason,
                                  "t_unix_ns": dump["t_unix_ns"]}
        if not self.spool_dir:
            return
        try:
            self._q.put_nowait(dump)
        except queue.Full:
            metrics.drops.inc(reason="spool_queue_full")

    # -- read side (debug endpoints, /healthz) -------------------------------

    def tail(self, n: int = 100) -> List[dict]:
        """The last ``n`` ring events, oldest first (/debug/events)."""
        evts = list(self._ring)
        return [_evt_dict(e) for e in evts[-max(0, int(n)):]]

    def dump_for(self, rid) -> Optional[dict]:
        """The anomaly snapshot for ``rid`` (/debug/flight/<id>), or the
        LIVE timeline for a still-running request, else None."""
        with self._lock:
            d = self._snapshots.get(rid)
            if d is not None:
                return d
            tl = self._timelines.get(rid)
            if tl is not None:
                return {"request_id": rid, "reason": "", "live": True,
                        "events": [_evt_dict(e) for e in tl]}
        return None

    def summary(self) -> dict:
        """Compact health view (/healthz, router fleet aggregation)."""
        with self._lock:
            last = dict(self._last_anomaly) if self._last_anomaly else None
        return {
            "enabled": self.enabled,
            "events_total": metrics.events.total(),
            "dumps_total": metrics.dumps.total(),
            "drops_total": metrics.drops.total(),
            "last_anomaly": last,
        }

    # -- worker side ---------------------------------------------------------

    def _spool_path(self) -> str:
        return os.path.join(self.spool_dir, "flight.jsonl")

    def _run(self):
        while not self._stop.is_set():
            try:
                dump = self._q.get(timeout=0.25)
            except queue.Empty:
                continue
            if dump is None:        # shutdown sentinel
                break
            self._busy = True
            try:
                self._write(dump)
            # tpulint: disable=R3 drop-by-design — a full disk costs black-box dumps, never requests; failures are counted below
            except Exception:
                metrics.dump_failures.inc()
                metrics.drops.inc(reason="dump_error")
            finally:
                self._busy = False

    def _write(self, dump: dict):
        ch = _chaos.get()
        if ch.enabled:
            ch.on_flight_dump()     # fault point: disk full / hang
        path = self._spool_path()
        os.makedirs(self.spool_dir, exist_ok=True)
        # capped spool: roll the file aside once it exceeds the budget (one
        # generation of history beats silent unbounded growth)
        try:
            if os.path.getsize(path) > self.spool_max_bytes:
                os.replace(path, path + ".1")
        except OSError:
            pass
        line = json.dumps(dump, separators=(",", ":"), default=str)
        with open(path, "a", encoding="utf-8") as f:
            f.write(line + "\n")

    # -- lifecycle -----------------------------------------------------------

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Best-effort wait until the spool queue drains (tests only)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._q.empty() and not self._busy:
                return True
            time.sleep(0.01)
        return False

    def shutdown(self, timeout_s: float = 2.0):
        self.flush(timeout_s)
        self._stop.set()
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)


# ---------------------------------------------------------------------------
# Module-level wiring: one recorder per process, helpers the hot paths call.
# ---------------------------------------------------------------------------

_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def get() -> FlightRecorder:
    """The process-wide recorder (a default in-memory one until
    :func:`configure` installs the served configuration)."""
    global _recorder
    with _recorder_lock:
        if _recorder is None:
            _recorder = FlightRecorder()
        return _recorder


def configure(spool_dir: str = "", enabled: bool = True,
              **kw) -> FlightRecorder:
    """Build and install the process recorder (build_state / tests)."""
    global _recorder
    rec = FlightRecorder(spool_dir=spool_dir, enabled=enabled, **kw)
    with _recorder_lock:
        old, _recorder = _recorder, rec
    if old is not None:
        old.shutdown(timeout_s=0.5)
    return rec


def reset() -> FlightRecorder:
    """Fresh default recorder (tests)."""
    return configure()


def record(etype: str, rid=None, **data):
    """Module-level shorthand the engine/server hot paths call."""
    get().record(etype, rid, **data)


def finish(rid, reason: str = "", ok: Optional[bool] = None, **data):
    """Module-level shorthand for terminal edges."""
    get().finish(rid, reason=reason, ok=ok, **data)
