"""Guided decoding: OpenAI ``response_format`` (json_object / json_schema)
plus vLLM's ``guided_json`` / ``guided_regex`` / ``guided_choice`` extensions
(:func:`grammar_for_request`; regex subset compiled by :func:`parse_regex`).

The reference serves this through its delegated vLLM engine (SURVEY.md §2.2
row 1: the OpenAI surface exercised by ``/root/reference/llm-d-test.yaml``
includes vLLM's guided-decoding extensions). Our engine owns the sampler, so
constrained output is implemented natively:

- A **character-level machine** defines the language: either the exact JSON
  pushdown machine (``json_object`` — arbitrary nesting via an explicit
  context stack folded into the state, depth-capped so the state space stays
  finite) or a schema-compiled NFA (``json_schema`` — the schema tree is
  finite, so Thompson construction + lazy subset stepping never blows up).
- A **token-level wrapper** (:class:`TokenGrammar`) lifts the char machine to
  the tokenizer's vocabulary: for a machine state, a token is *allowed* iff
  walking its bytes does not dead-end (partial progress is fine — the token
  need not complete the value). Masks are computed lazily per visited state,
  vectorized over the whole vocab with numpy (grouping by unique state per
  byte position), packed to uint32 bitmask words, and cached.
- The engine applies the mask on-device (``engine._apply_allow``) before
  sampling, exactly like the ban/bias rows, and advances the host-side state
  with each emitted token. Guided slots force horizon-1 decode dispatches
  (the host must see token N before it can mask token N+1) — the documented
  throughput trade of every host-FSM guided decoder; unguided traffic keeps
  the fused horizon.

EOS policy: the eos bit is set iff the machine is in an accepting state (the
JSON value is complete), so generation can only stop on valid output; in the
accepting state whitespace remains allowed so ``min_tokens`` can never wedge
a slot with an all-banned row.

Schema subset (documented, validated at compile): types object / array /
string / number / integer / boolean / null, ``enum`` / ``const`` of scalars,
``anyOf`` / ``oneOf``, type lists, nested to any (finite) schema depth.
Object properties are emitted **in schema order**; properties listed in
``required`` (or all, when ``required`` is absent — the OpenAI structured-
outputs contract) are mandatory, trailing non-required properties become
optional comma-groups. Unsupported keywords that would silently change
semantics (``$ref``, ``patternProperties``, ``additionalProperties: {...}``)
raise ``ValueError`` → HTTP 400.
"""

from __future__ import annotations

import json
import re
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# Character-level machine interface
# ---------------------------------------------------------------------------
#
# A char machine is any object with:
#   start() -> state            (hashable)
#   step(state, byte:int) -> state | None
#   accepting(state) -> bool
# States are interned by TokenGrammar, so tuples/frozensets are fine.

_WS = frozenset(b" \t\n\r")
_DIGITS = frozenset(b"0123456789")
_HEX = frozenset(b"0123456789abcdefABCDEF")
# String-body bytes: anything >= 0x20 except '"' and '\'. Continuation bytes
# of multi-byte UTF-8 chars fall in 0x80-0xFF and are allowed — the machine
# runs on bytes, so it accepts any UTF-8 content like JSON itself does.
_STR_BODY = frozenset(b for b in range(0x20, 0x100) if b not in (0x22, 0x5C))
_ESC_ONE = frozenset(b'"\\/bfnrt')

# Modes where a number may implicitly end (next char re-dispatches in parent)
_NUM_ENDABLE = {"num_zero", "num_int", "num_frac", "num_exp"}
_NUM_CONT = {
    "num_zero": frozenset(b".eE"),
    "num_int": _DIGITS | frozenset(b".eE"),
    "num_frac": _DIGITS | frozenset(b"eE"),
    "num_exp": _DIGITS,
}


class JsonMachine:
    """Exact JSON over bytes: state = (mode, context-stack).

    The stack (tuple of 'O'/'A') makes nesting exact to ``max_depth``; a
    '{'/'[' beyond the cap rejects, keeping the reachable state space finite
    so TokenGrammar's caches stay bounded. ``top='object'`` is the OpenAI
    ``json_object`` contract (top level must be an object); ``top='value'``
    accepts any JSON value (used for schema-less array/scalar tests).
    """

    def __init__(self, top: str = "object", max_depth: int = 32):
        assert top in ("object", "value")
        self._top = top
        self._max_depth = max_depth

    def start(self):
        return ("top", ())

    def accepting(self, st) -> bool:
        mode, stack = st
        if mode == "done":
            return True
        return not stack and mode in _NUM_ENDABLE and self._top == "value"

    # -- helpers ------------------------------------------------------------

    def _value_done(self, stack):
        if not stack:
            return ("done", ())
        return (("obj_post_val", stack) if stack[-1] == "O"
                else ("arr_post_val", stack))

    def _dispatch_value(self, stack, c):
        """Transition for a byte seen where a value may start."""
        if c == 0x22:                                   # '"'
            return ("str", stack)
        if c == 0x7B:                                   # '{'
            if len(stack) >= self._max_depth:
                return None
            return ("obj_open", stack + ("O",))
        if c == 0x5B:                                   # '['
            if len(stack) >= self._max_depth:
                return None
            return ("arr_open", stack + ("A",))
        if c == 0x2D:                                   # '-'
            return ("num_neg", stack)
        if c == 0x30:                                   # '0'
            return ("num_zero", stack)
        if c in _DIGITS:
            return ("num_int", stack)
        if c == 0x74:                                   # 't'
            return (("lit", "true", 1), stack)
        if c == 0x66:                                   # 'f'
            return (("lit", "false", 1), stack)
        if c == 0x6E:                                   # 'n'
            return (("lit", "null", 1), stack)
        return None

    # -- the transition function --------------------------------------------

    def step(self, st, c: int):
        mode, stack = st
        # number end-and-redispatch: ',' after "12" closes the number first
        if mode in _NUM_ENDABLE and c not in _NUM_CONT[mode]:
            return self.step(self._value_done(stack), c)

        if mode == "top":
            if c in _WS:
                return st
            if self._top == "object":
                return ("obj_open", ("O",)) if c == 0x7B else None
            return self._dispatch_value(stack, c)
        if mode == "done":
            return st if c in _WS else None

        # strings (value and object-key variants share shapes)
        if mode in ("str", "key"):
            if c == 0x22:
                return (self._value_done(stack) if mode == "str"
                        else ("post_key", stack))
            if c == 0x5C:
                return (mode + "_esc", stack)
            return st if c in _STR_BODY else None
        if mode in ("str_esc", "key_esc"):
            base = mode[:-4]
            if c in _ESC_ONE:
                return (base, stack)
            if c == 0x75:                               # 'u'
                return (base + "_u4", stack)
            return None
        if isinstance(mode, str) and mode.endswith(("_u1", "_u2", "_u3",
                                                    "_u4")):
            if c not in _HEX:
                return None
            base, n = mode.rsplit("_u", 1)
            left = int(n) - 1
            return ((base, stack) if left == 0
                    else (f"{base}_u{left}", stack))

        # numbers
        if mode == "num_neg":
            if c == 0x30:
                return ("num_zero", stack)
            return ("num_int", stack) if c in _DIGITS else None
        if mode in _NUM_ENDABLE:                        # continuation chars
            if c == 0x2E:                               # '.'
                return ("num_dot", stack)
            if c in (0x65, 0x45):                       # e E
                return ("num_e", stack)
            return (mode, stack) if c in _DIGITS else None
        if mode == "num_dot":
            return ("num_frac", stack) if c in _DIGITS else None
        if mode == "num_e":
            if c in (0x2B, 0x2D):
                return ("num_esign", stack)
            return ("num_exp", stack) if c in _DIGITS else None
        if mode == "num_esign":
            return ("num_exp", stack) if c in _DIGITS else None

        # literals true/false/null
        if isinstance(mode, tuple) and mode[0] == "lit":
            _, word, i = mode
            if c != ord(word[i]):
                return None
            if i + 1 == len(word):
                return self._value_done(stack)
            return (("lit", word, i + 1), stack)

        # objects
        if mode == "obj_open":
            if c in _WS:
                return st
            if c == 0x22:
                return ("key", stack)
            if c == 0x7D:                               # '}'
                return self._value_done(stack[:-1])
            return None
        if mode == "post_key":
            if c in _WS:
                return st
            return ("obj_val_expect", stack) if c == 0x3A else None
        if mode == "obj_val_expect":
            if c in _WS:
                return st
            return self._dispatch_value(stack, c)
        if mode == "obj_post_val":
            if c in _WS:
                return st
            if c == 0x2C:                               # ','
                return ("obj_key_expect", stack)
            if c == 0x7D:
                return self._value_done(stack[:-1])
            return None
        if mode == "obj_key_expect":
            if c in _WS:
                return st
            return ("key", stack) if c == 0x22 else None

        # arrays
        if mode == "arr_open":
            if c in _WS:
                return st
            if c == 0x5D:                               # ']'
                return self._value_done(stack[:-1])
            return self._dispatch_value(stack, c)
        if mode == "arr_post_val":
            if c in _WS:
                return st
            if c == 0x2C:
                return ("arr_val_expect", stack)
            if c == 0x5D:
                return self._value_done(stack[:-1])
            return None
        if mode == "arr_val_expect":
            if c in _WS:
                return st
            return self._dispatch_value(stack, c)

        return None


# ---------------------------------------------------------------------------
# Schema → char NFA (Thompson construction, lazily determinized by stepping
# on frozensets of NFA nodes)
# ---------------------------------------------------------------------------


class _Nfa:
    """Mutable NFA builder: nodes hold byte-transitions + epsilon edges."""

    def __init__(self):
        self.trans: List[Dict[int, set]] = []
        self.eps: List[set] = []

    def node(self) -> int:
        self.trans.append({})
        self.eps.append(set())
        return len(self.trans) - 1

    def edge(self, a: int, c: int, b: int):
        self.trans[a].setdefault(c, set()).add(b)

    def eedge(self, a: int, b: int):
        self.eps[a].add(b)


def _build(nfa: _Nfa, rx, a: int, b: int):
    """Wire regex AST ``rx`` between nodes a → b."""
    kind = rx[0]
    if kind == "lit":
        cur = a
        data = rx[1]
        for i, c in enumerate(data):
            nxt = b if i == len(data) - 1 else nfa.node()
            nfa.edge(cur, c, nxt)
            cur = nxt
        if not data:
            nfa.eedge(a, b)
    elif kind == "cls":
        for c in rx[1]:
            nfa.edge(a, c, b)
    elif kind == "seq":
        parts = rx[1]
        if not parts:
            nfa.eedge(a, b)
        else:
            cur = a
            for i, p in enumerate(parts):
                nxt = b if i == len(parts) - 1 else nfa.node()
                _build(nfa, p, cur, nxt)
                cur = nxt
    elif kind == "alt":
        for p in rx[1]:
            _build(nfa, p, a, b)
    elif kind == "star":
        mid = nfa.node()
        nfa.eedge(a, mid)
        _build(nfa, rx[1], mid, mid)
        nfa.eedge(mid, b)
    elif kind == "opt":
        nfa.eedge(a, b)
        _build(nfa, rx[1], a, b)
    else:  # pragma: no cover
        raise AssertionError(kind)


def _lit(s: bytes):
    return ("lit", s)


def _cls(s):
    return ("cls", frozenset(s if not isinstance(s, (bytes, bytearray))
                             else bytes(s)))


def _seq(*parts):
    return ("seq", tuple(parts))


def _alt(*parts):
    return ("alt", tuple(parts))


def _star(p):
    return ("star", p)


def _plus(p):
    return _seq(p, _star(p))


def _opt(p):
    return ("opt", p)


_RX_WS = _star(_cls(b" \t\n\r"))
_RX_STRING = _seq(
    _lit(b'"'),
    _star(_alt(
        _cls(_STR_BODY),
        _seq(_lit(b"\\"), _alt(
            _cls(_ESC_ONE),
            _seq(_lit(b"u"), _cls(_HEX), _cls(_HEX), _cls(_HEX),
                 _cls(_HEX)))))),
    _lit(b'"'))
_RX_INT = _seq(_opt(_lit(b"-")),
               _alt(_lit(b"0"), _seq(_cls(b"123456789"), _star(_cls(_DIGITS)))))
_RX_NUMBER = _seq(_RX_INT,
                  _opt(_seq(_lit(b"."), _plus(_cls(_DIGITS)))),
                  _opt(_seq(_cls(b"eE"), _opt(_cls(b"+-")),
                            _plus(_cls(_DIGITS)))))
_RX_BOOL = _alt(_lit(b"true"), _lit(b"false"))
_RX_NULL = _lit(b"null")

_UNSUPPORTED = ("$ref", "patternProperties", "allOf", "not",
                "if", "then", "else")


def schema_to_rx(schema) -> tuple:
    """Compile a JSON-schema subtree to a regex AST. Raises ValueError on
    constructs outside the documented subset."""
    if schema is True or schema == {}:
        # any value: approximate with the scalar types + flat containers is
        # wrong; instead reject — callers wanting "any JSON" should use
        # json_object mode's exact machine.
        raise ValueError("unconstrained subschema ({} / true) is not "
                         "supported inside json_schema; give it a type")
    if not isinstance(schema, dict):
        raise ValueError(f"schema must be an object, got {type(schema)}")
    for k in _UNSUPPORTED:
        if k in schema:
            raise ValueError(f"unsupported json_schema keyword: {k}")
    if isinstance(schema.get("additionalProperties"), dict):
        raise ValueError("additionalProperties with a schema is unsupported")
    if "enum" in schema or "const" in schema:
        vals = schema.get("enum", [schema.get("const")])
        outs = []
        for v in vals:
            if isinstance(v, (dict, list)):
                raise ValueError("enum/const of containers is unsupported")
            outs.append(_lit(json.dumps(v).encode()))
        return _alt(*outs)
    if "anyOf" in schema or "oneOf" in schema:
        subs = schema.get("anyOf") or schema.get("oneOf")
        return _alt(*[schema_to_rx(s) for s in subs])

    t = schema.get("type")
    if isinstance(t, list):
        return _alt(*[schema_to_rx({**schema, "type": one}) for one in t])
    if t == "string":
        return _RX_STRING
    if t == "number":
        return _RX_NUMBER
    if t == "integer":
        return _RX_INT
    if t == "boolean":
        return _RX_BOOL
    if t == "null":
        return _RX_NULL
    if t == "array":
        items = schema.get("items")
        if items is None:
            raise ValueError("array schema requires items")
        item = schema_to_rx(items)
        more = _star(_seq(_RX_WS, _lit(b","), _RX_WS, item))
        body = _seq(item, more)
        if int(schema.get("minItems", 0)) == 0:
            body = _opt(body)
        return _seq(_lit(b"["), _RX_WS, body, _RX_WS, _lit(b"]"))
    if t == "object":
        props = schema.get("properties")
        if not props:
            raise ValueError("object schema requires properties")
        required = set(schema.get("required", list(props.keys())))
        entries = [(k, _seq(_lit(json.dumps(k).encode()), _RX_WS,
                            _lit(b":"), _RX_WS, schema_to_rx(v)))
                   for k, v in props.items()]
        req = [(k, e) for k, e in entries if k in required]
        opt = [(k, e) for k, e in entries if k not in required]
        if req:
            body = req[0][1]
            for _, e in req[1:]:
                body = _seq(body, _RX_WS, _lit(b","), _RX_WS, e)
            for _, e in opt:
                body = _seq(body, _opt(_seq(_RX_WS, _lit(b","), _RX_WS, e)))
        else:
            # no required props: any non-empty SUBSET in schema order must
            # be reachable — alternate over which property appears FIRST,
            # each later one an optional comma-group (review r5: a linear
            # optional chain made the first property a prerequisite,
            # e.g. '{"b": 1}' was unreachable beside '{"a": 1}')
            alts = []
            for i, (_, first) in enumerate(opt):
                tail = first
                for _, later in opt[i + 1:]:
                    tail = _seq(tail, _opt(_seq(_RX_WS, _lit(b","),
                                               _RX_WS, later)))
                alts.append(tail)
            body = _opt(_alt(*alts))
        return _seq(_lit(b"{"), _RX_WS, body, _RX_WS, _lit(b"}"))
    raise ValueError(f"unsupported schema type: {t!r}")


class NfaMachine:
    """Char machine over a compiled NFA; states are frozensets of nodes.

    ``pad_ws`` (the json_schema default) wraps the language in optional
    whitespace; exact-match modes (guided_regex / guided_choice) keep the
    language as written."""

    def __init__(self, rx, pad_ws: bool = True):
        nfa = _Nfa()
        self._start_node = nfa.node()
        self._accept = nfa.node()
        if pad_ws:
            rx = _seq(_RX_WS, rx, _RX_WS)
        _build(nfa, rx, self._start_node, self._accept)
        self._nfa = nfa

    def _closure(self, nodes) -> frozenset:
        out, work = set(nodes), list(nodes)
        while work:
            n = work.pop()
            for m in self._nfa.eps[n]:
                if m not in out:
                    out.add(m)
                    work.append(m)
        return frozenset(out)

    def start(self):
        return self._closure({self._start_node})

    def step(self, st, c: int):
        nxt = set()
        for n in st:
            nxt.update(self._nfa.trans[n].get(c, ()))
        if not nxt:
            return None
        return self._closure(nxt)

    def accepting(self, st) -> bool:
        return self._accept in st


# ---------------------------------------------------------------------------
# Regex → AST (vLLM ``guided_regex``)
# ---------------------------------------------------------------------------

_CLASS_SHORTCUTS = {
    "d": frozenset(b"0123456789"),
    "w": frozenset(b"abcdefghijklmnopqrstuvwxyz"
                   b"ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_"),
    "s": frozenset(b" \t\n\r\f\v"),
}
_ANY = frozenset(b for b in range(256) if b != 0x0A)   # '.' excludes \n
_REP_CAP = 256      # per-quantifier {m,n} bound
# TOTAL expanded-AST atom budget: counted quantifiers compose
# MULTIPLICATIVELY under nesting ("((a{256}){256})" is 65k atoms from 12
# chars), and grammars compile synchronously in the request handler — the
# per-quantifier cap alone left a one-request DoS (review r5)
_RX_NODE_CAP = 10_000


def _rx_size(rx) -> int:
    kind = rx[0]
    if kind in ("lit", "cls"):
        return max(1, len(rx[1])) if kind == "lit" else 1
    if kind in ("seq", "alt"):
        return 1 + sum(_rx_size(p) for p in rx[1])
    return 1 + _rx_size(rx[1])                     # star / opt


def parse_regex(pattern: str) -> tuple:
    """Parse a practical regex subset into the NFA-combinator AST.

    Supported: literals, escapes (incl. \\d \\w \\s and their negations),
    ``.``, ``[...]`` classes with ranges/negation, ``|``, ``(...)`` and
    ``(?:...)`` groups, ``* + ? {m} {m,} {m,n}`` (also non-greedy suffix
    ``?``, which constrains the same language). Anchors ``^``/``$`` at the
    ends are accepted and ignored (the whole output matches by
    construction). Unsupported constructs (backrefs, lookaround) raise
    ``ValueError`` → HTTP 400. ASCII/byte semantics: multi-byte UTF-8
    literals work byte-wise; classes are byte classes.
    """
    data = pattern.encode()
    pos = 0

    def err(msg):
        raise ValueError(f"guided_regex: {msg} at offset {pos} in "
                         f"{pattern!r}")

    def peek():
        return data[pos] if pos < len(data) else None

    def parse_alt():
        nonlocal pos
        parts = [parse_seq()]
        while peek() == 0x7C:                      # '|'
            pos += 1
            parts.append(parse_seq())
        return parts[0] if len(parts) == 1 else _alt(*parts)

    def parse_seq():
        nonlocal pos
        out = []
        while True:
            c = peek()
            if c is None or c in (0x7C, 0x29):     # '|' ')'
                break
            out.append(parse_repeat())
        return _seq(*out) if len(out) != 1 else out[0]

    def parse_repeat():
        nonlocal pos
        atom = parse_atom()
        while True:
            c = peek()
            if c == 0x2A:                          # '*'
                atom, pos = _star(atom), pos + 1
            elif c == 0x2B:                        # '+'
                atom, pos = _plus(atom), pos + 1
            elif c == 0x3F:                        # '?'
                atom, pos = _opt(atom), pos + 1
            elif c == 0x7B:                        # '{'
                end = data.find(b"}", pos)
                if end < 0:
                    err("unterminated {quantifier}")
                spec = data[pos + 1:end].decode()
                pos = end + 1
                m, _, n = spec.partition(",")
                try:
                    lo = int(m)
                    hi = None if _ and not n else (lo if not _ else int(n))
                except ValueError:
                    err(f"bad quantifier {{{spec}}}")
                if lo > _REP_CAP or (hi is not None and hi > _REP_CAP):
                    err(f"quantifier beyond the {_REP_CAP} bound")
                if hi is not None and hi < lo:
                    err(f"reversed quantifier {{{spec}}}")
                reps = lo + (1 if hi is None else hi - lo)
                if _rx_size(atom) * max(1, reps) > _RX_NODE_CAP:
                    err(f"pattern expansion beyond the {_RX_NODE_CAP}-node "
                        f"budget")
                rep = [atom] * lo
                if hi is None:
                    rep.append(_star(atom))
                else:
                    rep += [_opt(atom)] * (hi - lo)
                atom = _seq(*rep)
            else:
                break
            if peek() == 0x3F:                     # non-greedy: same language
                pos += 1
        return atom

    def parse_class_escape():
        """One escape inside or outside a class → (set|byte)."""
        nonlocal pos
        pos += 1
        c = peek()
        if c is None:
            err("dangling backslash")
        pos += 1
        ch = chr(c)
        if ch in _CLASS_SHORTCUTS:
            return _CLASS_SHORTCUTS[ch]
        if ch.upper() in _CLASS_SHORTCUTS and ch.isupper():
            return frozenset(range(256)) - _CLASS_SHORTCUTS[ch.lower()]
        mapped = {"n": 0x0A, "t": 0x09, "r": 0x0D, "f": 0x0C, "v": 0x0B,
                  "0": 0x00}.get(ch)
        if mapped is not None:
            return mapped
        if ch == "x":
            hx = data[pos:pos + 2].decode()
            pos += 2
            try:
                return int(hx, 16)
            except ValueError:
                err(f"bad \\x escape {hx!r}")
        if ch.isalnum():
            err(f"unsupported escape \\{ch}")
        return c                                   # escaped punctuation

    def parse_atom():
        nonlocal pos
        c = peek()
        if c == 0x28:                              # '('
            pos += 1
            if data[pos:pos + 2] == b"?:":
                pos += 2
            elif peek() == 0x3F:
                err("unsupported (?...) construct")
            inner = parse_alt()
            if peek() != 0x29:
                err("unterminated group")
            pos += 1
            return inner
        if c == 0x5B:                              # '['
            return _cls(parse_class())
        if c == 0x2E:                              # '.'
            pos += 1
            return _cls(_ANY)
        if c == 0x5E:                              # '^' only valid leading
            if pos != 0:
                err("mid-pattern '^' anchors are unsupported")
            pos += 1
            return _seq()
        if c == 0x24:                              # '$' only valid trailing
            if pos != len(data) - 1:
                err("mid-pattern '$' anchors are unsupported")
            pos += 1
            return _seq()
        if c == 0x5C:
            got = parse_class_escape()
            return _cls(got) if isinstance(got, frozenset) else \
                _lit(bytes([got]))
        if c in (0x2A, 0x2B, 0x3F, 0x7B):
            err("quantifier with nothing to repeat")
        pos += 1
        return _lit(bytes([c]))

    def parse_class():
        nonlocal pos
        pos += 1                                   # consume '['
        negate = peek() == 0x5E
        if negate:
            pos += 1
        out = set()
        first = True
        while True:
            c = peek()
            if c is None:
                err("unterminated character class")
            if c == 0x5D and not first:            # ']'
                pos += 1
                break
            first = False
            if c == 0x5C:
                got = parse_class_escape()
                if isinstance(got, frozenset):
                    out |= got
                    continue
                lo = got
            else:
                lo = c
                pos += 1
            if peek() == 0x2D and pos + 1 < len(data) \
                    and data[pos + 1] != 0x5D:     # range a-b
                pos += 1
                hi = peek()
                if hi == 0x5C:
                    hi = parse_class_escape()
                    if isinstance(hi, frozenset):
                        err("class shortcut cannot end a range")
                else:
                    pos += 1
                if hi < lo:
                    err("reversed class range")
                out |= set(range(lo, hi + 1))
            else:
                out.add(lo)
        return frozenset(range(256)) - frozenset(out) if negate \
            else frozenset(out)

    rx = parse_alt()
    if pos != len(data):
        err("unbalanced ')'")
    return rx


# ---------------------------------------------------------------------------
# Token-level wrapper
# ---------------------------------------------------------------------------


def token_byte_table(tokenizer) -> List[Optional[bytes]]:
    """token id → exact byte string, or None (never allowed: specials,
    unrepresentable artifacts). Handles our ByteTokenizer, byte-level-BPE HF
    tokenizers (GPT-2 unicode-to-byte map — Qwen/Llama-3/OPT/Phi), and
    sentencepiece-style '▁' tokenizers (Gemma/Mistral); falls back to
    per-token decode when no token-string view exists."""
    V = tokenizer.vocab_size
    inner = getattr(tokenizer, "_tok", None)
    out: List[Optional[bytes]] = [None] * V
    if inner is None:
        # ByteTokenizer: id == byte for < 256; specials stay None
        for i in range(min(256, V)):
            out[i] = bytes([i])
        return out

    specials = set(getattr(inner, "all_special_ids", []) or [])
    # GPT-2 byte-level unicode map (the printable stand-ins byte-level BPE
    # tokenizers store token strings in)
    bs = list(range(0x21, 0x7F)) + list(range(0xA1, 0xAD)) + \
        list(range(0xAE, 0x100))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    uni2byte = {chr(c): b for b, c in zip(bs, cs)}

    try:
        toks = inner.convert_ids_to_tokens(list(range(V)))
    # tpulint: disable=R3 capability probe — tokenizers lacking convert_ids_to_tokens fall back to the decode-based byte table below
    except Exception:
        toks = None
    if toks is not None:
        sample = [t for t in toks[:2000] if t]
        byte_level = sample and all(ch in uni2byte for t in sample[:50]
                                    for ch in t)
        bytefb = re.compile(r"^<0x([0-9A-Fa-f]{2})>$")
        for i, t in enumerate(toks):
            if i in specials or not t:
                continue
            if byte_level:
                try:
                    out[i] = bytes(uni2byte[ch] for ch in t)
                    continue
                except KeyError:
                    pass
            m = bytefb.match(t)
            if m:
                # sentencepiece byte-fallback: "<0x22>" DECODES to one raw
                # byte — mapping the literal 6-char string would desync the
                # FSM from the emitted text (review r5)
                out[i] = bytes([int(m.group(1), 16)])
                continue
            out[i] = t.replace("▁", " ").encode("utf-8")
        return out
    for i in range(V):                    # last-resort: lossy single decodes
        if i in specials:
            continue
        s = inner.decode([i])
        if s and "�" not in s:
            out[i] = s.encode("utf-8")
    return out


class TokenGrammar:
    """A char machine lifted to token-level masks over one vocabulary.

    States are interned to dense ids; per-state artifacts are cached:
    ``_rows[sid]`` = 256-wide next-sid table (-1 = reject) and
    ``_masks[sid]`` = packed uint32 allow-bitmask over the vocab (bit v of
    word v>>5). The mask computation walks ALL tokens in parallel with
    numpy, grouping by unique live state per byte position — cost is
    O(L × unique_states × V) elementwise, a few ms for a 152k vocab, paid
    once per distinct grammar state ever visited.
    """

    def __init__(self, machine, tokenizer, eos_ids, exact: bool = False):
        self._m = machine
        # exact-match grammars (guided_regex / guided_choice) allow NOTHING
        # in their final accepting states — not even whitespace — so a
        # device-side min_tokens eos-ban would leave an all-masked logits
        # row; engine.submit rejects that combination (review r5)
        self.exact = exact
        self._eos = [e for e in (eos_ids or []) if e is not None]
        tb = token_byte_table(tokenizer)
        self.vocab_size = len(tb)
        self.n_words = (self.vocab_size + 31) // 32
        L = max((len(b) for b in tb if b), default=1)
        self._tbmat = np.zeros((self.vocab_size, L), np.uint8)
        self._tlen = np.zeros(self.vocab_size, np.int32)
        self._no_bytes = np.ones(self.vocab_size, bool)
        for i, b in enumerate(tb):
            if b:
                self._tbmat[i, :len(b)] = np.frombuffer(b, np.uint8)
                self._tlen[i] = len(b)
                self._no_bytes[i] = False
        self._tb = tb
        # strong tokenizer ref: the grammar cache keys on id(tokenizer), so
        # the tokenizer must outlive the grammar or a recycled address could
        # alias a different vocab (review r5)
        self._tokenizer = tokenizer
        # BOUNDED caches keyed by the (hashable) machine STATE itself —
        # review r5 twice over: per-state masks at ~V/8 bytes leak for the
        # server's lifetime unbounded, and an earlier fix that LRU'd masks
        # but permanently interned every state in an id table just moved
        # the leak down a level. No global interning exists now; evicted
        # entries recompute from the state object, so eviction can never
        # invalidate a live request's cursor.
        self._rows: "OrderedDict[object, tuple]" = OrderedDict()
        self._masks: "OrderedDict[object, np.ndarray]" = OrderedDict()
        self._rows_cap = 1024
        self._masks_cap = 2048
        # whitespace token ids: allowed in accepting states alongside eos so
        # a min_tokens-banned eos can never leave an all-masked row
        self._ws_ids = [i for i, b in enumerate(tb)
                        if b and all(c in _WS for c in b)]
        self.start_state = machine.start()

    def _row(self, st) -> tuple:
        """256-entry tuple of next states (None = reject) for ``st``."""
        row = self._rows.get(st)
        if row is None:
            row = tuple(self._m.step(st, c) for c in range(256))
            self._rows[st] = row
            if len(self._rows) > self._rows_cap:
                self._rows.popitem(last=False)
        else:
            self._rows.move_to_end(st)
        return row

    def accepting(self, st) -> bool:
        return self._m.accepting(st)

    def advance(self, st, token_id: int):
        """State after emitting ``token_id``; None = rejected."""
        if token_id in self._eos:
            return st if self.accepting(st) else None
        if token_id >= self.vocab_size or self._no_bytes[token_id]:
            return None
        for c in self._tbmat[token_id, :self._tlen[token_id]]:
            st = self._row(st)[c]
            if st is None:
                return None
        return st

    def mask_words(self, st) -> np.ndarray:
        """Packed uint32 allow-bitmask for machine state ``st``.

        The vocab walk vectorizes with WALK-LOCAL state ids (a dict built
        per computation) — nothing outlives the call except the LRU'd
        result."""
        m = self._masks.get(st)
        if m is not None:
            self._masks.move_to_end(st)
            return m
        V = self.vocab_size
        local: Dict[object, int] = {st: 0}
        states: List[object] = [st]

        def lid(s) -> int:
            i = local.get(s)
            if i is None:
                i = len(states)
                local[s] = i
                states.append(s)
            return i

        row_ids_memo: Dict[int, np.ndarray] = {}

        def row_ids(u: int) -> np.ndarray:
            r = row_ids_memo.get(u)
            if r is None:
                r = np.fromiter(
                    (-1 if s is None else lid(s)
                     for s in self._row(states[u])), np.int64, 256)
                row_ids_memo[u] = r
            return r

        cur = np.zeros(V, np.int64)
        cur[self._no_bytes] = -1
        for p in range(self._tbmat.shape[1]):
            act = (p < self._tlen) & (cur >= 0)
            if not act.any():
                break
            nxt = cur.copy()
            for u in np.unique(cur[act]):
                sel = act & (cur == u)
                nxt[sel] = row_ids(int(u))[self._tbmat[sel, p]]
            cur = nxt
        allowed = cur >= 0
        if self.accepting(st):
            for e in self._eos:
                if e < V:
                    allowed[e] = True
        if not allowed.any():
            # unreachable by construction (accepting states allow ws + eos;
            # others always have a continuation) — but a vocab missing the
            # needed bytes must finish, not wedge
            for e in self._eos:
                if e < V:
                    allowed[e] = True
        words = np.zeros(self.n_words, np.uint32)
        idx = np.nonzero(allowed)[0]
        np.bitwise_or.at(words, idx >> 5,
                         (np.uint32(1) << (idx & 31).astype(np.uint32)))
        self._masks[st] = words
        if len(self._masks) > self._masks_cap:
            self._masks.popitem(last=False)
        return words


class GuidedState:
    """Per-request cursor over a shared TokenGrammar."""

    __slots__ = ("grammar", "state", "dead")

    def __init__(self, grammar: TokenGrammar):
        self.grammar = grammar
        self.state = grammar.start_state
        self.dead = False

    def clone(self) -> "GuidedState":
        return GuidedState(self.grammar)

    def mask_words(self) -> np.ndarray:
        if self.dead:
            # force-finish: only eos (and ws) remain
            g = self.grammar
            words = np.zeros(g.n_words, np.uint32)
            for e in g._eos + g._ws_ids:
                if e < g.vocab_size:
                    words[e >> 5] |= np.uint32(1) << np.uint32(e & 31)
            return words
        return self.grammar.mask_words(self.state)

    def advance(self, token_id: int) -> None:
        if self.dead:
            return
        nxt = self.grammar.advance(self.state, token_id)
        if nxt is None:
            self.dead = True
        else:
            self.state = nxt

    def fingerprint(self):
        """Hashable identity of the current mask: two cursors with equal
        fingerprints produce bit-identical ``mask_words()`` (machine states
        are the TokenGrammar mask cache's own keys). The engine's
        device-mask caches (EnginePrograms._allow_row/_allow_words) key on
        this to skip rebuilding + re-uploading an allow operand whose FSM
        did not advance between dispatches."""
        return (self.state, self.dead)

    @property
    def complete(self) -> bool:
        return (not self.dead) and self.grammar.accepting(self.state)


# ---------------------------------------------------------------------------
# Server-facing entry
# ---------------------------------------------------------------------------

_GRAMMAR_CACHE: Dict[Tuple[int, str], TokenGrammar] = {}
_CACHE_CAP = 64


def grammar_for(tokenizer, response_format: dict, eos_ids) -> TokenGrammar:
    """Resolve an OpenAI ``response_format`` dict to a (cached) TokenGrammar.

    Accepts {"type": "json_object"} and {"type": "json_schema",
    "json_schema": {"schema": {...}}} (also tolerates the schema directly
    under "schema" — the vLLM extension shape). Raises ValueError for
    malformed input; the server maps that to HTTP 400.
    """
    t = response_format.get("type")
    if t == "json_object":
        key = (id(tokenizer), "json_object")
        g = _GRAMMAR_CACHE.get(key)
        if g is None:
            g = TokenGrammar(JsonMachine(top="object"), tokenizer, eos_ids)
            _cache_put(key, g)
        return g
    if t == "json_schema":
        spec = response_format.get("json_schema", response_format)
        schema = spec.get("schema") if isinstance(spec, dict) else None
        if not isinstance(schema, dict):
            raise ValueError("json_schema response_format requires "
                             "json_schema.schema to be an object")
        key = (id(tokenizer), json.dumps(schema, sort_keys=True))
        g = _GRAMMAR_CACHE.get(key)
        if g is None:
            g = TokenGrammar(NfaMachine(schema_to_rx(schema)), tokenizer,
                             eos_ids)
            _cache_put(key, g)
        return g
    raise ValueError(f"unsupported response_format type: {t!r} "
                     "(expected json_object or json_schema)")


def _cache_put(key, g):
    if len(_GRAMMAR_CACHE) >= _CACHE_CAP:
        _GRAMMAR_CACHE.pop(next(iter(_GRAMMAR_CACHE)))
    _GRAMMAR_CACHE[key] = g


def _cached(tokenizer, key_tail: str, build) -> TokenGrammar:
    key = (id(tokenizer), key_tail)
    g = _GRAMMAR_CACHE.get(key)
    if g is None:
        g = build()
        _cache_put(key, g)
    return g


def grammar_for_request(tokenizer, body: dict, eos_ids):
    """Resolve a request body's constrained-output spec to a TokenGrammar.

    Beside OpenAI ``response_format``, accepts vLLM's sampling-params
    extensions: ``guided_json`` (a JSON schema), ``guided_regex`` (compiled
    by :func:`parse_regex`), and ``guided_choice`` (list of exact strings).
    At most one spec may be present. Returns None when unconstrained;
    raises ValueError (→ HTTP 400) on conflicts or malformed specs.
    """
    specs = [k for k in ("response_format", "guided_json", "guided_regex",
                         "guided_choice") if body.get(k) is not None]
    if not specs:
        return None
    # a present-but-null response_format is "unset" (OpenAI SDKs serialize
    # it that way) — body.get's default doesn't cover that, hence `or {}`
    rf = body.get("response_format") or {}
    if rf.get("type") in (None, "text") and specs == ["response_format"]:
        return None
    if len(specs) > 1:
        raise ValueError(f"at most one guided-decoding spec allowed, got "
                         f"{specs}")
    kind = specs[0]
    if kind == "response_format":
        return grammar_for(tokenizer, body["response_format"], eos_ids)
    if kind == "guided_json":
        schema = body["guided_json"]
        if not isinstance(schema, dict):
            raise ValueError("guided_json must be a JSON schema object")
        return _cached(
            tokenizer, "json:" + json.dumps(schema, sort_keys=True),
            lambda: TokenGrammar(NfaMachine(schema_to_rx(schema)),
                                 tokenizer, eos_ids))
    if kind == "guided_regex":
        pattern = body["guided_regex"]
        if not isinstance(pattern, str) or not pattern:
            raise ValueError("guided_regex must be a non-empty string")
        return _cached(
            tokenizer, "re:" + pattern,
            lambda: TokenGrammar(
                NfaMachine(parse_regex(pattern), pad_ws=False),
                tokenizer, eos_ids, exact=True))
    choices = body["guided_choice"]
    if not isinstance(choices, list) or not choices \
            or not all(isinstance(c, str) for c in choices):
        raise ValueError("guided_choice must be a non-empty list of strings")
    return _cached(
        tokenizer, "choice:" + json.dumps(choices),
        lambda: TokenGrammar(
            NfaMachine(_alt(*[_lit(c.encode()) for c in choices]),
                       pad_ws=False),
            tokenizer, eos_ids, exact=True))
