"""Device telemetry & roofline attribution — the DCGM-analogue layer.

The reference stack deploys a DCGM exporter so Prometheus sees the *device*
(utilization, memory, clocks) beside the serving metrics; here the TPU was a
black box — one undifferentiated ``device_busy_seconds`` counter and a
static compiled-bytes gauge. This module turns the busy-watermark samples
the engine already takes (serving/programs.py) into:

1. **Per-program roofline attribution.** Every dispatch reports
   ``(program_kind, batch, tokens, mean_ctx, device_seconds)`` into windowed
   accumulators. An analytical FLOP/byte cost model (weights + KV bytes per
   step, derived from ModelConfig — the PERF.md model, now falsifiable in
   production) converts the window sums into ``tpu_device_mfu{program}``,
   ``tpu_device_membw_util{program}``, ``tpu_device_duty_cycle`` and
   ``tpu_device_dma_wait_fraction`` (measured step time vs the
   roofline-predicted floor: max(flops/peak_flops, bytes/peak_bw)).

2. **Live HBM ledger.** Actual occupancy by component (params, KV pages in
   use, sampler carry, cached sampling operands, …) sampled from host-side
   metadata — never a device read — rendered as
   ``tpu_device_hbm_live_bytes{component}`` and reconciled against the AOT
   manifest's compiled ledger: ``tpu_device_hbm_drift_bytes`` plus a
   warn-never-kill verdict for /healthz.

Recording follows the flight-recorder contract: ``note()`` is a handful of
float ops and a deque append under a lock — it can never block, fail, or
perturb a request (seeded streams are byte-identical with devmon on or
off). All six gauges are written from exactly ONE site, ``DevMon.export()``
(tpulint R10), and every timestamp comes through an injectable monotonic
clock (slo.py discipline) so the /debug/roofline table is exact-arithmetic
testable under a fake clock.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Deque, Dict, Optional, Tuple

from aws_k8s_ansible_provisioner_tpu.serving.metrics import (
    Gauge, Registry)
from aws_k8s_ansible_provisioner_tpu.serving.slo import trim_window

# Attribution window (seconds). One window: the dashboard question is "what
# is the device doing NOW", not SLO burn over an hour — slo.py owns that.
WINDOW_S = 60.0

# v5e defaults (PERF.md): bf16 peak and HBM bandwidth per chip.
DEFAULT_PEAK_TFLOPS = 197.0
DEFAULT_HBM_GBPS = 819.0
DEFAULT_HBM_TOLERANCE_MB = 64.0

# Program kinds the engine reports — the label set is closed so the gauge
# cardinality is bounded no matter what traffic does.
PROGRAM_KINDS = ("prefill", "prefill_batch", "prefill_chunk", "prefix_copy",
                 "kv_restore", "decode", "spec_decode", "mixed_step")


class DevMonMetrics:
    """The tpu_device_* family. Registered here, rendered by BOTH /metrics
    routes, written only by DevMon.export() (tpulint R10)."""

    def __init__(self):
        r = Registry()
        self.registry = r
        self.mfu = r.register(Gauge(
            "tpu_device_mfu",
            "Model FLOP utilization per program over the attribution "
            "window (analytical flops / measured device seconds / peak)"))
        self.membw_util = r.register(Gauge(
            "tpu_device_membw_util",
            "HBM bandwidth utilization per program over the attribution "
            "window (analytical bytes moved / measured device seconds / "
            "peak bandwidth)"))
        self.duty_cycle = r.register(Gauge(
            "tpu_device_duty_cycle",
            "Fraction of the attribution window the device spent inside "
            "dispatched programs (busy-watermark seconds / window)"))
        self.dma_wait_fraction = r.register(Gauge(
            "tpu_device_dma_wait_fraction",
            "Fraction of measured device time above the roofline-predicted "
            "compute/bandwidth floor — the DMA-wait + dispatch-gap residue "
            "the PERF.md double-buffer model predicts"))
        self.hbm_live_bytes = r.register(Gauge(
            "tpu_device_hbm_live_bytes",
            "Live HBM occupancy by component, from host-side metadata "
            "(params, KV pages in use, sampler carry, cached operands)"))
        self.hbm_drift_bytes = r.register(Gauge(
            "tpu_device_hbm_drift_bytes",
            "Live HBM total minus the AOT manifest's compiled ledger "
            "(0 when no manifest is loaded; positive = the ledger "
            "under-promised)"))


metrics = DevMonMetrics()


@dataclass(frozen=True)
class CostModel:
    """Analytical per-dispatch FLOP/byte model (the PERF.md roofline).

    ``flops_per_token``  — 2 x matmul params touched per generated/prefilled
                           token (attention score flops excluded, standard
                           weight-MFU accounting).
    ``weight_bytes``     — bytes streamed from HBM for one full forward
                           step, amortized over the whole batch.
    ``kv_row_bytes``     — k+v bytes for ONE token of context across all
                           layers/heads (int8 rows include their f32 scale,
                           mirroring kv_cache.py's accounting).
    ``mask_row_bytes``   — bytes of ONE row of the guided-decoding allow
                           bitset (ceil(V/32) uint32 words): the per-step
                           host→HBM upload a guided row adds when it rides
                           the ragged pipeline (ISSUE 16). Tiny next to
                           weights — the point of attributing it is proving
                           that, not worrying about it.
    """

    flops_per_token: float
    weight_bytes: float
    kv_row_bytes: float
    mask_row_bytes: float = 0.0

    @staticmethod
    def from_config(cfg, kv_dtype: str = "bf16",
                    weight_bytes: Optional[float] = None) -> "CostModel":
        """Derive the model from a ModelConfig (+ the serving kv dtype)."""
        h = cfg.hidden_size
        q_dim = cfg.num_heads * cfg.head_dim
        kv_dim = cfg.num_kv_heads * cfg.head_dim
        attn = h * q_dim + 2 * h * kv_dim + q_dim * h
        mlp = 3 * h * cfg.intermediate_size
        matmul_params = cfg.num_layers * (attn + mlp) + cfg.vocab_size * h
        if weight_bytes is None:
            # embedding table streams too; bf16 resident weights
            weight_bytes = float(matmul_params + cfg.vocab_size * h) * 2.0
        if kv_dtype == "int8":
            per_head_row = cfg.head_dim * 1 + 4   # int8 row + f32 scale
        else:
            per_head_row = cfg.head_dim * 2       # bf16
        kv_row = cfg.num_layers * 2 * cfg.num_kv_heads * per_head_row
        mask_row = float(-(-cfg.vocab_size // 32) * 4)   # ceil(V/32) u32 words
        return CostModel(flops_per_token=2.0 * matmul_params,
                         weight_bytes=float(weight_bytes),
                         kv_row_bytes=float(kv_row),
                         mask_row_bytes=mask_row)

    def cost(self, kind: str, batch: int, tokens: int, ctx_rows: float,
             steps: int, guided_rows: int = 0) -> Tuple[float, float]:
        """(flops, hbm_bytes) for one dispatch.

        decode-like: weights stream once per STEP (shared by the batch);
        each generated token reads its whole context's KV rows.
        prefill-like: weights stream once; each prompt token writes its KV
        row (attention reads ride the same rows and stay sub-dominant).
        prefix_copy: pure DMA — read + write of the copied rows, zero flops.
        kv_restore: host-tier restore (ISSUE 20) — one HBM write per
        restored KV row, zero flops. Its bandwidth-sense MFU column is the
        restore-vs-reprefill ledger: the same tokens through a prefill kind
        would have cost flops_per_token * tokens of MXU work.
        """
        if kind == "prefix_copy":
            return 0.0, 2.0 * tokens * self.kv_row_bytes
        if kind == "kv_restore":
            return 0.0, float(tokens) * self.kv_row_bytes
        flops = self.flops_per_token * tokens
        # Guided rows upload one allow-bitset row per step (the one-ahead
        # async upload ISSUE 16 added); pure extra HBM traffic, zero flops.
        mask = guided_rows * steps * self.mask_row_bytes
        if kind == "mixed_step":
            # ragged mixed batch: weights stream once for BOTH the decode
            # rows and the packed prefill chunk (the fusion's bandwidth
            # win); decode rows read their context, chunk rows write theirs
            return flops, (self.weight_bytes
                           + tokens * ctx_rows * self.kv_row_bytes + mask)
        if kind in ("decode", "spec_decode"):
            byts = steps * self.weight_bytes \
                + tokens * ctx_rows * self.kv_row_bytes + mask
        else:
            byts = steps * self.weight_bytes + tokens * self.kv_row_bytes \
                + mask
        return flops, byts


class DevMon:
    """Windowed per-program attribution + live HBM ledger.

    ``clock`` is injectable (tests drive a fake); every public method takes
    the lock, so engine-thread notes and HTTP-thread exports never race.
    """

    def __init__(self, enabled: bool = True,
                 peak_tflops: float = DEFAULT_PEAK_TFLOPS,
                 hbm_gbps: float = DEFAULT_HBM_GBPS,
                 hbm_tolerance_mb: float = DEFAULT_HBM_TOLERANCE_MB,
                 window_s: float = WINDOW_S,
                 clock: Callable[[], float] = time.monotonic):
        self.enabled = enabled
        self.peak_flops = max(1.0, peak_tflops) * 1e12
        self.peak_bw = max(1.0, hbm_gbps) * 1e9
        self.hbm_tolerance_bytes = max(0.0, hbm_tolerance_mb) * 1e6
        self.window_s = window_s
        self.clock = clock
        self._lock = threading.Lock()
        self._t0 = clock()
        # kind -> deque of (t, device_s, flops, bytes, tokens, steps)
        self._acc: Dict[str, Deque[tuple]] = {
            k: deque(maxlen=100_000) for k in PROGRAM_KINDS}
        self.cost_model: Optional[CostModel] = None
        # () -> {component: bytes} from host metadata; () -> compiled bytes
        self._hbm_live_fn: Optional[Callable[[], Dict[str, float]]] = None
        self._hbm_compiled_fn: Optional[Callable[[], float]] = None

    # -- wiring -------------------------------------------------------------

    def install_cost_model(self, cm: CostModel):
        with self._lock:
            self.cost_model = cm

    def install_hbm(self, live_fn: Callable[[], Dict[str, float]],
                    compiled_fn: Callable[[], float]):
        with self._lock:
            self._hbm_live_fn = live_fn
            self._hbm_compiled_fn = compiled_fn

    # -- recording (engine thread; drop-not-fail, never blocks on device) ---

    def note(self, kind: str, device_s: float, batch: int = 1,
             tokens: int = 1, ctx_rows: float = 0.0, steps: int = 1,
             guided_rows: int = 0):
        """Record one settled dispatch. Called ONLY after the engine has
        already synced the dispatch (the _decode_fetch side of the
        pipeline) — never adds a device read to the dispatch path (R8).
        ``guided_rows`` = decode rows carrying a grammar allow-mask operand
        (each adds one mask_row_bytes upload per step to the byte model)."""
        if not self.enabled or kind not in self._acc:
            return
        cm = self.cost_model
        if cm is None:
            flops, byts = 0.0, 0.0
        else:
            flops, byts = cm.cost(kind, batch, tokens, ctx_rows, steps,
                                  guided_rows=guided_rows)
        now = self.clock()
        with self._lock:
            dq = self._acc[kind]
            dq.append((now, device_s, flops, byts, tokens, steps))
            trim_window(dq, now, self.window_s)

    # -- queries ------------------------------------------------------------

    def program_stats(self, now: Optional[float] = None) -> Dict[str, dict]:
        """Per-program window aggregates: measured s/step, roofline floor,
        MFU, bandwidth utilization, dma-wait fraction."""
        now = self.clock() if now is None else now
        out: Dict[str, dict] = {}
        with self._lock:
            for kind, dq in self._acc.items():
                trim_window(dq, now, self.window_s)
                if not dq:
                    continue
                dev = sum(e[1] for e in dq)
                flops = sum(e[2] for e in dq)
                byts = sum(e[3] for e in dq)
                toks = sum(e[4] for e in dq)
                steps = sum(e[5] for e in dq)
                floor = max(flops / self.peak_flops, byts / self.peak_bw)
                dev_safe = max(dev, 1e-12)
                out[kind] = {
                    "dispatches": len(dq),
                    "device_seconds": dev,
                    "tokens": toks,
                    "measured_s_per_step": dev / max(1, steps),
                    "predicted_floor_s_per_step": floor / max(1, steps),
                    "mfu": flops / (dev_safe * self.peak_flops),
                    "membw_util": byts / (dev_safe * self.peak_bw),
                    "dma_wait_fraction": max(0.0, dev - floor) / dev_safe,
                }
        return out

    def duty_cycle(self, now: Optional[float] = None) -> float:
        now = self.clock() if now is None else now
        elapsed = min(self.window_s, max(now - self._t0, 1e-9))
        with self._lock:
            busy = sum(e[1] for dq in self._acc.values() for e in dq
                       if e[0] >= now - self.window_s)
        return min(1.0, busy / elapsed)

    def service_rates(self, now: Optional[float] = None) -> dict:
        """Decode-side service capacity over the window, aggregated across
        the decode-like programs — the measurement serving/capacity.py
        blends into its ceiling. ``measured_tps`` divides real device
        seconds (already degraded by DMA-wait); ``roofline_tps`` divides
        the analytical floor (what the chip could do at the roofline; equal
        to measured when no cost model is installed, i.e. floor unknown)."""
        now = self.clock() if now is None else now
        progs = self.program_stats(now)
        toks = dev = floor = 0.0
        for kind in ("decode", "spec_decode"):
            p = progs.get(kind)
            if not p:
                continue
            toks += p["tokens"]
            dev += p["device_seconds"]
            floor += p["device_seconds"] * (1.0 - p["dma_wait_fraction"])
        measured = (toks / dev) if dev > 0.0 else 0.0
        roofline = (toks / floor) if floor > 0.0 else measured
        return {"tokens": toks, "device_seconds": dev,
                "measured_tps": measured, "roofline_tps": roofline,
                "dma_wait_fraction": ((dev - floor) / dev) if dev > 0.0
                else 0.0,
                "duty_cycle": self.duty_cycle(now)}

    def hbm_snapshot(self) -> dict:
        """Live component map + drift vs the AOT compiled ledger. Verdict
        warns (never kills) when live exceeds compiled + tolerance."""
        with self._lock:
            live_fn, compiled_fn = self._hbm_live_fn, self._hbm_compiled_fn
        components: Dict[str, float] = {}
        if live_fn is not None:
            try:
                components = {k: float(v) for k, v in live_fn().items()}
            except Exception:   # tpulint: disable=R3 drop-by-design — a broken HBM sampler costs the ledger, never requests; the snapshot degrades to empty
                components = {}
        live = sum(components.values())
        compiled = 0.0
        if compiled_fn is not None:
            try:
                compiled = float(compiled_fn() or 0.0)
            except Exception:   # tpulint: disable=R3 drop-by-design — no compiled ledger means drift reads 0, never a failed request
                compiled = 0.0
        drift = (live - compiled) if compiled > 0.0 else 0.0
        verdict = "warn" if (compiled > 0.0
                             and live > compiled
                             + self.hbm_tolerance_bytes) else "ok"
        return {"components": components, "live_bytes": live,
                "compiled_bytes": compiled, "drift_bytes": drift,
                "tolerance_bytes": self.hbm_tolerance_bytes,
                "verdict": verdict}

    def snapshot(self, now: Optional[float] = None) -> dict:
        """The /debug/roofline payload (also embedded in /healthz)."""
        now = self.clock() if now is None else now
        progs = self.program_stats(now)
        dev = sum(p["device_seconds"] for p in progs.values())
        excess = sum(p["dma_wait_fraction"] * p["device_seconds"]
                     for p in progs.values())
        return {
            "enabled": self.enabled,
            "window_s": self.window_s,
            "peak_tflops": self.peak_flops / 1e12,
            "peak_hbm_gbps": self.peak_bw / 1e9,
            "duty_cycle": self.duty_cycle(now),
            "dma_wait_fraction": (excess / dev) if dev > 0 else 0.0,
            "programs": progs,
            "hbm": self.hbm_snapshot(),
        }

    def export(self):
        """Refresh every tpu_device_* gauge from the current window — the
        single writer site for the family (tpulint R10). Routes call this
        right before rendering, the slo.py pattern."""
        snap = self.snapshot()
        for kind, p in snap["programs"].items():
            metrics.mfu.set(p["mfu"], program=kind)
            metrics.membw_util.set(p["membw_util"], program=kind)
        metrics.duty_cycle.set(snap["duty_cycle"])
        metrics.dma_wait_fraction.set(snap["dma_wait_fraction"])
        for comp, b in snap["hbm"]["components"].items():
            metrics.hbm_live_bytes.set(b, component=comp)
        metrics.hbm_drift_bytes.set(snap["hbm"]["drift_bytes"])
        return snap


_monitor: Optional[DevMon] = None
_monitor_lock = threading.Lock()


def get() -> DevMon:
    global _monitor
    with _monitor_lock:
        if _monitor is None:
            _monitor = DevMon()
        return _monitor


def configure(**kw) -> DevMon:
    """Swap in a freshly-configured monitor, carrying over the engine wiring
    (cost model + HBM samplers) the previous instance held — build_state
    configures AFTER the engine attaches."""
    global _monitor
    with _monitor_lock:
        old = _monitor
        _monitor = DevMon(**kw)
        if old is not None:
            if old.cost_model is not None and _monitor.cost_model is None:
                _monitor.cost_model = old.cost_model
            if old._hbm_live_fn is not None:
                _monitor._hbm_live_fn = old._hbm_live_fn
                _monitor._hbm_compiled_fn = old._hbm_compiled_fn
        return _monitor


def reset() -> DevMon:
    global _monitor
    with _monitor_lock:
        _monitor = DevMon()
        return _monitor


def note(kind: str, device_s: float, **kw):
    """Module shorthand for the engine's hot path (flightrec.record style)."""
    get().note(kind, device_s, **kw)
