"""Capacity & saturation observatory — the scaling-signal plane.

ROADMAP item 4 (serverless autoscaling, per DeepServe) scales on
queue-depth/shed-rate/p95 — but those are raw counters, not a signal:
nothing estimated offered load, nothing knew a replica's ceiling, and
nothing forecast time-to-saturation against the measured 5.5 s AOT
ready-time (BENCH_coldstart_r01). This module composes the primitives the
stack already has into the complete signal an autoscaler actuates on
(recommendation-only in this PR — no actuation):

1. **Offered load.** Every ``Engine.submit()`` outcome — admitted or shed —
   reports its requested decode budget here. Sliding 60 s / 5 m windows
   (slo.py's ``trim_window`` discipline) yield request and token arrival
   rates plus the admitted-vs-shed split. Offered load counts sheds: demand
   the admission controller turned away is still demand.

2. **Service capacity.** Sustained decode tok/s blended from devmon's
   roofline ceiling and the measured per-program throughput
   (``DevMon.service_rates()``): the measured rate is already degraded by
   DMA-wait (it divides real device seconds), the analytical roofline is an
   upper bound never fully achieved, so the ceiling sits ``ROOFLINE_BLEND``
   of the way between them, then degrades by a duty-cycle factor for the
   host gaps the dispatch loop pays between programs.

3. **Saturation.** Utilization = offered / ceiling; a Little's-law queue
   delay (queue depth ÷ service rate in requests/s); shed fraction over the
   window.

4. **Forecast.** Bucketed offered-load rates over the 5 m window feed an
   EWMA level and a least-squares trend slope → ``seconds_to_saturation``
   (capped at ``FORECAST_CAP_S``; 0.0 = saturated now), and
   ``recommended_replicas`` sized so the fleet absorbs the demand projected
   ``headroom_s`` ahead — headroom equal to the AOT manifest's measured
   ready-time, so a replica started on this signal is serving before the
   projection lands.

Surfaces: the six ``tpu_capacity_*`` gauges on BOTH /metrics routes
(written only by ``CapacityEstimator.export()`` — tpulint R11), a
``capacity`` block on /healthz relayed by the router's ~1 Hz poller into
``GET /debug/capacity``, and the tputop capacity panel.

Contracts, inherited from flightrec/slo/devmon: ``observe_submit`` is an
O(1) append under a short lock (seeded streams are byte-identical with the
estimator on or off); ``export()`` drops-not-fails (chaos fault
``capacity_export_error`` — a broken estimator costs one gauge refresh,
never a request or a /metrics render); every timestamp flows through an
injectable monotonic clock so forecasts are exact-arithmetic testable.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, Optional, Tuple

from aws_k8s_ansible_provisioner_tpu.serving.metrics import (
    Counter, Gauge, Registry)
from aws_k8s_ansible_provisioner_tpu.serving.slo import trim_window

# Rate window (headline gauges) and trend window (forecast slope).
WINDOW_S = 60.0
TREND_WINDOW_S = 300.0

# Trend resolution: offered-load rates are bucketed at this granularity
# before the EWMA/least-squares pass (raw per-submit timestamps would make
# the slope an artifact of arrival jitter, not of load growth).
TREND_BUCKET_S = 10.0

# EWMA weight per trend bucket (0.5: the level halves its memory every
# bucket — fast enough to track a ramp, slow enough to ignore one burst).
EWMA_ALPHA = 0.5

# Ceiling blend: how far the ceiling sits from measured toward the
# analytical roofline. The roofline is an upper bound never fully achieved;
# promising 25% of the remaining gap acknowledges optimization headroom
# without scaling the fleet against a number the chip has never hit.
ROOFLINE_BLEND = 0.25

# Assumed sustainable duty cycle: device tok/s -> wall tok/s degradation
# for the host gaps between dispatched programs. When the observed duty
# cycle exceeds it, the observation wins (the host demonstrably keeps the
# device busier than the assumption).
DUTY_FLOOR = 0.9

# Forecast cap: seconds_to_saturation at/above this means "no saturation
# within the horizon" — a finite sentinel keeps the gauge OpenMetrics-clean
# (no +Inf) and the dashboards sortable.
FORECAST_CAP_S = 3600.0

# Headroom the replica recommendation buys: the AOT registry's measured
# ready-time (BENCH_coldstart_r01 aot_ready_s — 13.4 s cold, 5.5 s AOT).
DEFAULT_HEADROOM_S = 5.5


class CapacityMetrics:
    """The tpu_capacity_* family. Registered here, rendered by BOTH
    /metrics routes, written only by CapacityEstimator.export()
    (tpulint R11)."""

    def __init__(self):
        r = Registry()
        self.registry = r
        self.offered_tps = r.register(Gauge(
            "tpu_capacity_offered_tps",
            "Offered decode load over the rate window, tokens/s of "
            "requested budget — admitted AND shed (demand, not service)"))
        self.ceiling_tps = r.register(Gauge(
            "tpu_capacity_ceiling_tps",
            "Estimated sustainable decode tokens/s for this replica "
            "(devmon measured throughput blended toward the roofline, "
            "degraded by the duty-cycle factor)"))
        self.utilization = r.register(Gauge(
            "tpu_capacity_utilization",
            "Offered load over the capacity ceiling (>= 1.0 = saturated; "
            "0 when the ceiling is still unknown)"))
        self.queue_delay_s = r.register(Gauge(
            "tpu_capacity_queue_delay_s",
            "Little's-law queue-delay estimate: admission queue depth "
            "over the ceiling-derived service rate in requests/s"))
        self.seconds_to_saturation = r.register(Gauge(
            "tpu_capacity_seconds_to_saturation",
            "EWMA + linear-trend forecast of when offered load crosses "
            "the ceiling (0 = saturated now; capped, cap = no saturation "
            "within the horizon)"))
        self.recommended_replicas = r.register(Gauge(
            "tpu_capacity_recommended_replicas",
            "Replicas of this class needed for the demand projected one "
            "AOT ready-time ahead (recommendation only — nothing actuates "
            "on it in-process)"))
        self.export_drops = r.register(Counter(
            "tpu_capacity_export_drops_total",
            "Gauge refreshes dropped because the estimator raised "
            "(drop-not-fail: the /metrics render proceeds with stale "
            "values)"))


metrics = CapacityMetrics()


class CapacityEstimator:
    """Per-replica offered-load / ceiling / saturation / forecast engine.

    ``clock`` is injectable (tests drive a fake); the lock guards only the
    submit deque, and no devmon or engine closure is ever called while it
    is held (locksan: no nested lock order against devmon's)."""

    MAX_SAMPLES = 100_000   # hard memory bound (drop-oldest via deque)

    def __init__(self, enabled: bool = True,
                 headroom_s: float = DEFAULT_HEADROOM_S,
                 window_s: float = WINDOW_S,
                 trend_window_s: float = TREND_WINDOW_S,
                 roofline_blend: float = ROOFLINE_BLEND,
                 duty_floor: float = DUTY_FLOOR,
                 clock: Callable[[], float] = time.monotonic):
        self.enabled = bool(enabled)
        self.headroom_s = max(0.0, float(headroom_s))
        self.window_s = float(window_s)
        self.trend_window_s = max(float(trend_window_s), self.window_s)
        self.roofline_blend = min(1.0, max(0.0, roofline_blend))
        self.duty_floor = min(1.0, max(0.0, duty_floor))
        self.clock = clock
        self._lock = threading.Lock()
        self._t0 = clock()
        # (t, tokens_requested, shed) — one entry per submit() outcome
        self._submits: Deque[Tuple[float, float, int]] = deque(
            maxlen=self.MAX_SAMPLES)
        # last submit() of any kind — the autoscaler's scale-to-zero idle
        # signal (None = never: idle since birth)
        self._last_submit_t: Optional[float] = None
        # saturation-calibrated ceiling (tok/s): EWMA of the admitted
        # token rate measured while admission was SHEDDING — ground truth
        # that overrides an optimistic analytical ceiling (0 = never
        # calibrated; see snapshot())
        self._observed_ceiling_tps: float = 0.0
        # engine wiring (installed by Engine._install_capacity)
        self._queue_depth_fn: Optional[Callable[[], int]] = None
        self._measured_tps_fn: Optional[Callable[[], float]] = None
        # service-rate source; default reads the process devmon (injectable
        # so tests hand-build the ceiling arithmetic)
        self._devmon_fn: Optional[Callable[[], dict]] = None

    # -- wiring --------------------------------------------------------------

    def install_engine(self, queue_depth_fn: Callable[[], int],
                       measured_tps_fn: Callable[[], float]):
        with self._lock:
            self._queue_depth_fn = queue_depth_fn
            self._measured_tps_fn = measured_tps_fn

    def install_devmon(self, devmon_fn: Callable[[], dict]):
        with self._lock:
            self._devmon_fn = devmon_fn

    # -- observation side (engine submit path; O(1), never blocks) ----------

    def observe_submit(self, tokens: float = 1.0, shed: bool = False):
        """Record one submit() outcome — admitted or shed — with its
        requested decode budget in tokens. Offered load counts both:
        demand the admission controller turned away is still demand."""
        if not self.enabled:
            return
        now = self.clock()
        with self._lock:
            self._last_submit_t = now
            self._submits.append((now, max(0.0, float(tokens)),
                                  1 if shed else 0))
            trim_window(self._submits, now, self.trend_window_s)

    # -- query side (deterministic at a fixed clock reading) -----------------

    def offered(self, now: Optional[float] = None,
                window_s: Optional[float] = None) -> dict:
        """Arrival rates over the window: requests/s, tokens/s, and the
        admitted-vs-shed split. Rates divide by the LIVE part of the
        window (a 10 s old estimator doesn't dilute its rate over 60 s)."""
        now = self.clock() if now is None else now
        window_s = self.window_s if window_s is None else window_s
        horizon = now - window_s
        n = shed = 0
        toks = shed_toks = 0.0
        with self._lock:
            for t, tok, s in reversed(self._submits):
                if t < horizon:
                    break
                n += 1
                toks += tok
                if s:
                    shed += 1
                    shed_toks += tok
        elapsed = max(min(window_s, now - self._t0), 1e-9)
        return {
            "window_s": window_s,
            "requests_per_s": n / elapsed,
            "tokens_per_s": toks / elapsed,
            "admitted_per_s": (n - shed) / elapsed,
            "shed_per_s": shed / elapsed,
            "shed_fraction": (shed / n) if n else 0.0,
            "avg_tokens_per_request": (toks / n) if n else 0.0,
        }

    def ceiling(self, now: Optional[float] = None) -> dict:
        """Sustainable decode tok/s: devmon's measured service rate
        blended ``roofline_blend`` of the way toward the analytical
        roofline, then degraded by the duty factor. Falls back to the
        engine's own tok/s gauge when devmon has no decode window yet."""
        with self._lock:
            devmon_fn = self._devmon_fn
            tps_fn = self._measured_tps_fn
        rates: dict = {}
        if devmon_fn is None:
            # late import: capacity must stay importable engine-free
            from aws_k8s_ansible_provisioner_tpu.serving import devmon
            try:
                rates = devmon.get().service_rates(now)
            except Exception:   # tpulint: disable=R3 drop-by-design — a broken devmon costs the ceiling one refresh (reads 0 / engine fallback), never a request
                rates = {}
        else:
            try:
                rates = dict(devmon_fn() or {})
            except Exception:   # tpulint: disable=R3 drop-by-design — same contract for an injected source
                rates = {}
        measured = float(rates.get("measured_tps") or 0.0)
        roofline = float(rates.get("roofline_tps") or 0.0)
        duty = float(rates.get("duty_cycle") or 0.0)
        source = "devmon"
        if measured <= 0.0 and tps_fn is not None:
            # no decode window yet: the engine's throughput gauge is the
            # only measurement; no roofline to blend toward
            try:
                measured = max(0.0, float(tps_fn() or 0.0))
            except Exception:   # tpulint: disable=R3 drop-by-design — a broken engine gauge reads 0, never fails the snapshot
                measured = 0.0
            roofline = measured
            source = "engine"
        if measured <= 0.0:
            return {"ceiling_tps": 0.0, "measured_tps": 0.0,
                    "roofline_tps": 0.0, "duty_factor": self.duty_floor,
                    "source": "none"}
        roofline = max(roofline, measured)
        blended = measured + self.roofline_blend * (roofline - measured)
        duty_factor = min(1.0, max(duty, self.duty_floor))
        return {"ceiling_tps": blended * duty_factor,
                "measured_tps": measured, "roofline_tps": roofline,
                "duty_factor": duty_factor, "source": source}

    def _trend_series(self, now: float) -> list:
        """Bucketed offered-token rates over the trend window, oldest
        first: [(bucket_mid_t, tokens_per_s), ...]. Buckets align to
        ``now``; the in-progress bucket is excluded (its rate would read
        low), and buckets predating the estimator are excluded (they were
        never observable, not observed-empty)."""
        start = now - self.trend_window_s
        with self._lock:
            samples = list(self._submits)
        n_buckets = int(self.trend_window_s / TREND_BUCKET_S)
        sums = [0.0] * n_buckets
        for t, tok, _ in samples:
            i = int((t - start) / TREND_BUCKET_S)
            if 0 <= i < n_buckets:
                sums[i] += tok
        series = []
        for i in range(n_buckets):
            lo = start + i * TREND_BUCKET_S
            if lo < self._t0 - 1e-9 or lo + TREND_BUCKET_S > now + 1e-9:
                continue
            series.append((lo + TREND_BUCKET_S / 2.0,
                           sums[i] / TREND_BUCKET_S))
        return series

    @staticmethod
    def _ewma_and_slope(series: list) -> Tuple[Optional[float], float]:
        """(EWMA level, least-squares slope tok/s per s) over the bucket
        series; (None, 0.0) when there is nothing to fit."""
        if not series:
            return None, 0.0
        level = series[0][1]
        for _, r in series[1:]:
            level = EWMA_ALPHA * r + (1.0 - EWMA_ALPHA) * level
        if len(series) < 2:
            return level, 0.0
        n = float(len(series))
        mx = sum(t for t, _ in series) / n
        my = sum(r for _, r in series) / n
        var = sum((t - mx) ** 2 for t, _ in series)
        if var <= 0.0:
            return level, 0.0
        cov = sum((t - mx) * (r - my) for t, r in series)
        return level, cov / var

    def snapshot(self, now: Optional[float] = None) -> dict:
        """The /healthz capacity block (and the /debug/capacity row)."""
        now = self.clock() if now is None else now
        off = self.offered(now)
        ceil_d = self.ceiling(now)
        ceiling = ceil_d["ceiling_tps"]
        ceiling_source = ceil_d["source"]
        # Saturation calibration: while admission is SHEDDING, the replica
        # is by definition serving at its real limit, so the admitted token
        # rate in that window is a measured ceiling — ground truth that
        # beats the roofline blend (wildly optimistic off-TPU, where a
        # ceiling too generous would report ~0 utilization while clients
        # eat 429s, pinning the fleet recommendation at its current size).
        admitted_tps = off["admitted_per_s"] * off["avg_tokens_per_request"]
        with self._lock:
            if off["shed_per_s"] > 0.0 and admitted_tps > 0.0:
                prior = self._observed_ceiling_tps
                self._observed_ceiling_tps = admitted_tps if prior <= 0.0 \
                    else EWMA_ALPHA * admitted_tps + (1 - EWMA_ALPHA) * prior
            observed = self._observed_ceiling_tps
        if 0.0 < observed < ceiling:
            ceiling = observed
            ceiling_source = "observed"
        offered_tps = off["tokens_per_s"]
        utilization = (offered_tps / ceiling) if ceiling > 0.0 else 0.0

        queue_depth = 0
        with self._lock:
            q_fn = self._queue_depth_fn
        if q_fn is not None:
            try:
                queue_depth = max(0, int(q_fn()))
            except Exception:   # tpulint: disable=R3 drop-by-design — a broken queue probe reads 0, never fails the snapshot
                queue_depth = 0
        avg_tok = off["avg_tokens_per_request"]
        if ceiling > 0.0 and avg_tok > 0.0:
            # Little's law: delay = L / mu, with mu in requests/s
            queue_delay_s = queue_depth * avg_tok / ceiling
        else:
            queue_delay_s = 0.0

        level, slope = self._ewma_and_slope(self._trend_series(now))
        if ceiling <= 0.0:
            # capacity unknown: no saturation claim either way
            sts = FORECAST_CAP_S
        elif offered_tps >= ceiling or (level is not None
                                        and level >= ceiling):
            sts = 0.0
        elif level is None or slope <= 1e-9:
            sts = FORECAST_CAP_S
        else:
            sts = min(FORECAST_CAP_S, (ceiling - level) / slope)

        projected = (level if level is not None else offered_tps) \
            + max(0.0, slope) * self.headroom_s
        if ceiling > 0.0 and projected > 0.0:
            recommended = max(1, math.ceil(projected / ceiling - 1e-9))
        else:
            recommended = 1
        with self._lock:
            last_submit = self._last_submit_t
        if last_submit is not None:
            last_submit_age = max(0.0, now - last_submit)
        else:
            # never submitted: idle for the estimator's whole life
            last_submit_age = max(0.0, now - self._t0)
        return {
            "enabled": self.enabled,
            "window_s": self.window_s,
            "trend_window_s": self.trend_window_s,
            "headroom_s": self.headroom_s,
            "last_submit_age_s": round(last_submit_age, 3),
            "idle": offered_tps <= 0.0,
            "offered": off,
            "offered_tps": offered_tps,
            "ceiling_tps": ceiling,
            "ceiling_source": ceiling_source,
            "measured_tps": ceil_d["measured_tps"],
            "roofline_tps": ceil_d["roofline_tps"],
            "duty_factor": ceil_d["duty_factor"],
            "utilization": utilization,
            "queue_depth": queue_depth,
            "queue_delay_s": queue_delay_s,
            "ewma_offered_tps": level if level is not None else 0.0,
            "trend_tps_per_s": slope,
            "projected_offered_tps": projected,
            "seconds_to_saturation": sts,
            "saturated": sts <= 0.0,
            "recommended_replicas": recommended,
        }

    def export(self) -> Optional[dict]:
        """Refresh every tpu_capacity_* gauge — the single writer site for
        the family (tpulint R11). Routes call this right before rendering;
        a raise here is swallowed and counted (drop-not-fail: the render
        proceeds with the previous values)."""
        try:
            from aws_k8s_ansible_provisioner_tpu.serving import chaos
            chaos.get().on_capacity_export()
            snap = self.snapshot()
            metrics.offered_tps.set(snap["offered_tps"])
            metrics.ceiling_tps.set(snap["ceiling_tps"])
            metrics.utilization.set(snap["utilization"])
            metrics.queue_delay_s.set(snap["queue_delay_s"])
            metrics.seconds_to_saturation.set(
                snap["seconds_to_saturation"])
            metrics.recommended_replicas.set(
                float(snap["recommended_replicas"]))
            return snap
        except Exception:   # tpulint: disable=R3 drop-by-design — the estimator can never fail a /metrics render; the drop is itself counted
            metrics.export_drops.inc()
            return None


# ---------------------------------------------------------------------------
# Module-level wiring: one estimator per process (the devmon pattern).
# ---------------------------------------------------------------------------

_estimator: Optional[CapacityEstimator] = None
_estimator_lock = threading.Lock()


def get() -> CapacityEstimator:
    global _estimator
    with _estimator_lock:
        if _estimator is None:
            _estimator = CapacityEstimator()
        return _estimator


def configure(**kw) -> CapacityEstimator:
    """Swap in a freshly-configured estimator, carrying over the wiring
    (engine closures + devmon source) the previous instance held —
    build_state configures AFTER the engine attaches."""
    global _estimator
    with _estimator_lock:
        old = _estimator
        _estimator = CapacityEstimator(**kw)
        if old is not None:
            _estimator._queue_depth_fn = old._queue_depth_fn
            _estimator._measured_tps_fn = old._measured_tps_fn
            _estimator._devmon_fn = old._devmon_fn
        return _estimator


def reset() -> CapacityEstimator:
    global _estimator
    with _estimator_lock:
        _estimator = CapacityEstimator()
        return _estimator
