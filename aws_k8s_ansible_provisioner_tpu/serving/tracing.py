"""Dependency-free distributed tracing for the serving path (W3C + OTLP).

The deploy stack has shipped a full trace pipeline since PR 0 — an OTEL
collector with an OTLP receiver forwarding to a Tempo backend
(deploy/otel-observability-setup.yaml:185-263) — but the serving path emitted
zero spans, so the backend ran dark (ROADMAP / VERDICT next #5). This module
is the missing producer, in the same zero-dependency idiom as the rest of the
serving stack (stdlib http.client, no opentelemetry-sdk):

- **W3C Trace Context**: :func:`parse_traceparent` / :func:`format_traceparent`
  speak the ``traceparent`` header (``00-<32hex>-<16hex>-<2hex>``), so the
  router's root context propagates through every dispatch hop into the server,
  and an upstream caller's own traceparent is continued rather than replaced.
- **Spans**: :class:`Tracer` creates spans with explicit start/end timestamps —
  phase children (queue-wait, prefill, decode) are built *retroactively* from
  the engine's Request timestamps, so the engine's hot loop never touches the
  tracer. Ids come from a seedable generator (``TPU_SERVE_TRACE_SEED`` or
  ``Tracer(seed=...)``) so tests can assert a byte-exact golden span tree.
- **Export**: :class:`OTLPHTTPExporter` batches finished spans on a background
  thread and POSTs OTLP/JSON to ``<endpoint>/v1/traces``. The queue is
  bounded and the failure mode is DROP: a dead/hanging/5xx-ing collector can
  never stall or fail a request — it only increments
  ``tpu_serve_spans_dropped_total`` (the same contract as the engine's
  load-shed counters: degradation is observable, never amplifying).

Engine Request timestamps are ``time.monotonic()``; OTLP wants unix nanos.
:func:`mono_ns` maps between the clocks through one (monotonic, wall) pair
captured at import, so all spans in a process share a consistent skew.
"""

from __future__ import annotations

import json
import os
import queue
import random
import threading
import time
import urllib.parse
from typing import Dict, List, Optional

from aws_k8s_ansible_provisioner_tpu.serving import chaos as _chaos
from aws_k8s_ansible_provisioner_tpu.serving.metrics import Counter, Registry

TRACEPARENT_HEADER = "traceparent"

# OTLP SpanKind enum values (trace.proto): the three the serving path uses.
KIND_INTERNAL = 1
KIND_SERVER = 2
KIND_CLIENT = 3

# The serving tree's ONLY sanctioned wall-clock reads (tpulint R1): every
# other site must use time.monotonic()/mono_ns — deadline or duration math
# on the wall clock breaks the moment NTP steps it. True wall-clock stamps
# (API ``created`` fields, span timestamps, log lines) route through these
# two helpers so the intent is explicit and greppable.


def wall_clock() -> float:
    """Current unix time in seconds — the explicit wall-clock stamp."""
    return time.time()


def wall_clock_ns() -> int:
    """Current unix time in nanoseconds — the explicit wall-clock stamp."""
    return time.time_ns()


# One (monotonic, wall) reference pair per process: every span derived from
# engine monotonic timestamps shares the same skew, so phase children never
# jitter against each other even if the wall clock steps mid-request.
_MONO_REF = time.monotonic()
_WALL_REF_NS = wall_clock_ns()


def mono_ns(t_mono: float) -> int:
    """Map a ``time.monotonic()`` reading onto the unix-nano timeline."""
    return _WALL_REF_NS + int((t_mono - _MONO_REF) * 1e9)


class TraceMetrics:
    """The tracing layer's own counters, rendered by BOTH the engine's and
    the router's /metrics routes (the subsystem is shared; its drop counter
    is the one signal that distinguishes 'collector outage' from 'tracing
    off')."""

    def __init__(self):
        self.registry = Registry()
        r = self.registry
        self.spans_dropped = r.register(Counter(
            "tpu_serve_spans_dropped_total",
            "Finished spans dropped instead of exported, by reason "
            "(queue_full = bounded queue at capacity; export_error = "
            "collector refused/hung/5xx'd — requests are never stalled "
            "either way)", ("reason",)))
        self.spans_exported = r.register(Counter(
            "tpu_serve_spans_exported_total",
            "Spans accepted by the OTLP endpoint"))
        self.export_failures = r.register(Counter(
            "tpu_serve_span_export_failures_total",
            "Failed OTLP export batches (each drops its spans)"))


# Process-wide: the exporter(s) and both /metrics routes share these.
metrics = TraceMetrics()


class SpanContext:
    """Identity that crosses process boundaries: (trace_id, span_id, sampled).

    ``trace_id`` is 32 lowercase hex chars, ``span_id`` 16 — the W3C wire
    widths, kept as strings end-to-end (they are echoed into response bodies
    and OTLP/JSON, both of which want hex text)."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def __repr__(self):
        return (f"SpanContext({self.trace_id}, {self.span_id}, "
                f"sampled={self.sampled})")


def parse_traceparent(value: Optional[str]) -> Optional[SpanContext]:
    """Parse a W3C ``traceparent`` header; None for absent/malformed.

    Malformed headers are treated as absent (a fresh trace starts) — the
    W3C-specified recovery; tracing must never 4xx a request."""
    if not value or not isinstance(value, str):
        return None
    parts = value.strip().lower().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id, flags = parts[0], parts[1], parts[2], parts[3]
    if version == "ff" or len(version) != 2:
        return None
    if len(trace_id) != 32 or len(span_id) != 16 or len(flags) != 2:
        return None
    try:
        int(version, 16)
        t = int(trace_id, 16)
        s = int(span_id, 16)
        f = int(flags, 16)
    except ValueError:
        return None
    if t == 0 or s == 0:    # all-zero ids are invalid per spec
        return None
    return SpanContext(trace_id, span_id, sampled=bool(f & 0x01))


def format_traceparent(ctx: SpanContext) -> str:
    """Render the context as a version-00 ``traceparent`` header value."""
    return f"00-{ctx.trace_id}-{ctx.span_id}-{'01' if ctx.sampled else '00'}"


class Span:
    """One timed operation. Mutable until :meth:`Tracer.finish`."""

    __slots__ = ("name", "context", "parent_span_id", "kind", "start_ns",
                 "end_ns", "attributes", "status", "status_message")

    def __init__(self, name: str, context: SpanContext,
                 parent_span_id: str = "", kind: int = KIND_INTERNAL,
                 start_ns: Optional[int] = None,
                 attributes: Optional[dict] = None):
        self.name = name
        self.context = context
        self.parent_span_id = parent_span_id
        self.kind = kind
        self.start_ns = wall_clock_ns() if start_ns is None else int(start_ns)
        self.end_ns: Optional[int] = None
        self.attributes: Dict[str, object] = dict(attributes or {})
        self.status = "unset"       # "unset" | "ok" | "error"
        self.status_message = ""

    def set_attribute(self, key: str, value) -> "Span":
        self.attributes[key] = value
        return self

    def error(self, message: str) -> "Span":
        self.status = "error"
        self.status_message = str(message)
        return self


class Tracer:
    """Span factory with W3C propagation and (optionally seeded) id
    generation. One instance per component (router / engine server) so each
    carries its own ``service.name`` resource, even in-process in tests."""

    def __init__(self, service_name: str = "tpu-serve",
                 exporter: Optional["OTLPHTTPExporter"] = None,
                 sample: float = 1.0, seed: Optional[int] = None):
        self.service_name = service_name
        self.exporter = exporter
        self.sample = max(0.0, min(1.0, float(sample)))
        # Deterministic ids for golden tests; os.urandom entropy otherwise
        # (replicas must not collide). The lock serializes the seeded RNG so
        # concurrent handler threads still draw a well-defined sequence.
        self._rng = random.Random(seed) if seed is not None else None
        self._lock = threading.Lock()

    def _hex(self, nbits: int) -> str:
        width = nbits // 4
        while True:
            if self._rng is not None:
                with self._lock:
                    v = self._rng.getrandbits(nbits)
            else:
                v = int.from_bytes(os.urandom(nbits // 8), "big")
            if v:           # the all-zero id is invalid on the wire
                return format(v, f"0{width}x")

    def _sampled(self) -> bool:
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        if self._rng is not None:
            with self._lock:
                return self._rng.random() < self.sample
        return int.from_bytes(os.urandom(4), "big") < self.sample * 2**32

    def start_span(self, name: str, parent: Optional[SpanContext] = None,
                   kind: int = KIND_INTERNAL,
                   attributes: Optional[dict] = None,
                   start_ns: Optional[int] = None) -> Span:
        """New span. With ``parent``, joins its trace and inherits its
        sampling decision (the W3C parent-based policy: the root decides
        once, the whole tree follows); without, starts a trace and decides
        by ``sample``."""
        if parent is not None:
            ctx = SpanContext(parent.trace_id, self._hex(64), parent.sampled)
            return Span(name, ctx, parent_span_id=parent.span_id, kind=kind,
                        start_ns=start_ns, attributes=attributes)
        ctx = SpanContext(self._hex(128), self._hex(64), self._sampled())
        return Span(name, ctx, kind=kind, start_ns=start_ns,
                    attributes=attributes)

    def finish(self, span: Span, end_ns: Optional[int] = None) -> Span:
        """Seal the span and hand it to the exporter (non-blocking, may
        drop). Unsampled spans are created-but-never-exported: their ids
        still flow into responses for log correlation."""
        if span.end_ns is None:
            span.end_ns = wall_clock_ns() if end_ns is None else int(end_ns)
        if span.end_ns < span.start_ns:
            span.end_ns = span.start_ns
        if self.exporter is not None and span.context.sampled:
            self.exporter.export(span, self.service_name)
        return span

    def emit_span(self, name: str, parent: SpanContext, start_ns: int,
                  end_ns: int, kind: int = KIND_INTERNAL,
                  attributes: Optional[dict] = None) -> Span:
        """Create-and-finish a retroactive span from explicit timestamps —
        how the server turns engine Request timings into phase children
        without the engine ever holding a tracer."""
        span = self.start_span(name, parent=parent, kind=kind,
                               attributes=attributes, start_ns=start_ns)
        return self.finish(span, end_ns=end_ns)


# ---------------------------------------------------------------------------
# OTLP/HTTP JSON export
# ---------------------------------------------------------------------------


def _attr_value(v) -> dict:
    """OTLP AnyValue JSON encoding (bool before int: bool is an int
    subclass)."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}     # proto JSON maps int64 to string
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def _encode_attrs(attrs: dict) -> List[dict]:
    return [{"key": k, "value": _attr_value(v)} for k, v in attrs.items()]


def encode_spans(items: List[tuple]) -> dict:
    """OTLP/JSON ExportTraceServiceRequest for (span, service_name) pairs,
    grouped into one resourceSpans entry per service."""
    by_service: Dict[str, List[Span]] = {}
    for span, service in items:
        by_service.setdefault(service, []).append(span)
    resource_spans = []
    for service, spans in by_service.items():
        encoded = []
        for s in spans:
            d = {
                "traceId": s.context.trace_id,
                "spanId": s.context.span_id,
                "name": s.name,
                "kind": s.kind,
                "startTimeUnixNano": str(s.start_ns),
                "endTimeUnixNano": str(s.end_ns or s.start_ns),
                "attributes": _encode_attrs(s.attributes),
            }
            if s.parent_span_id:
                d["parentSpanId"] = s.parent_span_id
            if s.status == "error":
                d["status"] = {"code": 2, "message": s.status_message}
            elif s.status == "ok":
                d["status"] = {"code": 1}
            encoded.append(d)
        resource_spans.append({
            "resource": {"attributes": _encode_attrs(
                {"service.name": service})},
            "scopeSpans": [{"scope": {"name": "tpu_serve.tracing"},
                            "spans": encoded}],
        })
    return {"resourceSpans": resource_spans}


class OTLPHTTPExporter:
    """Batching OTLP/HTTP JSON exporter: bounded queue, background thread,
    drop-on-failure.

    The request path only ever executes :meth:`export` — a lock-free
    ``put_nowait`` — so the worst a collector outage can cost a request is
    that enqueue. Everything that can block (connect, send, a chaos-injected
    hang) happens on the worker thread, and every failure converts to
    ``tpu_serve_spans_dropped_total`` instead of backpressure."""

    def __init__(self, endpoint: str, batch_size: int = 64,
                 flush_interval_s: float = 1.0, queue_max: int = 2048,
                 timeout_s: float = 5.0):
        u = urllib.parse.urlsplit(endpoint if "://" in endpoint
                                  else "http://" + endpoint)
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or 4318
        self.path = (u.path.rstrip("/") or "") + "/v1/traces"
        self.endpoint = endpoint
        self.batch_size = max(1, int(batch_size))
        self.flush_interval_s = float(flush_interval_s)
        self.timeout_s = float(timeout_s)
        self._q: "queue.Queue[tuple]" = queue.Queue(maxsize=max(1, queue_max))
        self._stop = threading.Event()
        self._busy = False          # worker holds a batch (flush() polls)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="otlp-exporter")
        self._thread.start()

    # -- request-path side ---------------------------------------------------

    def export(self, span: Span, service_name: str) -> bool:
        """Enqueue one finished span. Never blocks, never raises; a full
        queue drops the span and counts it."""
        try:
            self._q.put_nowait((span, service_name))
            return True
        except queue.Full:
            metrics.spans_dropped.inc(reason="queue_full")
            return False

    # -- worker side ---------------------------------------------------------

    def _run(self):
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=self.flush_interval_s)
            except queue.Empty:
                continue
            if first is None:       # shutdown sentinel
                break
            self._busy = True
            batch = [first]
            while len(batch) < self.batch_size:
                try:
                    item = self._q.get_nowait()
                except queue.Empty:
                    break
                if item is None:
                    self._stop.set()
                    break
                batch.append(item)
            try:
                self._send(batch)
                metrics.spans_exported.inc(len(batch))
            # tpulint: disable=R3 drop-by-design — a dead collector costs telemetry, never requests; failures are counted below
            except Exception:
                # Drop, count, carry on: a dead collector costs telemetry,
                # never requests. (Includes the chaos-injected refuse/hang/
                # 5xx faults — tests/test_chaos.py asserts this contract.)
                metrics.export_failures.inc()
                metrics.spans_dropped.inc(len(batch), reason="export_error")
            finally:
                self._busy = False

    def _send(self, batch: List[tuple]):
        import http.client

        ch = _chaos.get()
        if ch.enabled:
            ch.on_span_export()     # fault point: refuse / hang / 5xx
        body = json.dumps(encode_spans(batch)).encode()
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout_s)
        try:
            conn.request("POST", self.path, body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read()
            if resp.status >= 400:
                raise RuntimeError(f"OTLP endpoint answered {resp.status}")
        finally:
            conn.close()

    # -- lifecycle -----------------------------------------------------------

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Best-effort wait until the queue is drained and no batch is in
        flight (tests; the request path never calls this)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._q.empty() and not self._busy:
                return True
            time.sleep(0.01)
        return False

    def shutdown(self, timeout_s: float = 2.0):
        self.flush(timeout_s)
        self._stop.set()
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass
        self._thread.join(timeout=timeout_s)


# ---------------------------------------------------------------------------
# Module-level wiring
# ---------------------------------------------------------------------------

_default_tracer: Optional[Tracer] = None
_default_lock = threading.Lock()


def build_tracer(service_name: str, endpoint: Optional[str] = None,
                 sample: float = 1.0,
                 seed: Optional[int] = None) -> Tracer:
    """Assemble a tracer for one component. ``endpoint`` falls back to
    ``$OTEL_EXPORTER_OTLP_ENDPOINT`` (the standard env the serving manifest
    sets from ansible_vars); empty = spans are created (ids echo into
    responses) but never exported. ``seed`` falls back to
    ``$TPU_SERVE_TRACE_SEED`` for reproducible harnesses."""
    if endpoint is None:
        endpoint = os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT", "")
    if seed is None:
        raw = os.environ.get("TPU_SERVE_TRACE_SEED", "")
        if raw:
            try:
                seed = int(raw)
            except ValueError:
                seed = None
    exporter = OTLPHTTPExporter(endpoint) if endpoint else None
    return Tracer(service_name, exporter=exporter, sample=sample, seed=seed)


def configure(service_name: str = "tpu-serve",
              endpoint: Optional[str] = None, sample: float = 1.0,
              seed: Optional[int] = None) -> Tracer:
    """Build and install the process-default tracer (components that carry
    their own Tracer — router, server — don't need this)."""
    global _default_tracer
    tracer = build_tracer(service_name, endpoint=endpoint, sample=sample,
                          seed=seed)
    with _default_lock:
        _default_tracer = tracer
    return tracer


def get_tracer() -> Tracer:
    """The process-default tracer; lazily a no-export tracer honoring
    ``$OTEL_EXPORTER_OTLP_ENDPOINT`` when set."""
    global _default_tracer
    with _default_lock:
        if _default_tracer is None:
            _default_tracer = build_tracer("tpu-serve")
        return _default_tracer
