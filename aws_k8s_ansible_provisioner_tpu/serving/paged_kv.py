"""True paged KV cache: page pool + block tables + host allocator.

The slot-contiguous cache (serving/kv_cache.py) reserves ``max_len`` rows per
slot forever — HBM cost is ``slots x window`` regardless of actual lengths, so
a 16 GB chip tops out near 128-192 concurrent kilotoken windows (VERDICT r2
missing #2). The vLLM engine the reference delegates to (SURVEY.md §2.2 row 1,
/root/reference/llm-d-deploy.yaml:176-193) allocates KV *blocks on demand*,
admitting far more concurrent short requests from the same HBM. This module is
the TPU-native equivalent:

- **Page pool**: ``k, v : [L, P, Hkv, page, D]`` (+ per-row scale leaves
  ``ks, vs : [L, P, Hkv, page]`` when int8) — P physical pages shared by all
  slots, allocated once at startup (XLA static shapes; capacity planning picks
  P, not per-slot reservations).
- **Block tables**: host numpy ``[num_slots, max_pages_per_slot]`` int32 of
  physical page ids, passed to each step program as a device array; the
  Pallas kernels read it via scalar prefetch and fetch page
  ``table[slot, logical_chunk]`` instead of the identity mapping
  (ops/pallas_attention.py paged variants).
- **Host allocator** (:class:`PagePool`): free list + per-page refcounts +
  a content-hash index over FULL pages for prefix reuse (vLLM's automatic
  prefix caching at page granularity — a new prompt whose leading full pages
  hash-match resident pages just bumps refcounts and prefills only the tail).
  Freed requests' pages go to an LRU *evictable* pool keyed by that hash, so
  capacity is never held hostage by dead requests, yet follow-up turns still
  hit. O(n_pages) lookup per prompt, independent of slot count (VERDICT r2
  weak #5 / next #8 — replaces the O(slots x prompt_len) token scan).

Layout note: pages keep the head-major ``[Hkv, page, D]`` inner layout of the
slot-contiguous design, so each Pallas grid step still DMAs one head-contiguous
block and the MXU matmul shape is unchanged — the ONLY difference between
dense and paged decode is which physical block the index_map picks. page_size
must satisfy the same Mosaic tiling rules as the dense chunk (multiple of 8
for bf16, 32 for int8; the int8 scale block spans the full page axis, which is
always legal).
"""

from __future__ import annotations

import collections
import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from aws_k8s_ansible_provisioner_tpu.config import ModelConfig
from aws_k8s_ansible_provisioner_tpu.serving.kv_cache import quantize_rows

# Drop sentinel for page-table entries that must never be written (padding
# rows of a batched prefill, out-of-window rows). Must be a LARGE POSITIVE
# id: jnp scatters treat negative indices as wrapped (in-bounds!) — a -1
# would silently write the pool's last page — while indices >= the pool size
# are dropped by mode='drop'.
OOB_PAGE = np.int32(2**31 - 1)


def init_pool(cfg: ModelConfig, num_pages: int, page_size: int,
              dtype=jnp.bfloat16, quant: bool = False) -> dict:
    """Allocate the physical page pool. Leaves carry a leading [L] axis."""
    shape = (cfg.num_layers, num_pages, cfg.num_kv_heads, page_size,
             cfg.head_dim)
    if quant:
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "ks": jnp.zeros(shape[:-1], jnp.float32),
            "vs": jnp.zeros(shape[:-1], jnp.float32),
        }
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def pool_bytes(cfg: ModelConfig, num_pages: int, page_size: int,
               dtype=jnp.bfloat16, quant: bool = False) -> int:
    rows = 2 * cfg.num_layers * num_pages * page_size * cfg.num_kv_heads
    if quant:
        return rows * (cfg.head_dim + 4)
    return rows * cfg.head_dim * jnp.dtype(dtype).itemsize


def _write_kv(pool: dict, update, k_val: jnp.ndarray, v_val: jnp.ndarray) -> dict:
    """Mirror of kv_cache._write_kv for the pool layout: one indexing
    expression updates k/v (and, quantized, the scale leaves — whose target is
    the row target minus the trailing head_dim axis)."""
    if "ks" in pool:
        k_val, ks = quantize_rows(k_val)
        v_val, vs = quantize_rows(v_val)
        return {"k": update(pool["k"], k_val), "v": update(pool["v"], v_val),
                "ks": update(pool["ks"], ks), "vs": update(pool["vs"], vs)}
    return {"k": update(pool["k"], k_val), "v": update(pool["v"], v_val)}


# ---------------------------------------------------------------------------
# XLA writers (fallback + prefill paths). All take PHYSICAL page ids computed
# from the slot's block table on the host or in-program from a table array.
# ---------------------------------------------------------------------------


def write_prompt_paged(pool_l: dict, pages: jnp.ndarray, k: jnp.ndarray,
                       v: jnp.ndarray, page_size: int) -> dict:
    """Write one prefilled prompt's K/V across its pages (single layer slice).

    pool_l: {'k','v': [P, Hkv, page, D]}; pages: [max_pages] int32 physical
    page ids for the destination slot; k/v: [1, T, Hkv, D] where T is the
    BUCKET width, usually > the true prompt length.

    Token t lands at (pages[t // page_size], t % page_size): one scatter with
    advanced indices on (page, row), the head axis broadcast between them —
    the same mode='drop' contract as the dense batched writer (OOB_PAGE ids
    drop). CONTRACT: padded rows past the true prompt DO write through the
    table, so every entry of ``pages`` must name either a page owned by this
    slot or the engine's scratch page — never another slot's page (the
    engine keeps unallocated table entries at scratch page 0; padding
    garbage then lands in the slot's own partial tail page — rows >= the
    true length, which reads mask and sharing never indexes — or in
    scratch).
    """
    T = k.shape[1]
    tok = jnp.arange(T, dtype=jnp.int32)
    pg = pages[tok // page_size]                       # [T]
    off = tok % page_size
    return _write_kv(
        pool_l,
        lambda arr, val: arr.at[pg, :, off].set(val, mode="drop"),
        k[0], v[0])


def write_prompts_paged(pool_l: dict, tables: jnp.ndarray, k: jnp.ndarray,
                        v: jnp.ndarray, page_size: int) -> dict:
    """Batched prompt write: N prompts into their pages in one scatter.

    pool_l: {'k','v': [P, Hkv, page, D]}; tables: [N, max_pages] int32 (row n
    = destination pages of prompt n; PADDING rows of a power-of-two prefill
    batch carry OOB_PAGE everywhere and drop); k/v: [N, T, Hkv, D]. Same
    contract as :func:`write_prompt_paged`: rows padded past each prompt's
    true length write through the table, so entries past a prompt's own
    pages must be scratch/own pages, never another slot's.
    """
    N, T = k.shape[:2]
    tok = jnp.arange(T, dtype=jnp.int32)
    pg = tables[:, tok // page_size]                   # [N, T]
    off = jnp.broadcast_to(tok % page_size, (N, T))
    return _write_kv(
        pool_l,
        lambda arr, val: arr.at[pg, :, off].set(val, mode="drop"),
        k, v)


def write_prompts_paged_layer(pool: dict, layer, tables: jnp.ndarray,
                              k: jnp.ndarray, v: jnp.ndarray,
                              page_size: int) -> dict:
    """FULL-pool ([L, P, ...] leaves) variant of :func:`write_prompts_paged`
    for the scan-CARRY prefill path (round 5): the pool stays in the layer
    scan's carry — XLA's loop-carry aliasing keeps it in place — instead of
    streaming xs→ys, whose re-stack held a second full-size pool buffer in
    the compiled program (the batch-128 paged HBM OOM recorded in
    BENCH_session_r5.stderr.txt; the dense cache's simpler scatter pattern
    aliased and survived). Same index/drop contract as the per-layer form,
    with the scalar ``layer`` leading the scatter."""
    N, T = k.shape[:2]
    tok = jnp.arange(T, dtype=jnp.int32)
    pg = tables[:, tok // page_size]                   # [N, T]
    off = jnp.broadcast_to(tok % page_size, (N, T))
    return _write_kv(
        pool,
        lambda arr, val: arr.at[layer, pg, :, off].set(val, mode="drop"),
        k, v)


def write_chunk_paged_layer(pool: dict, layer, pages: jnp.ndarray,
                            start, k: jnp.ndarray, v: jnp.ndarray,
                            page_size: int) -> dict:
    """FULL-pool variant of :func:`write_chunk_paged` (carry prefill path —
    see write_prompts_paged_layer). k/v: [1, C, Hkv, D]."""
    C = k.shape[1]
    rows = start + jnp.arange(C, dtype=jnp.int32)      # [C]
    idx = rows // page_size
    valid = idx < pages.shape[0]
    pg = jnp.where(valid, pages[jnp.clip(idx, 0, pages.shape[0] - 1)],
                   OOB_PAGE)
    off = rows % page_size
    return _write_kv(
        pool,
        lambda arr, val: arr.at[layer, pg, :, off].set(val, mode="drop"),
        k[0], v[0])


def write_chunk_paged(pool_l: dict, pages: jnp.ndarray, start: jnp.ndarray,
                      k: jnp.ndarray, v: jnp.ndarray, page_size: int) -> dict:
    """Write one prefill CHUNK's rows [start, start+C) across pages.

    pool_l: {'k','v': [P, Hkv, page, D]}; pages: [max_pages] int32 for the
    slot; start: scalar row offset; k/v: [1, C, Hkv, D]. Rows past max_pages *
    page_size drop (mode='drop' via clamped gather producing OOB_PAGE).
    """
    C = k.shape[1]
    rows = start + jnp.arange(C, dtype=jnp.int32)      # [C]
    idx = rows // page_size
    valid = idx < pages.shape[0]
    pg = jnp.where(valid, pages[jnp.clip(idx, 0, pages.shape[0] - 1)],
                   OOB_PAGE)
    off = rows % page_size
    return _write_kv(
        pool_l,
        lambda arr, val: arr.at[pg, :, off].set(val, mode="drop"),
        k[0], v[0])


def write_token_layer_paged(pool: dict, layer: jnp.ndarray,
                            lengths: jnp.ndarray, table: jnp.ndarray,
                            k: jnp.ndarray, v: jnp.ndarray,
                            page_size: int) -> dict:
    """Scatter one new token per slot into the FULL pool at a given layer
    (XLA fallback for the Pallas paged row-write kernel).

    pool: {'k','v': [L, P, Hkv, page, D]}; layer: scalar; lengths: [B] row
    index per slot; table: [B, max_pages] int32; k/v: [B, 1, Hkv, D]. Rows
    outside [0, max_pages*page_size) drop — the surplus-write invariant.
    """
    B = k.shape[0]
    idx = lengths // page_size
    valid = (lengths >= 0) & (idx < table.shape[1])
    pg = jnp.where(valid,
                   table[jnp.arange(B), jnp.clip(idx, 0, table.shape[1] - 1)],
                   OOB_PAGE)
    off = jnp.where(valid, lengths % page_size, 0)
    return _write_kv(
        pool,
        lambda arr, val: arr.at[layer, pg, :, off].set(val, mode="drop"),
        k[:, 0], v[:, 0])


def gather_slot(pool_l: dict, pages: jnp.ndarray, page_size: int,
                name: str) -> jnp.ndarray:
    """Materialize one slot's logical [Hkv, S_v, D] view from its pages
    (S_v = len(pages) * page_size). Prefill-only helper (chunk attention
    reads the cached prefix); the decode kernels never gather.
    """
    arr = pool_l[name][pages]                    # [n, Hkv, page, (D)]
    arr = jnp.moveaxis(arr, 1, 0)                # [Hkv, n, page, (D)]
    return arr.reshape((arr.shape[0], -1) + arr.shape[3:])


def gather_layer_dense(pool: dict, layer, table: jnp.ndarray) -> dict:
    """One layer's logical dense view from the pool (XLA-fallback decode):
    {name: [B, Hkv, S_v, (D)]}. Test/CPU path only — a full gather per step
    is exactly what the Pallas paged kernels avoid."""
    out = {}
    for name, arr in pool.items():
        al = jax.lax.dynamic_index_in_dim(arr, layer, 0, keepdims=False)
        g = al[table]                            # [B, n, Hkv, page, (D)]
        g = jnp.moveaxis(g, 2, 1)                # [B, Hkv, n, page, (D)]
        out[name] = g.reshape(g.shape[:2] + (-1,) + g.shape[4:])
    return out


def gather_dense(pool: dict, table: jnp.ndarray, page_size: int) -> dict:
    """Whole logical [L, B, Hkv, S_v, (D)] cache from the pool — a stack of
    :func:`gather_layer_dense` slices, so the pool layout has exactly one
    decoding (tests compare paged results against dense references through
    this)."""
    L = pool["k"].shape[0]
    layers = [gather_layer_dense(pool, jnp.int32(l), table) for l in range(L)]
    return {name: jnp.stack([g[name] for g in layers]) for name in pool}


# ---------------------------------------------------------------------------
# Host tier (tier-2 KV): spill/restore of whole pages across PCIe
# ---------------------------------------------------------------------------


def gather_pages(pool: dict, pages: Sequence[int]) -> dict:
    """Enqueue a device-side gather of whole physical pages for spilling.

    pool: FULL-pool leaves ``[L, P, ...]``; pages: global physical ids.
    Returns ``{name: [L, k, Hkv, page, (D)]}`` — eager jnp ops only, so this
    just enqueues device work without blocking the dispatch thread (R8-safe);
    the actual PCIe copy is started with ``copy_to_host_async`` and settled
    lazily by :meth:`HostTier.flush_to_host` at the next sanctioned block
    point. The gather is enqueued BEFORE any program that overwrites the
    reclaimed pages, so XLA's data-dependency ordering guarantees it reads
    the pre-reclaim content.
    """
    idx = jnp.asarray(list(pages), jnp.int32)
    return {name: jnp.take(arr, idx, axis=1) for name, arr in pool.items()}


@functools.partial(jax.jit, donate_argnums=(0,))
def _restore_scatter(pool: dict, pages: jnp.ndarray, data: dict) -> dict:
    return {name: arr.at[:, pages].set(data[name], mode="drop")
            for name, arr in pool.items()}


def restore_pages(pool: dict, pages: Sequence[int], data: dict) -> dict:
    """Scatter host-tier page payloads back into freshly allocated pages.

    pool: FULL-pool leaves (donated — the scatter is in place, no second
    pool-sized buffer); pages: global physical ids; data: ``{name:
    [L, k, Hkv, page, (D)]}`` stacked page payloads in the same per-page
    layout ``write_prompts_paged_layer`` produces. The page axis is padded to
    the next power of two with ``OOB_PAGE`` ids (dropped by the scatter) so
    restore bursts of any size hit a log-bounded set of compiled programs.
    """
    k = len(pages)
    width = 1
    while width < k:
        width *= 2
    pg = np.full(width, OOB_PAGE, np.int32)
    pg[:k] = list(pages)
    padded = {}
    for name, arr in data.items():
        if arr.shape[1] != width:
            pad = [(0, 0)] * arr.ndim
            pad[1] = (0, width - arr.shape[1])
            arr = jnp.pad(jnp.asarray(arr), pad)
        padded[name] = jnp.asarray(arr)
    return _restore_scatter(pool, jnp.asarray(pg), padded)


class HostTier:
    """Byte-budgeted host-RAM store of spilled KV pages, keyed by chain hash.

    Tier-2 of the cache hierarchy: when the HBM LRU reclaims an evictable
    page, the engine gathers its per-layer K/V and parks it here; a later
    prompt whose prefix chain walks past the resident pages can restore the
    host extension with a batched ``device_put`` instead of re-prefilling
    (arxiv 2504.11816: restore is bandwidth-bound and far cheaper than
    recompute). Entries are whole fixed-shape pages — the transfer path is
    static (SnapStream, arxiv 2511.03092) and rides the existing page layout.

    Entry data values start life as device arrays (the async gather's
    output) with ``copy_to_host_async`` already issued; ``flush_to_host``
    converts them to numpy at the next sanctioned block point, releasing the
    HBM. Eviction is LRU by bytes. Content is verified on fetch: token
    mismatch, wrong shapes/dtypes, or truncation (chaos ``kv_offload_error``)
    drop the entry — the caller falls back to re-prefill, never to wrong
    tokens.
    """

    def __init__(self, budget_bytes: int):
        if budget_bytes <= 0:
            raise ValueError("HostTier needs a positive byte budget")
        self.budget_bytes = int(budget_bytes)
        self.used_bytes = 0
        # chain key -> {"tokens": tuple, "data": {name: array}, "nbytes": int}
        self._entries: collections.OrderedDict = collections.OrderedDict()
        self._unflushed: List[Tuple] = []     # keys whose data is on-device
        self.spilled_pages = 0
        self.spilled_bytes = 0
        self.restored_pages = 0
        self.restored_bytes = 0
        self.dropped_lru = 0        # evicted by byte pressure
        self.dropped_invalid = 0    # failed verification on fetch

    def __len__(self) -> int:
        return len(self._entries)

    def put(self, key: Tuple, tokens: Tuple, data: dict, nbytes: int):
        """Insert/refresh one spilled page; evicts LRU entries over budget."""
        old = self._entries.pop(key, None)
        if old is not None:
            self.used_bytes -= old["nbytes"]
        self._entries[key] = {"tokens": tokens, "data": data,
                              "nbytes": int(nbytes)}
        self._unflushed.append(key)
        self.used_bytes += int(nbytes)
        self.spilled_pages += 1
        self.spilled_bytes += int(nbytes)
        while self.used_bytes > self.budget_bytes and self._entries:
            _, dropped = self._entries.popitem(last=False)   # LRU front
            self.used_bytes -= dropped["nbytes"]
            self.dropped_lru += 1

    def contains(self, key: Tuple, tokens: Tuple) -> bool:
        """Cheap membership + token verification (no LRU bump, no payload
        checks — :meth:`fetch` is the authority at restore time)."""
        e = self._entries.get(key)
        return e is not None and e["tokens"] == tokens

    def fetch(self, key: Tuple, tokens: Tuple,
              shapes: Dict[str, Tuple]) -> Optional[dict]:
        """Return a verified entry's payload (LRU-bumped), or None.

        ``shapes`` maps leaf name -> expected per-page shape
        ``[L, Hkv, page, (D)]``. A corrupted or truncated entry (chaos
        ``kv_offload_error``) fails the shape check, is dropped from the
        tier, and the caller re-prefills that span — drop, never corrupt.
        """
        e = self._entries.get(key)
        if e is None:
            return None
        data = e["data"]
        ok = (e["tokens"] == tokens
              and set(data.keys()) == set(shapes.keys())
              and all(tuple(data[n].shape) == tuple(shapes[n])
                      for n in shapes))
        if not ok:
            del self._entries[key]
            self.used_bytes -= e["nbytes"]
            self.dropped_invalid += 1
            return None
        self._entries.move_to_end(key)
        return data

    def note_restored(self, pages: int, nbytes: int):
        self.restored_pages += pages
        self.restored_bytes += nbytes

    def corrupt(self, key: Tuple):
        """Chaos hook (``kv_offload_error``): truncate an entry's payload in
        place so the next :meth:`fetch` fails verification and drops it."""
        e = self._entries.get(key)
        if e is not None:
            e["data"] = {n: a[:-1] for n, a in e["data"].items()}

    def flush_to_host(self):
        """Convert device-resident payloads to numpy, releasing their HBM.
        Called from sanctioned block points only — the ``copy_to_host_async``
        issued at spill time has normally landed by now, making this cheap."""
        for key in self._unflushed:
            e = self._entries.get(key)
            if e is None:
                continue
            e["data"] = {n: np.asarray(a) for n, a in e["data"].items()}
        self._unflushed = []

    def stats(self) -> dict:
        return {
            "budget_bytes": self.budget_bytes,
            "used_bytes": self.used_bytes,
            "entries": len(self._entries),
            "spilled_pages": self.spilled_pages,
            "spilled_bytes": self.spilled_bytes,
            "restored_pages": self.restored_pages,
            "restored_bytes": self.restored_bytes,
            "dropped_lru": self.dropped_lru,
            "dropped_invalid": self.dropped_invalid,
        }


# ---------------------------------------------------------------------------
# Host allocator
# ---------------------------------------------------------------------------


class PagePool:
    """Host-side physical page allocator with refcounts + prefix-hash reuse.

    The device never sees this object — it only sees the block tables the
    engine builds from it. Thread-compat: engine calls are already serialized
    by the scheduler thread.

    States of a physical page:
      free       — on ``_free``, content meaningless.
      live       — refcount > 0 (referenced by >= 1 slot's table).
      evictable  — refcount 0 but content retained, indexed by its chain hash
                   in ``_hash_to_page`` and sitting in the LRU ``_evictable``;
                   reusable instantly on a prefix hit, reclaimed from the LRU
                   front when the free list runs dry.

    Prefix hashing: a FULL page holding tokens[p*ps:(p+1)*ps] of some prompt
    is keyed by hash((parent_key, those tokens)) — the chain makes the key
    depend on the whole prefix, so equal keys mean equal full prefixes
    (modulo hash collisions: we store the page's own tokens and verify on
    hit). Partial (tail) pages are never shared.
    """

    def __init__(self, num_pages: int, page_size: int, first_page: int = 0):
        """``first_page`` reserves pages [0, first_page) out of circulation —
        the engine keeps page 0 as the SCRATCH page every idle slot's table
        points at (decode dispatches write one garbage row for every slot at
        its current length; idle slots' land at scratch row 0 instead of in
        pages another slot may now own)."""
        if num_pages <= first_page or page_size <= 0 or first_page < 0:
            raise ValueError("invalid pool geometry")
        self.num_pages = num_pages
        self.first_page = first_page
        self.page_size = page_size
        self._free: collections.deque = collections.deque(
            range(first_page, num_pages))
        self._ref = np.zeros(num_pages, np.int32)
        # Fault-injection hook (serving/chaos.py "page_exhaustion"): while
        # positive, alloc() refuses and decrements — a logically-dry pool
        # with deterministic healing, driving the engine's requeue/preempt
        # degradation paths without filling real HBM.
        self.fail_next_allocs = 0
        # page id -> (chain_key, tokens tuple) for hash-indexed pages
        self._page_key: Dict[int, Tuple] = {}
        # chain key -> page id (latest content wins)
        self._hash_to_page: Dict[Tuple, int] = {}
        # LRU of evictable pages: OrderedDict page_id -> None
        self._evictable: collections.OrderedDict = collections.OrderedDict()
        # Tier-2 spill plumbing (engine-owned). When a HostTier is attached,
        # every hash-indexed page the LRU reclaims is recorded here as
        # (local_pid, chain_key, tokens); the ENGINE drains the log right
        # after the allocation burst — before any program can overwrite the
        # page — gathers the content and parks it in the tier. The pool
        # itself never touches the device.
        self.host_tier: Optional["HostTier"] = None
        self.evicted_log: List[Tuple[int, Tuple, Tuple]] = []

    # -- capacity ----------------------------------------------------------

    @property
    def free_pages(self) -> int:
        """Pages allocatable right now (free list + evictable)."""
        return len(self._free) + len(self._evictable)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - self.first_page - self.free_pages

    # -- allocation --------------------------------------------------------

    def _pop_physical(self) -> Optional[int]:
        if self._free:
            return self._free.popleft()
        if self._evictable:
            pid, _ = self._evictable.popitem(last=False)   # LRU front
            if self.host_tier is not None and pid in self._page_key:
                key, toks = self._page_key[pid]
                self.evicted_log.append((pid, key, toks))
            self._drop_index(pid)
            return pid
        return None

    def _drop_index(self, pid: int):
        key = self._page_key.pop(pid, None)
        if key is not None and self._hash_to_page.get(key[0]) == pid:
            del self._hash_to_page[key[0]]

    def alloc(self, n: int = 1) -> Optional[List[int]]:
        """Allocate n pages (refcount 1 each), or None if not enough."""
        if self.fail_next_allocs > 0:
            self.fail_next_allocs -= 1
            return None
        if n > self.free_pages:
            return None
        out = []
        for _ in range(n):
            pid = self._pop_physical()
            assert pid is not None
            self._ref[pid] = 1
            out.append(pid)
        return out

    def retain(self, pid: int):
        """Take an extra reference on a live or evictable page."""
        if self._ref[pid] == 0:
            # leaving the evictable pool, keep its hash index (still valid)
            self._evictable.pop(pid, None)
        self._ref[pid] += 1

    def release(self, pid: int):
        """Drop one reference; at zero the page becomes evictable (if hash-
        indexed) or free."""
        assert self._ref[pid] > 0, pid
        self._ref[pid] -= 1
        if self._ref[pid] == 0:
            if pid in self._page_key:
                self._evictable[pid] = None
                self._evictable.move_to_end(pid)
            else:
                self._free.append(pid)

    def release_all(self, pids: Sequence[int]):
        for pid in pids:
            self.release(pid)

    # -- prefix hashing ----------------------------------------------------

    @staticmethod
    def chain_key(parent_key, tokens: Tuple) -> Tuple:
        """Stable chain hash key for a full page holding ``tokens`` whose
        prefix chain is ``parent_key`` (None for the first page)."""
        return (hash((parent_key, tokens)),)

    def index_page(self, pid: int, parent_key, tokens: Tuple):
        """Register a LIVE full page's content for future prefix reuse."""
        key = self.chain_key(parent_key, tokens)
        self._drop_index(pid)       # replace any stale identity
        self._page_key[pid] = (key, tokens)
        self._hash_to_page[key] = pid
        return key

    def lookup_prefix(self, prompt: Sequence[int],
                      salt=None) -> Tuple[List[int], int, List[Tuple]]:
        """Two-level longest-prefix match: resident chain + host extension.

        Returns ``(page_ids, n_tokens, host_keys)``. Walks page-by-page —
        O(n_pages) hash probes with token verification, independent of slot
        count (VERDICT r2 weak #5). Only complete pages match; the caller
        re-prefills the tail. ``host_keys`` continues the chain walk into the
        attached :class:`HostTier` (empty without one): the chain keys of
        host-restorable pages extending the resident match, in prefix order —
        the engine restores those into fresh pages so the chunk program
        prefills only the suffix past the restored frontier. Matched resident
        pages are NOT retained — callers must ``retain`` each page they
        actually use before any other allocation can evict it.

        ``salt`` seeds the hash chain: pages written under different salts
        (e.g. different LoRA adapters — their K/V projections differ even
        for equal tokens) can never cross-match (review r5).
        """
        ps = self.page_size
        pages: List[int] = []
        parent = salt
        full = len(prompt) // ps
        p = 0
        while p < full:
            toks = tuple(prompt[p * ps:(p + 1) * ps])
            key = self.chain_key(parent, toks)
            pid = self._hash_to_page.get(key)
            if pid is None or self._page_key.get(pid, (None, None))[1] != toks:
                break
            pages.append(pid)
            parent = key
            p += 1
        host: List[Tuple] = []
        if self.host_tier is not None:
            while p < full:
                toks = tuple(prompt[p * ps:(p + 1) * ps])
                key = self.chain_key(parent, toks)
                if not self.host_tier.contains(key, toks):
                    break
                host.append(key)
                parent = key
                p += 1
        return pages, len(pages) * ps, host

    def stats(self) -> dict:
        out = {
            "pages_total": self.num_pages - self.first_page,
            "pages_free": len(self._free),
            "pages_evictable": len(self._evictable),
            "pages_live": int((self._ref > 0).sum()),
        }
        if self.host_tier is not None:
            out["host_tier"] = self.host_tier.stats()
        return out
