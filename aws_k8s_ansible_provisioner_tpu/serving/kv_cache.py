"""Decode KV cache, laid out for TPU HBM and XLA static shapes.

The reference's KV cache lives inside the external vLLM container (paged attention
over CUDA kernels; SURVEY.md §2.2 row 1). The TPU-native equivalent here uses a
**slot-contiguous, head-major** layout: one fixed region per decode slot,

    k, v : [num_layers, num_slots, num_kv_heads, max_len, head_dim]   (bf16)

which is exactly a paged cache whose per-slot block table is the identity —
``max_len/page_size`` pages per (slot, head), page p of slot b head h at
``k[:, b, h, p*page_size:(p+1)*page_size]``. This buys:

- static shapes (XLA compiles one decode program, no re-specialization),
- in-place row writes via scatter-at-index (donated buffers, zero copies),
- attention that reads the cache *in place* (no gather of pages, no repeat_kv
  materialization — see ops/attention.py),
- **head-contiguous K/V streams**: the Pallas decode kernel DMAs one
  ``[Hkv, chunk, D]`` block per grid step and issues a single batched MXU
  matmul over all heads — no in-kernel transpose, no per-head small-matmul
  loop (the [S, Hkv, D] row-major alternative forces one or the other).

Raggedness (every slot at a different sequence length) is expressed by a
``lengths[num_slots]`` vector and masking, not by dynamic shapes.

**Int8 quantization** (``quant=True`` / ServingConfig.kv_dtype="int8"): K/V rows
are stored int8 with one float32 scale per (layer, slot, head, row) —
``ks, vs : [num_layers, num_slots, num_kv_heads, max_len]`` — the standard
per-token-per-head dynamic scheme (near-lossless for attention; the vLLM
engine inside the reference's serving pods ships the same option as
``kv_cache_dtype=int8``). Decode is cache-bandwidth-bound, so halving the
bytes/row both halves the hot-loop HBM traffic and doubles the slot count a
chip's HBM can hold; the Pallas kernel dequantizes in VMEM by folding the
scales into the flash accumulation (ops/pallas_attention.py), so the f32 cache
never exists in HBM.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from aws_k8s_ansible_provisioner_tpu.config import ModelConfig


def init_cache(cfg: ModelConfig, num_slots: int, max_len: int,
               dtype=jnp.bfloat16, quant: bool = False) -> dict:
    """Allocate the decode cache. Leaves carry a leading [L] axis for lax.scan.

    With ``quant`` the K/V leaves are int8 and per-row scale leaves ``ks``/``vs``
    are added (see module docstring).
    """
    shape = (cfg.num_layers, num_slots, cfg.num_kv_heads, max_len, cfg.head_dim)
    if quant:
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "ks": jnp.zeros(shape[:-1], jnp.float32),
            "vs": jnp.zeros(shape[:-1], jnp.float32),
        }
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
    }


def is_quantized(cache_l: dict) -> bool:
    return "ks" in cache_l


def cache_bytes(cfg: ModelConfig, num_slots: int, max_len: int,
                dtype=jnp.bfloat16, quant: bool = False) -> int:
    rows = 2 * cfg.num_layers * num_slots * max_len * cfg.num_kv_heads
    if quant:
        return rows * (cfg.head_dim * 1 + 4)   # int8 row + f32 scale
    return rows * cfg.head_dim * jnp.dtype(dtype).itemsize


def quantize_rows(x: jnp.ndarray):
    """Per-row symmetric int8 quantization over the trailing head_dim axis.

    x: [..., D] float → (int8 [..., D], float32 scale [...]) with
    ``x ≈ q * scale``. Round-half-even, the same rule as the in-kernel
    quantization in ops/pallas_attention.cache_write_row_quant, so
    XLA-prefilled rows and Pallas-decoded rows are interchangeable (agreement
    to 1 int8 step; compiled-program fusion may differ by 1 ulp of scale).
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-6) / 127.0
    q = jnp.round(xf / scale[..., None]).astype(jnp.int8)
    return q, scale


def dequantize(q: jnp.ndarray, scale: jnp.ndarray, dtype=jnp.float32):
    """Inverse of quantize_rows: q [..., D] int8, scale [...] → float [..., D]."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def _write_kv(cache_l: dict, update, k_val: jnp.ndarray,
              v_val: jnp.ndarray) -> dict:
    """Apply one ``update(arr, val)`` expression to the k and v leaves —
    quantizing the values first and updating the scale leaves with the SAME
    expression when the cache is int8. That works because every writer's
    scale target is exactly its row target minus the trailing head_dim axis
    (quantize_rows drops that axis), so the k/v indexing never has to be
    written twice (once per dtype branch) per writer."""
    if is_quantized(cache_l):
        k_val, ks = quantize_rows(k_val)
        v_val, vs = quantize_rows(v_val)
        return {"k": update(cache_l["k"], k_val),
                "v": update(cache_l["v"], v_val),
                "ks": update(cache_l["ks"], ks),
                "vs": update(cache_l["vs"], vs)}
    return {"k": update(cache_l["k"], k_val),
            "v": update(cache_l["v"], v_val)}


def write_prompt(cache_l: dict, slot: jnp.ndarray, k: jnp.ndarray,
                 v: jnp.ndarray) -> dict:
    """Write a prefilled prompt's K/V into one slot (single layer slice).

    cache_l: {'k','v': [num_slots, Hkv, max_len, D]}; k/v: [1, T, Hkv, D];
    slot: scalar int. Writes rows [0, T) of the slot (padded tail rows beyond the
    true length hold garbage — decode masks by length, so they are never read).
    The [T, Hkv] → [Hkv, T] transpose happens once here, at prefill, so every
    decode step reads head-contiguous streams.
    """
    k3 = jnp.swapaxes(k[0], 0, 1)  # [Hkv, T, D]
    v3 = jnp.swapaxes(v[0], 0, 1)
    start = (slot, jnp.zeros_like(slot), jnp.zeros_like(slot),
             jnp.zeros_like(slot))
    return _write_kv(
        cache_l,
        lambda arr, val: jax.lax.dynamic_update_slice(arr, val[None],
                                                      start[:arr.ndim]),
        k3, v3)


def write_prompts(cache_l: dict, slots: jnp.ndarray, k: jnp.ndarray,
                  v: jnp.ndarray) -> dict:
    """Batched prompt write: N prompts into N slots in one scatter.

    cache_l: {'k','v': [num_slots, Hkv, max_len, D]}; slots: [N] int32;
    k/v: [N, T, Hkv, D]. Rows whose slot index is out of range (the padding
    rows a power-of-two prefill batch adds) are DROPPED by the scatter —
    mode='drop' makes that contract explicit rather than implicit.
    """
    kt = jnp.swapaxes(k, 1, 2)  # [N, Hkv, T, D]
    vt = jnp.swapaxes(v, 1, 2)
    T = k.shape[1]
    return _write_kv(
        cache_l,
        lambda arr, val: arr.at[slots, :, :T].set(val, mode="drop"),
        kt, vt)


def write_chunk(cache_l: dict, slot: jnp.ndarray, start: jnp.ndarray,
                k: jnp.ndarray, v: jnp.ndarray) -> dict:
    """Write one prefill CHUNK's K/V rows [start, start+C) into a slot.

    cache_l: {'k','v': [num_slots, Hkv, max_len, D]}; slot/start scalars;
    k/v: [1, C, Hkv, D]. A per-row scatter with mode='drop' — NOT
    dynamic_update_slice, whose out-of-bounds clamping would silently SHIFT a
    final chunk that pokes past max_len backward over earlier chunks'
    rows (when prefill_chunk doesn't divide the window). With the scatter,
    every valid row lands at its exact position and rows >= max_len drop.
    Rows past the chunk's true length (final-chunk padding) land beyond the
    sequence's final length and are never read (decode masks by length).
    """
    C = k.shape[1]
    rows = start + jnp.arange(C)                  # [C]
    # Advanced indices (scalar slot, row vector) separated by the head slice
    # broadcast to the FRONT: the update target is [C, Hkv, D] — exactly the
    # incoming chunk's layout, no transpose needed.
    return _write_kv(
        cache_l,
        lambda arr, val: arr.at[slot, :, rows].set(val, mode="drop"),
        k[0], v[0])


def write_token(cache_l: dict, lengths: jnp.ndarray, k: jnp.ndarray,
                v: jnp.ndarray) -> dict:
    """Scatter one new token per slot at its current length (single layer slice).

    cache_l: {'k','v': [B, Hkv, S, D]}; lengths: [B]; k/v: [B, 1, Hkv, D].
    """
    B = k.shape[0]
    rows = jnp.arange(B)
    # Advanced indexing at axes (0, 2) with the head slice between them yields
    # [B, Hkv, D] targets — exactly the incoming token's shape.
    return _write_kv(
        cache_l,
        lambda arr, val: arr.at[rows, :, lengths].set(val),
        k[:, 0], v[:, 0])


def write_token_layer(cache: dict, layer: jnp.ndarray, lengths: jnp.ndarray,
                      k: jnp.ndarray, v: jnp.ndarray) -> dict:
    """Scatter one new token per slot into the FULL cache at a given layer.

    cache: {'k','v': [L, B, Hkv, S, D]}; layer: scalar int; lengths: [B];
    k/v: [B, 1, Hkv, D]. This is the carry-path write (see
    models/layers.model_forward_carry): the cache flows through the layer scan
    as part of the carry, so this scatter updates the donated buffer IN PLACE —
    the xs→ys alternative costs a full-cache copy per layer per decode step
    (~7 GB/token for Qwen3-0.6B at batch 32 — measured 24 ms/token on v5e vs
    ~4 ms without the copies).
    """
    B = k.shape[0]
    rows = jnp.arange(B)
    return _write_kv(
        cache,
        lambda arr, val: arr.at[layer, rows, :, lengths].set(val, mode="drop"),
        k[:, 0], v[:, 0])


# Donating the cache is what makes this a ~rows-sized copy: the engine
# rebinds self.cache to the result immediately, so the input buffer is dead
# and XLA updates it in place. Without donation every prefix hit would
# materialize a second full cache (14+ GB transient at the bench config).
@functools.partial(jax.jit, donate_argnums=0)
def _copy_prefix(cache: dict, src: jnp.ndarray, dst: jnp.ndarray,
                 n_rows: jnp.ndarray) -> dict:
    def one(arr):
        # K/V leaves are [L, B, H, S, D]; quant scale leaves are [L, B, H, S]
        # — the sequence axis is 3 in both, the reshape pads trailing dims.
        S = arr.shape[3]
        src_s = jax.lax.dynamic_index_in_dim(arr, src, axis=1)   # [L,1,H,S,...]
        dst_s = jax.lax.dynamic_index_in_dim(arr, dst, axis=1)
        keep = jnp.arange(S).reshape((1, 1, 1, S) + (1,) * (arr.ndim - 4))
        mixed = jnp.where(keep < n_rows, src_s, dst_s)
        return jax.lax.dynamic_update_slice_in_dim(arr, mixed, dst, axis=1)

    return {name: one(arr) for name, arr in cache.items()}


def copy_prefix(cache: dict, src_slot: int, dst_slot: int, n_rows: int) -> dict:
    """Copy rows [0, n_rows) of ``src_slot`` into ``dst_slot``, all layers.

    The engine's automatic prefix caching (serving/engine.py): a new request
    whose prompt shares a prefix with tokens still resident in another slot
    reuses those K/V rows instead of recomputing them — the TPU analogue of
    vLLM's prefix caching, expressed as one masked slot-to-slot copy (the
    slot-contiguous layout makes the prefix a contiguous row range; for a
    512-token prefix of Qwen3-0.6B this moves ~60 MB, vs recomputing 512
    tokens x 28 layers of prefill FLOPs). Under a dp-sharded mesh GSPMD
    inserts the cross-shard collective when src and dst live on different
    data-parallel groups.
    """
    return _copy_prefix(cache, jnp.int32(src_slot), jnp.int32(dst_slot),
                        jnp.int32(n_rows))


def pages_view(cache: dict, page_size: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Reinterpret the cache as pages: [L, slots*heads*pages_per_stream, page, D].

    Zero-copy reshape (the per-(slot, head) stream is contiguous); the implied
    block table of (slot b, head h) is ``(b*Hkv + h)*pages_per_stream +
    arange(pages_per_stream)``. Used by the Pallas paged-attention kernel and by
    future true-paged allocation.
    """
    L, B, H, S, D = cache["k"].shape
    assert S % page_size == 0, (S, page_size)
    n = B * H * (S // page_size)
    return (cache["k"].reshape(L, n, page_size, D),
            cache["v"].reshape(L, n, page_size, D))
