"""Decode KV cache, laid out for TPU HBM and XLA static shapes.

The reference's KV cache lives inside the external vLLM container (paged attention
over CUDA kernels; SURVEY.md §2.2 row 1). The TPU-native equivalent here uses a
**slot-contiguous** layout: one fixed region per decode slot,

    k, v : [num_layers, num_slots, max_len, num_kv_heads, head_dim]   (bf16)

which is exactly a paged cache whose per-slot block table is the identity —
``max_len/page_size`` pages per slot, page p of slot b at
``k[:, b, p*page_size:(p+1)*page_size]``. This buys:

- static shapes (XLA compiles one decode program, no re-specialization),
- in-place row writes via scatter-at-index (donated buffers, zero copies),
- attention that reads the cache *in place* (no gather of pages, no repeat_kv
  materialization — see ops/attention.py),
- a pages **view** for the Pallas ragged-attention kernel without relayout.

Raggedness (every slot at a different sequence length) is expressed by a
``lengths[num_slots]`` vector and masking, not by dynamic shapes.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from aws_k8s_ansible_provisioner_tpu.config import ModelConfig


def init_cache(cfg: ModelConfig, num_slots: int, max_len: int,
               dtype=jnp.bfloat16) -> dict:
    """Allocate the decode cache. Leaves carry a leading [L] axis for lax.scan."""
    shape = (cfg.num_layers, num_slots, max_len, cfg.num_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
    }


def cache_bytes(cfg: ModelConfig, num_slots: int, max_len: int,
                dtype=jnp.bfloat16) -> int:
    itemsize = jnp.dtype(dtype).itemsize
    return (2 * cfg.num_layers * num_slots * max_len * cfg.num_kv_heads
            * cfg.head_dim * itemsize)


def write_prompt(cache_l: dict, slot: jnp.ndarray, k: jnp.ndarray,
                 v: jnp.ndarray) -> dict:
    """Write a prefilled prompt's K/V into one slot (single layer slice).

    cache_l: {'k','v': [num_slots, max_len, Hkv, D]}; k/v: [1, T, Hkv, D];
    slot: scalar int. Writes rows [0, T) of the slot (padded tail rows beyond the
    true length hold garbage — decode masks by length, so they are never read).
    """
    k3, v3 = k[0], v[0]  # [T, Hkv, D]
    start = (slot, jnp.zeros_like(slot), jnp.zeros_like(slot),
             jnp.zeros_like(slot))
    return {
        "k": jax.lax.dynamic_update_slice(cache_l["k"], k3[None], start),
        "v": jax.lax.dynamic_update_slice(cache_l["v"], v3[None], start),
    }


def write_token(cache_l: dict, lengths: jnp.ndarray, k: jnp.ndarray,
                v: jnp.ndarray) -> dict:
    """Scatter one new token per slot at its current length (single layer slice).

    cache_l: {'k','v': [B, S, Hkv, D]}; lengths: [B]; k/v: [B, 1, Hkv, D].
    """
    B = k.shape[0]
    rows = jnp.arange(B)
    return {
        "k": cache_l["k"].at[rows, lengths].set(k[:, 0]),
        "v": cache_l["v"].at[rows, lengths].set(v[:, 0]),
    }


def pages_view(cache: dict, page_size: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Reinterpret the slot cache as pages: [L, slots*pages_per_slot, page, H, D].

    Zero-copy reshape (the slot dimension is contiguous); the implied block table
    of slot b is ``b*pages_per_slot + arange(pages_per_slot)``. Used by the Pallas
    paged-attention kernel and by future true-paged allocation.
    """
    L, B, S, H, D = cache["k"].shape
    assert S % page_size == 0, (S, page_size)
    n = B * (S // page_size)
    return (cache["k"].reshape(L, n, page_size, H, D),
            cache["v"].reshape(L, n, page_size, H, D))
