"""Chat templating for /v1/chat/completions.

The reference ships two vLLM chat templates as ConfigMaps that **no playbook ever
applies** (SURVEY.md §2.1 row 18: "Referenced by no playbook"); wiring them in is
an explicit improvement required by the capability contract (SURVEY.md §7 item 7,
BASELINE.json configs #2-3). Behavior contract reproduced (not copied) from the
reference templates' rendering semantics (templates/phi-chat-template.yaml:1-25,
templates/opt-chat-template.yaml:1-25):

- ``phi`` style renders ``Human: ...`` / ``Assistant: ...`` turns;
- ``opt`` style renders ``User: ...`` / ``Assistant: ...`` turns;
- an optional single leading *system* message is hoisted to the top as plain text;
- when ``add_generation_prompt`` is true, the assistant prefix is appended so the
  model continues as the assistant.

Model-family default: phi-2 → phi style; everything else → opt style (generic
user/assistant). A tokenizer-provided template (real HF checkpoints) wins when
present, matching vLLM precedence.
"""

from __future__ import annotations

from typing import List, Optional

import jinja2

PHI_STYLE = """\
{%- if messages and messages[0].role == 'system' -%}
{{ messages[0].content }}

{% set messages = messages[1:] %}
{%- endif -%}
{%- for m in messages -%}
{%- if m.role == 'user' -%}
Human: {{ m.content }}
{% elif m.role == 'assistant' -%}
Assistant: {{ m.content }}
{% endif -%}
{%- endfor -%}
{%- if add_generation_prompt -%}
Assistant:{%- endif -%}
"""

OPT_STYLE = """\
{%- if messages and messages[0].role == 'system' -%}
{{ messages[0].content }}

{% set messages = messages[1:] %}
{%- endif -%}
{%- for m in messages -%}
{%- if m.role == 'user' -%}
User: {{ m.content }}
{% elif m.role == 'assistant' -%}
Assistant: {{ m.content }}
{% endif -%}
{%- endfor -%}
{%- if add_generation_prompt -%}
Assistant:{%- endif -%}
"""

_STYLES = {"phi": PHI_STYLE, "opt": OPT_STYLE}


def default_style_for_model(model_name: str) -> str:
    return "phi" if "phi" in model_name.lower() else "opt"


class ChatTemplater:
    """Render chat messages to a prompt string.

    Precedence (mirrors vLLM's --chat-template behavior): explicit template file
    > tokenizer-embedded template > family default style.
    """

    def __init__(self, model_name: str, tokenizer=None,
                 template_path: Optional[str] = None,
                 style: Optional[str] = None):
        self._tokenizer = tokenizer
        self._env = jinja2.Environment(keep_trailing_newline=True)
        source: Optional[str] = None
        if template_path:
            with open(template_path) as fh:
                source = fh.read()
        elif style:
            source = _STYLES[style]
        self._template = self._env.from_string(source) if source else None
        self._fallback = self._env.from_string(
            _STYLES[default_style_for_model(model_name)])

    def render(self, messages: List[dict], add_generation_prompt: bool = True
               ) -> str:
        msgs = [dict(role=m.get("role", "user"), content=m.get("content", ""))
                for m in messages]
        if self._template is not None:
            return self._template.render(messages=msgs,
                                         add_generation_prompt=add_generation_prompt)
        if self._tokenizer is not None and hasattr(self._tokenizer, "_tok") and \
                getattr(self._tokenizer._tok, "chat_template", None):
            return self._tokenizer.apply_chat_template(
                msgs, add_generation_prompt=add_generation_prompt)
        return self._fallback.render(messages=msgs,
                                     add_generation_prompt=add_generation_prompt)
