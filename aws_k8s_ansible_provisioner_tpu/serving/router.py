"""Inference gateway router: HTTP front door for N serving-engine replicas.

TPU-native replacement for the llm-d inference gateway (Go) that the reference
deploys via ``llmd-installer.sh`` and addresses at ``llm-d-test.yaml:14-26``.
The contract preserved:

- exposes the OpenAI surface (``/v1/*``) of the backends unchanged, so the L4
  test playbook's ephemeral curl pods work against the router exactly as they
  did against the llm-d gateway;
- load-balances across every replica behind the backend Service by resolving
  the DNS name to all A records per request batch (headless-Service friendly)
  and round-robining over them — the "latent DP" the reference hinted at with
  its two model PVCs (SURVEY.md §2.3);
- retries idempotent-safe failures on the next replica, taking a dead backend
  out of rotation for a cooldown window (the health-driven routing the
  reference delegated to the external gateway);
- streams responses through unbuffered (SSE passthrough for
  ``stream: true`` completions).

Stdlib-only (http.server + urllib) so the router container needs nothing
beyond the framework image.
"""

from __future__ import annotations

import argparse
import itertools
import json
import logging
import socket
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

log = logging.getLogger("tpu_serve.router")


class BackendPool:
    """Round-robin pool over the backend service's resolved replicas."""

    def __init__(self, backend_service: str, refresh_s: float = 10.0,
                 cooldown_s: float = 15.0):
        host, sep, port = backend_service.rpartition(":")
        if not sep or not host or not port.isdigit():
            raise ValueError(
                f"--backend-service must be host:port, got {backend_service!r}")
        self.host = host
        self.port = int(port)
        self.refresh_s = refresh_s
        self.cooldown_s = cooldown_s
        self._lock = threading.Lock()
        self._addrs: list[str] = []
        self._rr = itertools.count()
        self._dead: dict[str, float] = {}
        self._last_refresh = 0.0

    def _resolve(self) -> list[str]:
        try:
            infos = socket.getaddrinfo(self.host, self.port, socket.AF_INET,
                                       socket.SOCK_STREAM)
            return sorted({i[4][0] for i in infos})
        except socket.gaierror:
            return []

    def pick(self) -> list[str]:
        """Return candidate backends, healthiest-first (round-robin rotation)."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_refresh > self.refresh_s or not self._addrs:
                addrs = self._resolve()
                if addrs:
                    self._addrs = addrs
                self._last_refresh = now
            self._dead = {a: t for a, t in self._dead.items()
                          if now - t < self.cooldown_s}
            alive = [a for a in self._addrs if a not in self._dead]
            pool = alive or self._addrs  # all dead → try everything anyway
            if not pool:
                return []
            k = next(self._rr) % len(pool)
            return pool[k:] + pool[:k]

    def mark_dead(self, addr: str):
        with self._lock:
            self._dead[addr] = time.monotonic()

    def url(self, addr: str, path: str) -> str:
        return f"http://{addr}:{self.port}{path}"


class RouterHandler(BaseHTTPRequestHandler):
    pool: BackendPool = None  # injected by serve()
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet; structured logging below
        log.debug(fmt, *args)

    def _respond_json(self, code: int, obj: dict):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _proxy(self, method: str):
        if self.path == "/health":
            self._respond_json(200, {"status": "ok",
                                     "backends": self.pool._addrs})
            return
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else None
        candidates = self.pool.pick()
        if not candidates:
            self._respond_json(503, {"error": {
                "message": "no serving backends resolved", "type": "router_error"}})
            return
        last_err = None
        for addr in candidates:
            # Phase 1: reach the backend. Failures here are retryable — nothing
            # has been written to the client yet.
            try:
                req = urllib.request.Request(
                    self.pool.url(addr, self.path), data=body, method=method)
                for h in ("Content-Type", "Authorization", "Accept"):
                    if self.headers.get(h):
                        req.add_header(h, self.headers[h])
                resp = urllib.request.urlopen(req, timeout=600)
            except urllib.error.HTTPError as e:
                # Backend spoke HTTP: a 4xx/5xx is the app's answer, not a dead
                # replica — pass it through.
                data = e.read()
                self.send_response(e.code)
                self.send_header("Content-Type",
                                 e.headers.get("Content-Type", "application/json"))
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            except (urllib.error.URLError, socket.timeout, ConnectionError) as e:
                self.pool.mark_dead(addr)
                last_err = e
                log.warning("backend %s failed (%s); trying next", addr, e)
                continue
            # Phase 2: relay to the client. The response has started — a
            # failure here must NOT retry another replica (that would splice a
            # second status line into the body) and a client disconnect
            # (BrokenPipeError) must NOT mark the backend dead.
            try:
                self.send_response(resp.status)
                ctype = resp.headers.get("Content-Type", "application/json")
                self.send_header("Content-Type", ctype)
                if "text/event-stream" in ctype:
                    # SSE: stream chunks through unbuffered; connection close
                    # delimits the body.
                    self.send_header("Connection", "close")
                    self.end_headers()
                    # read1 returns as soon as ANY bytes arrive — read(4096)
                    # would buffer whole events and defeat token streaming.
                    read1 = getattr(resp, "read1", None) or resp.read
                    while True:
                        chunk = read1(4096)
                        if not chunk:
                            break
                        self.wfile.write(chunk)
                        self.wfile.flush()
                else:
                    data = resp.read()
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
            except BrokenPipeError:
                log.info("client disconnected mid-response")
                self.close_connection = True
            except (urllib.error.URLError, socket.timeout, ConnectionError) as e:
                # Backend died mid-body: response is unsalvageable; cut the
                # connection so the client sees a truncated body, not a corrupt one.
                self.pool.mark_dead(addr)
                log.warning("backend %s died mid-response: %s", addr, e)
                self.close_connection = True
            return
        self._respond_json(502, {"error": {
            "message": f"all backends failed: {last_err}", "type": "router_error"}})

    def do_GET(self):
        self._proxy("GET")

    def do_POST(self):
        self._proxy("POST")


def serve(backend_service: str, host: str, port: int):
    RouterHandler.pool = BackendPool(backend_service)
    httpd = ThreadingHTTPServer((host, port), RouterHandler)
    log.info("router listening on %s:%d -> %s", host, port, backend_service)
    httpd.serve_forever()


def main(argv=None):
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    p = argparse.ArgumentParser(description="TPU serving gateway router")
    p.add_argument("--backend-service", required=True,
                   help="host:port of the engine Service (DNS resolved to replicas)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8080)
    args = p.parse_args(argv)
    serve(args.backend_service, args.host, args.port)


if __name__ == "__main__":
    main()
