"""Inference gateway router: HTTP front door for N serving-engine replicas.

TPU-native replacement for the llm-d inference gateway (Go) that the reference
deploys via ``llmd-installer.sh`` and addresses at ``llm-d-test.yaml:14-26``.
The contract preserved:

- exposes the OpenAI surface (``/v1/*``) of the backends unchanged, so the L4
  test playbook's ephemeral curl pods work against the router exactly as they
  did against the llm-d gateway;
- load-balances across every replica behind the backend Service by resolving
  the DNS name to all A records (headless-Service friendly) — or a static
  comma-separated ``host:port`` list — the "latent DP" the reference hinted
  at with its two model PVCs (SURVEY.md §2.3);
- routes INFERENCE-AWARE, the actual capability of the llm-d gateway it
  replaces (VERDICT r3 missing #4: round-robin in front of
  continuous-batching engines with prefix caches throws away both signals):
  a ~1 Hz poller reads each replica's 3-field ``/load`` endpoint and requests
  go to the least-loaded replica; completion requests carry a prompt-prefix
  affinity key, and same-prefix requests stick to the same replica while its
  load permits — which is what makes the engines' paged prefix caches
  (hash-chain page sharing) actually hit across requests;
- retries idempotent-safe failures on the next replica, taking a dead backend
  out of rotation for a cooldown window (the health-driven routing the
  reference delegated to the external gateway);
- streams responses through unbuffered (SSE passthrough for
  ``stream: true`` completions).

Affinity keys hash the leading PROMPT TEXT (the router deliberately carries
no tokenizer): tokenization is prefix-stable for equal text, so equal text
prefixes are exactly the requests whose token pages the engine's hash-chain
index can share. Stdlib-only (http.server + urllib) so the router container
needs nothing beyond the framework image.
"""

from __future__ import annotations

import argparse
import collections
import hashlib
import http.client
import itertools
import json
import logging
import math
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from aws_k8s_ansible_provisioner_tpu.serving import chaos as _chaos
from aws_k8s_ansible_provisioner_tpu.serving import (autoscaler, capacity,
                                                     devmon, flightrec,
                                                     metrics, slo, tracing)
from aws_k8s_ansible_provisioner_tpu.serving.metrics import (
    Counter, Gauge, Registry)

log = logging.getLogger("tpu_serve.router")

# Connect phase gets its own short timeout: a dead replica should fail over in
# seconds. The read timeout stays long (a non-streaming completion can
# legitimately generate for minutes). Keeping these distinct is what makes the
# retry policy safe — see _proxy (ADVICE r1: a single 600s timeout meant a
# slow POST could be replayed on a second backend while the first was still
# generating).
CONNECT_TIMEOUT_S = 5.0
READ_TIMEOUT_S = 600.0
# End-to-end deadline header (serving/server.py DEADLINE_HEADER): forwarded
# to the backend unchanged AND used to bound this hop's read timeout — a
# request that declared a 5 s deadline must not pin a router thread for the
# full READ_TIMEOUT_S when its backend wedges.
DEADLINE_HEADER = "X-Request-Deadline-Ms"
READ_TIMEOUT_GRACE_S = 30.0
# 429 is a ROUTABLE signal: the backend shed the request at admission —
# nothing was generated — so trying the next replica (or the same pool again
# after a jittered backoff) is always safe, unlike mid-generation failures.
# The budget bounds the extra attempts per request; backoff is jittered so a
# synchronized burst doesn't re-converge on the same replica.
RETRY_429_BUDGET = 2
RETRY_429_BACKOFF_S = 0.1


class RouterMetrics:
    """Gateway-level request/failover counters for the L5 scrape (VERDICT r1
    weak #8: router requests were invisible to observability)."""

    def __init__(self):
        self.registry = Registry()
        r = self.registry
        self.requests = r.register(Counter(
            "tpu_router_requests_total", "Requests relayed, by response code",
            ("code",)))
        self.failovers = r.register(Counter(
            "tpu_router_failovers_total",
            "Requests retried on another replica after a connect failure"))
        self.dead_marks = r.register(Counter(
            "tpu_router_backend_dead_total",
            "Times a backend was taken out of rotation"))
        self.backends = r.register(Gauge(
            "tpu_router_backends", "Currently resolved backend replicas"))
        self.retries_429 = r.register(Counter(
            "tpu_router_429_retries_total",
            "Shed (429) responses retried on another replica after a "
            "jittered backoff"))
        self.recovered = r.register(Counter(
            "tpu_router_backend_recovered_total",
            "Cooling-down backends returned to rotation early after "
            "answering the health probe"))
        # Replica lifecycle (r8): mid-stream failover + drain-aware routing.
        self.stream_failovers = r.register(Counter(
            "tpu_router_stream_failovers_total",
            "Streams continued on another replica after a replica died "
            "mid-stream (deterministic continuation; only new chunks "
            "spliced to the client)"))
        self.draining_skips = r.register(Counter(
            "tpu_router_backend_draining_total",
            "Requests re-routed off a draining replica (503 draining "
            "shed at admission — nothing generated, always re-routable)"))


# A /load sample older than this no longer orders candidates (a replica that
# stopped answering its poller is either dead — the connect path will find
# out — or wedged; either way its last-known load is fiction).
LOAD_TTL_S = 5.0
# A replica reporting ``draining`` on /load is out of rotation WITHOUT being
# dead-marked (it is healthy, it is leaving). Entries refresh every poll;
# the TTL returns a replica whose poller went silent (restart completing)
# to normal connect-phase discovery instead of excluding it forever.
DRAIN_TTL_S = 10.0
# Mid-stream failovers per request: each continuation re-prefills the
# emitted prefix on another replica, so the budget bounds the worst-case
# extra prefill work a flapping fleet can induce per stream.
STREAM_FAILOVER_BUDGET = 2
# Affinity yields when the sticky replica's in-flight+queued exceeds the
# least-loaded replica's by more than this (prefix reuse saves prefill; it
# never justifies queueing behind a pile while a sibling idles).
LOAD_SLACK = 4
AFFINITY_CAP = 8192           # LRU entries (prefix-key -> replica)
AFFINITY_PREFIX_CHARS = 512   # prompt chars hashed into the key


class BackendPool:
    """Replica pool: least-loaded-first with prefix affinity, round-robin
    fallback while load is unknown.

    Backends come from DNS (``host:port`` resolved to all A records — the
    headless-Service contract) or a static comma-separated ``host:port``
    list (in-process rehearsal + mixed-port layouts). Internal addresses are
    ``"host:port"`` strings either way.
    """

    def __init__(self, backend_service: str, refresh_s: float = 10.0,
                 cooldown_s: float = 15.0, load_slack: int = LOAD_SLACK):
        self._static: list[str] = []
        self.host = self.port = None
        if "," in backend_service:
            for part in backend_service.split(","):
                host, sep, port = part.strip().rpartition(":")
                if not sep or not host or not port.isdigit():
                    raise ValueError(f"--backend-service list entries must "
                                     f"be host:port, got {part!r}")
                self._static.append(f"{host}:{port}")
        else:
            host, sep, port = backend_service.rpartition(":")
            if not sep or not host or not port.isdigit():
                raise ValueError(f"--backend-service must be host:port, "
                                 f"got {backend_service!r}")
            self.host = host
            self.port = int(port)
        self.refresh_s = refresh_s
        self.cooldown_s = cooldown_s
        self.load_slack = load_slack
        self._lock = threading.Lock()
        # autoscaler-managed replicas: layered on top of whatever DNS/the
        # static list resolves, surviving refreshes until remove_backend
        self._dynamic: list[str] = []
        self._addrs: list[str] = list(self._static)
        self._rr = itertools.count()
        self._dead: dict[str, float] = {}
        # addr -> time last seen draining (poller-fed; TTL'd in pick())
        self._draining: dict[str, float] = {}
        self._last_refresh = 0.0
        # addr -> (active + queued, t_sampled); written by the ~1 Hz poller
        self._load: dict[str, tuple[int, float]] = {}
        # addr -> (/healthz fleet summary dict, t_sampled); the poller
        # refreshes this beside /load so /debug/fleet and tools/tputop.py
        # read SLO burn rates + flight anomalies without fanning out a
        # scrape per dashboard refresh
        self._health: dict[str, tuple[dict, float]] = {}
        # prompt-prefix key -> last replica that served it (LRU)
        self._affinity: "collections.OrderedDict[str, str]" = \
            collections.OrderedDict()

    def _resolve(self) -> list[str]:
        if self._static:
            base = list(self._static)
        elif self.host is None:
            # a fully-drained static pool (scale-to-zero): nothing to
            # resolve — the autoscaler's dynamic layer is the whole fleet
            base = []
        else:
            try:
                infos = socket.getaddrinfo(self.host, self.port,
                                           socket.AF_INET,
                                           socket.SOCK_STREAM)
                base = sorted({f"{i[4][0]}:{self.port}" for i in infos})
            except socket.gaierror:
                base = []
        return base + [a for a in self._dynamic if a not in base]

    def addrs(self) -> list[str]:
        """Current replica set (refreshing if stale) — the poller's target
        list."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_refresh > self.refresh_s or not self._addrs:
                addrs = self._resolve()
                if addrs:
                    self._addrs = addrs
                self._last_refresh = now
            return list(self._addrs)

    def note_load(self, addr: str, active: int, queued: int):
        with self._lock:
            self._load[addr] = (int(active) + int(queued), time.monotonic())

    def note_health(self, addr: str, health: dict):
        """Stash a replica's /healthz fleet summary (poller-fed)."""
        with self._lock:
            self._health[addr] = (health, time.monotonic())

    def fleet(self) -> dict:
        """Per-replica fleet view: last /load + /healthz samples with ages
        (/debug/fleet; tools/tputop.py renders this)."""
        now = time.monotonic()
        with self._lock:
            out = {}
            for addr in self._addrs:
                ent: dict = {}
                ld = self._load.get(addr)
                if ld is not None:
                    ent["load"] = ld[0]
                    ent["load_age_s"] = round(now - ld[1], 2)
                h = self._health.get(addr)
                if h is not None:
                    ent["health"] = h[0]
                    ent["health_age_s"] = round(now - h[1], 2)
                ent["cooling"] = addr in self._dead \
                    and now - self._dead[addr] < self.cooldown_s
                ent["draining"] = addr in self._draining \
                    and now - self._draining[addr] < DRAIN_TTL_S
                out[addr] = ent
            return out

    def note_affinity(self, key: str, addr: str):
        """Remember which replica served this prompt prefix (its pages are
        now in that replica's prefix index)."""
        with self._lock:
            self._affinity[key] = addr
            self._affinity.move_to_end(key)
            while len(self._affinity) > AFFINITY_CAP:
                self._affinity.popitem(last=False)

    def migrate_affinity(self, src: str, dst: str) -> int:
        """Bulk re-point every affinity entry on ``src`` to ``dst``
        (ISSUE 20 satellite): when a replica leaves the pool its HBM prefix
        index dies with it, but the FIRST re-hit on the new home rebuilds
        the chain — and with the tier-2 host store the rebuilt pages
        outlive HBM pressure there — so keeping the cohort together beats
        scattering it over the pool and re-prefilling everywhere. LRU
        positions are preserved (no move_to_end: a migration is not a use).
        Returns the number of entries re-pointed."""
        with self._lock:
            return self._migrate_affinity_locked(src, dst)

    def _migrate_affinity_locked(self, src: str, dst: str) -> int:
        moved = 0
        for key, a in self._affinity.items():
            if a == src:
                self._affinity[key] = dst
                moved += 1
        return moved

    def _score(self, addr: str, now: float):
        ent = self._load.get(addr)
        if ent is None or now - ent[1] > LOAD_TTL_S:
            return None
        return ent[0]

    def pick(self, affinity_key: str | None = None) -> list[str]:
        """Candidate backends, best-first.

        Ordering: (1) the affinity replica, while alive and within
        ``load_slack`` of the least-loaded; (2) replicas with fresh /load
        samples, least-loaded first; (3) load-unknown replicas in round-robin
        rotation (the whole pool degrades to plain round-robin when the
        poller hasn't run — cold start, tests, or a /load-less backend)."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_refresh > self.refresh_s or not self._addrs:
                addrs = self._resolve()
                if addrs:
                    self._addrs = addrs
                self._last_refresh = now
            self._dead = {a: t for a, t in self._dead.items()
                          if now - t < self.cooldown_s}
            self._draining = {a: t for a, t in self._draining.items()
                              if now - t < DRAIN_TTL_S}
            alive = [a for a in self._addrs
                     if a not in self._dead and a not in self._draining]
            # all draining → fall back to the draining set (they shed 503
            # and the request-path handles it); all dead → try everything
            pool = alive \
                or [a for a in self._addrs if a not in self._dead] \
                or self._addrs
            if not pool:
                return []
            k = next(self._rr) % len(pool)
            rotated = pool[k:] + pool[:k]
            scored = [(self._score(a, now), a) for a in rotated]
            known = [(s, a) for s, a in scored if s is not None]
            unknown = [a for s, a in scored if s is None]
            known.sort(key=lambda sa: sa[0])
            order = [a for _, a in known] + unknown
            if affinity_key is not None:
                sticky = self._affinity.get(affinity_key)
                if sticky in pool and sticky != order[0]:
                    s = self._score(sticky, now)
                    best = known[0][0] if known else None
                    # A sticky replica with a stale/missing /load sample is
                    # only honored when NO replica has a fresh one (cold
                    # start / poller off): a wedged-but-connectable replica
                    # must not keep attracting its affinity traffic past the
                    # load_slack yield (advisor r4).
                    if (s is None and best is None) or (
                            s is not None and s <= best + self.load_slack):
                        order.remove(sticky)
                        order.insert(0, sticky)
            return order

    def mark_dead(self, addr: str):
        with self._lock:
            self._dead[addr] = time.monotonic()
            self._load.pop(addr, None)

    def add_backend(self, addr: str) -> bool:
        """Admit an autoscaler-launched replica into rotation NOW. The
        address joins the dynamic layer (surviving DNS refreshes) and any
        stale dead/draining record from a previous life at the same
        address is cleared. Returns whether it was new."""
        with self._lock:
            fresh = addr not in self._dynamic
            if fresh:
                self._dynamic.append(addr)
            if addr not in self._addrs:
                self._addrs.append(addr)
            self._dead.pop(addr, None)
            self._draining.pop(addr, None)
            return fresh

    def remove_backend(self, addr: str) -> bool:
        """Take a replica out of the pool permanently (autoscaler
        scale-down: the drain handles in-flight work; this stops NEW
        requests landing on it). Removes it from the static list too, so
        a drained initial backend stays gone. Returns whether it was
        present."""
        with self._lock:
            present = addr in self._addrs
            if present:
                self._addrs.remove(addr)
            if addr in self._dynamic:
                self._dynamic.remove(addr)
            if addr in self._static:
                self._static.remove(addr)
            self._load.pop(addr, None)
            # Re-point (not drop) the dead replica's affinity cohort to one
            # surviving replica — least-loaded by fresh /load sample, else
            # the first in rotation. The cohort's first re-hit there
            # re-prefills once and re-seeds the prefix chain (HBM + host
            # tier); dropping the entries instead would scatter the cohort
            # and pay that rebuild on EVERY replica it lands on. No
            # survivor → entries drop (nothing to point at).
            now = time.monotonic()
            survivors = [a for a in self._addrs
                         if a not in self._dead and a not in self._draining] \
                or self._addrs
            if survivors:
                dst = min(survivors,
                          key=lambda a: (self._score(a, now) is None,
                                         self._score(a, now) or 0.0))
                self._migrate_affinity_locked(addr, dst)
            else:
                self._affinity = collections.OrderedDict(
                    (k, a) for k, a in self._affinity.items() if a != addr)
            return present

    def note_draining(self, addr: str) -> bool:
        """A replica reported ``draining``: remove it from rotation WITHOUT
        dead-marking (no cooldown to serve out — it re-enters within one
        poll of draining going false). Returns whether this is a
        transition (was in rotation)."""
        with self._lock:
            fresh = addr not in self._draining
            self._draining[addr] = time.monotonic()
            return fresh

    def clear_draining(self, addr: str) -> bool:
        """The replica stopped draining (restart finished / drain
        cancelled): back into rotation NOW."""
        with self._lock:
            return self._draining.pop(addr, None) is not None

    def draining(self) -> list[str]:
        now = time.monotonic()
        with self._lock:
            return sorted(a for a, t in self._draining.items()
                          if now - t < DRAIN_TTL_S)

    def note_recovered(self, addr: str) -> bool:
        """A cooling-down replica answered its health probe: return it to
        rotation NOW instead of waiting out the rest of the cooldown (a
        restarted pod re-enters within one poller interval). Returns whether
        the replica was actually cooling."""
        with self._lock:
            return self._dead.pop(addr, None) is not None

    def cooling(self) -> list[str]:
        """Replicas currently inside their cooldown window."""
        now = time.monotonic()
        with self._lock:
            return [a for a, t in self._dead.items()
                    if now - t < self.cooldown_s]

    def url(self, addr: str, path: str) -> str:
        return f"http://{addr}{path}"


def _affinity_key(path: str, body: bytes | None) -> str | None:
    """Prefix-affinity key for a completion POST: hash of the leading prompt
    text (chat: the serialized messages). None = no affinity (malformed or
    non-completion traffic routes purely by load)."""
    if not body:
        return None
    try:
        obj = json.loads(body)
        if path.startswith("/v1/chat/completions"):
            # Conversation identity, not raw serialized-prefix: a shared
            # system prompt >= the prefix window would collapse EVERY chat
            # onto one key (review r4). The whole system text plus the
            # first non-system turn distinguishes conversations, while a
            # follow-up turn of the same conversation (same system + same
            # first user message, longer history) keeps its key — exactly
            # the requests whose prior-turn pages the engine indexed.
            msgs = obj.get("messages") or []
            if not isinstance(msgs, list) or not msgs:
                return None
            sys_txt = "".join(str(m.get("content", "")) for m in msgs
                              if isinstance(m, dict)
                              and m.get("role") == "system")
            first_turn = next((str(m.get("content", "")) for m in msgs
                               if isinstance(m, dict)
                               and m.get("role") != "system"), "")
            text = sys_txt + "\x00" + first_turn[:AFFINITY_PREFIX_CHARS]
        else:
            prompt = obj.get("prompt", "")
            if isinstance(prompt, list):
                prompt = prompt[0] if prompt else ""
            text = prompt if isinstance(prompt, str) else ""
            text = text[:AFFINITY_PREFIX_CHARS]
        if not text.strip("\x00"):
            return None
        return hashlib.sha1(text.encode("utf-8", "replace")).hexdigest()
    except (ValueError, TypeError, AttributeError):
        return None


def _fleet_capacity(fleet: dict) -> dict:
    """Aggregate the per-replica ``capacity`` blocks (poller-stashed
    /healthz) into the ``GET /debug/capacity`` fleet view.

    Fleet offered load and fleet ceiling are straight sums over replicas
    that report one (additive by construction — each replica measures its
    own arrivals and its own service rate). A replica whose /healthz
    predates serving/capacity.py (mixed-version fleet mid-rollout) gets an
    ``available: false`` row and is excluded from the sums, so a rollout
    never turns the dashboard into a KeyError and the fleet numbers only
    claim the replicas actually measured. The fleet replica recommendation
    scales total projected demand by the MEAN per-replica ceiling (what one
    more replica of the current mix would add)."""
    replicas = {}
    offered = ceiling = projected = 0.0
    admitted_rps = shed_rps = 0.0
    reporting = saturated = idle = 0
    for addr, ent in fleet.items():
        cap = (ent.get("health") or {}).get("capacity")
        if not isinstance(cap, dict):
            replicas[addr] = {"available": False}
            continue
        reporting += 1
        row = {
            "available": True,
            "offered_tps": cap.get("offered_tps", 0.0),
            "ceiling_tps": cap.get("ceiling_tps", 0.0),
            "ceiling_source": cap.get("ceiling_source", "none"),
            "utilization": cap.get("utilization", 0.0),
            "queue_delay_s": cap.get("queue_delay_s", 0.0),
            "seconds_to_saturation": cap.get("seconds_to_saturation"),
            "saturated": bool(cap.get("saturated", False)),
            "recommended_replicas": cap.get("recommended_replicas", 1),
            "idle": bool(cap.get("idle", False)),
            "last_submit_age_s": cap.get("last_submit_age_s"),
        }
        if row["idle"]:
            idle += 1
        if "health_age_s" in ent:
            row["age_s"] = ent["health_age_s"]
        replicas[addr] = row
        offered += float(cap.get("offered_tps") or 0.0)
        ceiling += float(cap.get("ceiling_tps") or 0.0)
        projected += float(cap.get("projected_offered_tps")
                           or cap.get("offered_tps") or 0.0)
        off_block = cap.get("offered")
        if isinstance(off_block, dict):
            admitted_rps += float(off_block.get("admitted_per_s") or 0.0)
            shed_rps += float(off_block.get("shed_per_s") or 0.0)
        if cap.get("saturated"):
            saturated += 1
    mean_ceiling = (ceiling / reporting) if reporting else 0.0
    if mean_ceiling > 0:
        # Demand-derived, deliberately NOT floored at the current fleet
        # size: a recommendation that can never go below reporting_replicas
        # would make scale-down impossible for the actuation loop. The
        # autoscaler's hysteresis + cooldown absorb a transiently low
        # reading; a fleet with no measured ceiling keeps the floor.
        recommended = max(1, math.ceil(projected / mean_ceiling - 1e-9))
    else:
        recommended = max(1, reporting)
    if shed_rps > 0.0 and reporting > 0:
        # Shed-aware floor: a fleet turning requests away at admission is
        # saturated by OBSERVATION, whatever the ceiling arithmetic claims
        # (the roofline blend is wildly optimistic off-TPU, and a ceiling
        # too generous would otherwise pin the recommendation at the
        # current size while clients eat 429s). Demand in requests/s is
        # admitted + shed; what the current fleet actually services is the
        # admitted rate, so size by their ratio.
        if admitted_rps > 0.0:
            factor = (admitted_rps + shed_rps) / admitted_rps
            recommended = max(recommended,
                              math.ceil(reporting * factor - 1e-9))
        else:
            recommended = max(recommended, reporting + 1)
    return {
        "replicas": replicas,
        "fleet": {
            "reporting_replicas": reporting,
            "missing_replicas": len(fleet) - reporting,
            "saturated_replicas": saturated,
            "idle_replicas": idle,
            # the autoscaler's scale-to-zero gate: every measured replica
            # reports zero offered load over its window
            "idle": reporting > 0 and idle == reporting,
            "offered_tps": round(offered, 6),
            "admitted_rps": round(admitted_rps, 6),
            "shed_rps": round(shed_rps, 6),
            "ceiling_tps": round(ceiling, 6),
            "utilization": round(offered / ceiling, 6) if ceiling > 0
            else 0.0,
            "projected_offered_tps": round(projected, 6),
            "recommended_replicas": recommended,
        },
    }


def start_load_poller(pool: BackendPool, interval_s: float = 1.0,
                      stop: threading.Event | None = None,
                      metrics: RouterMetrics | None = None
                      ) -> threading.Thread:
    """~1 Hz poller: /load samples for alive replicas (feeding
    BackendPool.note_load) and a /healthz RECOVERY probe for cooling-down
    ones — a restarted replica that answers healthy again re-enters rotation
    within one poll interval instead of serving out its whole cooldown
    (ISSUE r7 satellite; a stalled replica answers 503 and stays out). A
    failed poll just leaves the replica's sample to the stale-TTL — the
    request path's connect failures own dead-marking."""

    def poll_one(addr, cooling=False):
        host, _, port = addr.rpartition(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=2.0)
        try:
            if cooling:
                # recovery probe: /healthz, not /load — a wedged engine
                # still answers /load 200 but /healthz 503 ("stalled"),
                # and it must NOT re-attract traffic
                conn.request("GET", "/healthz")
                if conn.getresponse().status == 200 \
                        and pool.note_recovered(addr):
                    log.info("backend %s healthy again; back in rotation",
                             addr)
                    if metrics is not None:
                        metrics.recovered.inc()
                return
            conn.request("GET", "/load")
            resp = conn.getresponse()
            if resp.status == 200:
                d = json.loads(resp.read())
                if isinstance(d, dict):
                    # drain recognition (r8): a draining replica leaves
                    # rotation WITHOUT dead-marking and re-enters within
                    # one poll of draining going false (drain cancelled,
                    # or the drained pod restarted)
                    if d.get("draining"):
                        if pool.note_draining(addr):
                            log.info("backend %s draining; out of rotation",
                                     addr)
                    elif pool.clear_draining(addr):
                        log.info("backend %s done draining; back in "
                                 "rotation", addr)
                    pool.note_load(addr, d.get("active", 0) or 0,
                                   d.get("queued", 0) or 0)
            # SLO/flight fleet summary rides the same poll (same keep-alive
            # connection): /healthz carries burn rates, throughput, pool
            # pressure, and the flight recorder's last anomaly — the data
            # /debug/fleet and tputop render. A 503 still carries the JSON
            # (stalled/draining replicas are exactly the interesting rows).
            conn.request("GET", "/healthz")
            hresp = conn.getresponse()
            h = json.loads(hresp.read())
            if isinstance(h, dict):
                pool.note_health(addr, h)
        # tpulint: disable=R3 poller survival — a malformed /load reply must degrade to the stale-TTL path, never kill the poller thread
        except Exception:
            # NEVER let a malformed reply kill the poller thread — the
            # router would silently degrade to round-robin for its whole
            # lifetime (review r4). A failed poll just leaves the
            # replica's sample to the stale-TTL.
            log.debug("poll of %s failed", addr, exc_info=True)
        finally:
            conn.close()

    def poll_once():
        addrs = pool.addrs()
        cooling = set(pool.cooling())
        # CONCURRENT polls (cooling replicas get the cheap recovery probe):
        # a few blackholed pod IPs during a rolling restart must not stretch
        # the cycle past LOAD_TTL_S and stale out every healthy sample
        # (review r4) — the bounded join below caps the cycle either way
        threads = []
        for addr in addrs:
            t = threading.Thread(target=poll_one,
                                 args=(addr, addr in cooling), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=2.5)

    def run():
        while stop is None or not stop.is_set():
            poll_once()
            if stop is not None and stop.wait(interval_s):
                break
            if stop is None:
                time.sleep(interval_s)

    t = threading.Thread(target=run, daemon=True,
                         name="router-load-poller")
    t.start()
    return t


def _failover_spec(path: str, body: bytes | None):
    """The parsed request body when this request is eligible for mid-stream
    failover, else None.

    Eligible = a single-choice streaming completion the backend tags with
    per-chunk ``token_ids``: the router can then re-issue a dying stream to
    another replica as a deterministic continuation (resume_token_ids +
    resume_text_chars) and splice only new chunks. Multi-choice (n/best_of),
    echo, and requests that are already continuations stay on the
    truncate-on-death path."""
    if body is None or not path.startswith(("/v1/completions",
                                           "/v1/chat/completions")):
        return None
    try:
        obj = json.loads(body)
    except ValueError:
        return None
    if not isinstance(obj, dict) or not obj.get("stream"):
        return None
    if obj.get("n", 1) != 1 or obj.get("best_of", 1) != 1:
        return None
    if obj.get("echo") or obj.get("resume_token_ids") is not None:
        return None
    return obj


def _track_sse_event(event: bytes, st: dict):
    """Account one relayed SSE event into the failover state: generated
    token ids covered, generated-text chars the client now has, [DONE]."""
    if not event.startswith(b"data: "):
        return
    payload = event[len(b"data: "):].strip()
    if payload == b"[DONE]":
        st["done"] = True
        return
    try:
        obj = json.loads(payload)
    except ValueError:
        return
    if not isinstance(obj, dict):
        return
    for c in obj.get("choices") or []:
        if not isinstance(c, dict):
            continue
        if "token_ids" in c:
            # the backend speaks the failover dialect: relayed text is
            # fully accounted by relayed token ids, so continuation is safe
            st["tagged"] = True
            st["token_ids"].extend(int(t) for t in c.get("token_ids") or [])
        txt = c.get("text")
        if txt is None:
            txt = (c.get("delta") or {}).get("content")
        if isinstance(txt, str):
            st["chars"] += len(txt)


def _continuation_body(fo: dict, st: dict) -> bytes:
    """The continuation request for a stream that died after relaying
    ``st``: original body + resume fields, max_tokens decremented to the
    REMAINING budget (the backend adds the resume length back — a body
    without max_tokens keeps the server default as the total budget)."""
    obj = dict(fo)
    obj["resume_token_ids"] = list(st["token_ids"])
    obj["resume_text_chars"] = int(st["chars"])
    if "max_tokens" in fo:
        try:
            obj["max_tokens"] = max(0, int(fo["max_tokens"])
                                    - len(st["token_ids"]))
        except (TypeError, ValueError):
            pass
    return json.dumps(obj).encode()


class RouterHandler(BaseHTTPRequestHandler):
    pool: BackendPool = None       # injected by serve()
    metrics: RouterMetrics = None  # injected by serve()
    tracer: tracing.Tracer = None  # injected by serve(); None = no spans
    protocol_version = "HTTP/1.1"
    # Per-request trace state (class defaults so keep-alive connections
    # never leak a previous request's spans into the next).
    _root_span = None
    _hop_span = None
    _trace_ctx = None
    _next_kind = "first"

    def log_message(self, fmt, *args):  # quiet; structured logging below
        log.debug(fmt, *args)

    def _respond_json(self, code: int, obj: dict):
        if self._trace_ctx is not None and isinstance(obj.get("error"),
                                                      dict):
            # log correlation on gateway-originated errors (408/429/502/
            # 503): the ids to look the request up in Tempo
            obj["error"].setdefault("trace_id", self._trace_ctx.trace_id)
            obj["error"].setdefault("span_id", self._trace_ctx.span_id)
        if self._root_span is not None:
            self._root_span.set_attribute("http.status_code", code)
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- dispatch-hop span plumbing ------------------------------------------
    # One "router.dispatch" child span per attempt at a backend. The loop
    # body only ever calls _hop_begin at the attempt's top and _hop_end at
    # each branch that settles the attempt — ``next_kind`` names what the
    # FOLLOWING attempt will be (failover / retry_429 / stream_continuation),
    # which is how the golden span-tree test tells a 429 retry hop from a
    # connect failover hop.

    def _hop_begin(self, addr: str, index: int):
        if self._root_span is None:
            return
        self._hop_span = self.tracer.start_span(
            "router.dispatch", parent=self._root_span.context,
            kind=tracing.KIND_CLIENT,
            attributes={"backend.addr": addr, "dispatch.index": index,
                        "dispatch.kind": self._next_kind})

    def _hop_attr(self, key: str, value):
        if self._hop_span is not None:
            self._hop_span.set_attribute(key, value)

    def _hop_end(self, outcome: str = "", next_kind: str = ""):
        if self._hop_span is not None:
            if outcome:
                self._hop_span.set_attribute("dispatch.outcome", outcome)
            self.tracer.finish(self._hop_span)
            self._hop_span = None
        if next_kind:
            self._next_kind = next_kind

    def _proxy(self, method: str):
        """Root-span wrapper around the dispatch loop: opens (or continues,
        when the client sent a ``traceparent``) the trace whose child hops
        the loop emits, and guarantees both the dangling hop and the root
        are finished however the loop exits."""
        tracer = self.tracer
        if tracer is None or self.path.split("?")[0] in (
                "/health", "/metrics", "/debug/fleet", "/debug/capacity"):
            return self._proxy_impl(method)
        parent = tracing.parse_traceparent(
            self.headers.get(tracing.TRACEPARENT_HEADER))
        self._root_span = tracer.start_span(
            "router.request", parent=parent, kind=tracing.KIND_SERVER,
            attributes={"http.method": method,
                        "http.target": self.path.split("?")[0]})
        self._trace_ctx = self._root_span.context
        self._hop_span = None
        self._next_kind = "first"
        try:
            return self._proxy_impl(method)
        except Exception as e:
            self._root_span.error(f"{type(e).__name__}: {e}")
            raise
        finally:
            self._hop_end()
            tracer.finish(self._root_span)
            self._root_span = None
            self._trace_ctx = None

    def _proxy_impl(self, method: str):
        if self.path == "/health":
            now = time.monotonic()
            with self.pool._lock:
                loads = {a: self.pool._load[a][0]
                         for a in self.pool._addrs
                         if a in self.pool._load
                         and now - self.pool._load[a][1] <= LOAD_TTL_S}
                # same expiry pick() applies — a router receiving only
                # health probes must not report recovered replicas as
                # cooling down forever (review r4)
                dead = sorted(a for a, t in self.pool._dead.items()
                              if now - t < self.pool.cooldown_s)
                draining = sorted(a for a, t in self.pool._draining.items()
                                  if now - t < DRAIN_TTL_S)
            self._respond_json(200, {"status": "ok",
                                     "backends": self.pool._addrs,
                                     # fresh per-replica active+queued from
                                     # the /load poller; absent = unknown
                                     "backend_load": loads,
                                     "cooling_down": dead,
                                     "draining": draining})
            return
        if self.path == "/metrics":
            # The router's OWN counters (not proxied): the engine pods are
            # scraped directly by pod discovery; this route makes the gateway
            # itself visible to L5. The shared flight/SLO registries render
            # here too (tpulint R2's both-routes contract) — in the router
            # process they carry the GATEWAY's view (its own process has no
            # engine, so burn gauges stay at their exported defaults).
            slo.get().export()
            devmon.get().export()
            capacity.get().export()
            autoscaler.get().export()
            om = "application/openmetrics-text" in \
                (self.headers.get("Accept") or "")
            text = (self.metrics.registry.render(om)
                    + tracing.metrics.registry.render(om)
                    + flightrec.metrics.registry.render(om)
                    + slo.metrics.registry.render(om)
                    + devmon.metrics.registry.render(om)
                    + capacity.metrics.registry.render(om)
                    + autoscaler.metrics.registry.render(om)
                    + metrics.pipeline.registry.render(om))
            if om:
                text += "# EOF\n"
                ctype = ("application/openmetrics-text; version=1.0.0; "
                         "charset=utf-8")
            else:
                ctype = "text/plain; version=0.0.4"
            body = text.encode()
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if self.path.split("?")[0] == "/debug/fleet":
            # Fleet health aggregation (this PR): the poller's last /load +
            # /healthz sample per replica — burn rates, throughput, pool
            # pressure, last flight anomaly — in one gateway round trip.
            # tools/tputop.py renders this; ages tell a dashboard how stale
            # each row is (a silent replica keeps its last sample + age).
            doc = {
                "backends": list(self.pool.addrs()),
                "cooling_down": self.pool.cooling(),
                "draining": self.pool.draining(),
                "replicas": self.pool.fleet(),
            }
            a = autoscaler.get()
            if a.enabled:
                doc["autoscale"] = a.status()
            self._respond_json(200, doc)
            return
        if self.path.split("?")[0] == "/debug/autoscale":
            # The controller's own view: committed target vs actual,
            # standby/draining/stuck counts, decision journal head —
            # deploy/probes.py L3 and tools/tputop.py read this.
            self._respond_json(200, autoscaler.get().status())
            return
        if self.path.split("?")[0] == "/debug/capacity":
            # Fleet capacity aggregation: per-replica offered load vs
            # service ceiling from the poller's last /healthz ``capacity``
            # block, summed into fleet-level saturation + a fleet replica
            # recommendation. Replicas running a pre-capacity build (mixed
            # version fleet during a rollout) get an explicit
            # ``available: false`` row rather than poisoning the sums.
            self._respond_json(200, _fleet_capacity(self.pool.fleet()))
            return
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else None
        path = self.path.split("?")[0]
        affinity_key = None
        if method == "POST" and path in ("/v1/completions",
                                         "/v1/chat/completions"):
            affinity_key = _affinity_key(path, body)
        candidates = self.pool.pick(affinity_key)
        self.metrics.backends.set(len(self.pool._addrs))
        if not candidates and method == "POST" \
                and path.startswith("/v1/") and autoscaler.get().enabled:
            # Scale-to-zero wake-up: the fleet is parked and a request
            # arrived. Hold THIS request (bounded) while the autoscaler
            # promotes a standby or cold-starts a replica (AOT-backed:
            # the wait is the manifest ready-time, not a full compile),
            # then re-pick. A standby promotion resolves in ~one tick.
            if autoscaler.get().request_cold_start():
                candidates = self.pool.pick(affinity_key)
        if not candidates:
            self.metrics.requests.inc(code="503")
            self._respond_json(503, {"error": {
                "message": "no serving backends resolved", "type": "router_error"}})
            return
        hdrs = {h: self.headers[h]
                for h in ("Content-Type", "Authorization", "Accept",
                          DEADLINE_HEADER)
                if self.headers.get(h)}
        # End-to-end deadline, parsed ONCE: every re-dispatch (429 backoff,
        # connect failover, mid-stream continuation) forwards only the
        # REMAINING budget — sleeps and failed attempts eat real wall-clock
        # the backend's enforcement must count (r8 satellite; previously the
        # header was forwarded verbatim, so a second hop saw a fresh
        # deadline). The same remainder bounds this hop's read timeout. A
        # malformed header is forwarded verbatim; the backend answers 400.
        t_start = time.monotonic()
        ddl_ms = None
        raw_ddl = self.headers.get(DEADLINE_HEADER)
        if raw_ddl:
            try:
                ddl_ms = float(raw_ddl)
            except ValueError:
                pass    # backend rejects the malformed header with a 400
        # Mid-stream failover (r8): for an eligible stream, every relayed
        # SSE event is accounted (token ids / text chars / [DONE]) so a
        # replica death mid-stream re-enters this loop as a CONTINUATION —
        # original body + resume fields — and only new chunks reach the
        # client. ``headers_sent`` guards every would-send-status path.
        fo = _failover_spec(path, body) if method == "POST" else None
        fo_state = {"token_ids": [], "chars": 0, "done": False,
                    "tagged": False, "headers_sent": False, "failovers": 0}
        cur_body = body
        last_err = None
        shed = None          # last 429 body, relayed if every retry sheds
        drained = None       # last draining-503 body, relayed if all drain
        n_429 = 0
        for i, addr in enumerate(candidates):
            if i > 0 and not fo_state["headers_sent"]:
                self.metrics.failovers.inc()
            hdrs2 = dict(hdrs)
            self._hop_begin(addr, i)
            if self._hop_span is not None:
                # the hop span IS the backend's parent: the server's
                # request span hangs off this dispatch attempt, so a
                # failover's two attempts stay distinguishable in Tempo
                hdrs2[tracing.TRACEPARENT_HEADER] = \
                    tracing.format_traceparent(self._hop_span.context)
            read_to = READ_TIMEOUT_S
            if ddl_ms is not None:
                rem_ms = ddl_ms - (time.monotonic() - t_start) * 1000.0
                if rem_ms <= 0:
                    # deadline burnt inside the gateway: answering now beats
                    # dispatching work the backend must immediately expire
                    self._hop_end("deadline_exhausted")
                    if fo_state["headers_sent"]:
                        self.close_connection = True
                        return
                    self.metrics.requests.inc(code="408")
                    self._respond_json(408, {"error": {
                        "message": "request deadline exhausted during "
                                   "gateway retries",
                        "type": "timeout", "code": "deadline_exceeded"}})
                    return
                hdrs2[DEADLINE_HEADER] = str(int(max(1.0, rem_ms)))
                # the per-hop remaining budget: the golden span-tree test
                # asserts this decreases strictly across retry hops
                self._hop_attr("deadline.remaining_ms",
                               int(max(1.0, rem_ms)))
                # the remaining deadline bounds this hop's read timeout too:
                # the backend answers 408 within it, so waiting the full
                # READ_TIMEOUT_S past it only pins a router thread
                read_to = min(READ_TIMEOUT_S,
                              max(1.0, rem_ms / 1000.0)
                              + READ_TIMEOUT_GRACE_S)
            # Phase 1: CONNECT, with its own short timeout. Connect-level
            # failures (refused, unreachable, DNS) are always safe to retry on
            # the next replica — the request never reached a server, so even a
            # non-idempotent POST cannot have started generating (ADVICE r1:
            # retrying POSTs after a long read timeout duplicated in-flight
            # generations).
            a_host, _, a_port = addr.rpartition(":")
            conn = http.client.HTTPConnection(a_host, int(a_port),
                                              timeout=CONNECT_TIMEOUT_S)
            try:
                _chaos.get().check_connect(addr)   # fault injection hook
                conn.connect()
            except OSError as e:
                conn.close()
                self.pool.mark_dead(addr)
                self.metrics.dead_marks.inc()
                last_err = e
                self._hop_end("connect_failed", next_kind="failover")
                log.warning("backend %s connect failed (%s); trying next",
                            addr, e)
                continue
            # Phase 2: send + await response under the deadline-bounded read
            # timeout. The backend HAS the request now; a timeout here may
            # mean it is still generating. Requests with a body are NOT
            # retried past this point (a retry would duplicate the
            # generation on a second replica) — EXCEPT failover-eligible
            # streams, which re-issue as a continuation: whatever the dead
            # replica generated but didn't relay is re-derived
            # deterministically, and the client never sees a byte twice.
            try:
                conn.sock.settimeout(read_to)
                conn.request(method, self.path, body=cur_body, headers=hdrs2)
                resp = conn.getresponse()
            except OSError as e:
                conn.close()
                self.pool.mark_dead(addr)
                self.metrics.dead_marks.inc()
                last_err = e
                if cur_body is not None:
                    if fo is not None \
                            and fo_state["failovers"] < STREAM_FAILOVER_BUDGET \
                            and (fo_state["tagged"]
                                 or fo_state["chars"] == 0):
                        fo_state["failovers"] += 1
                        self.metrics.stream_failovers.inc()
                        cur_body = _continuation_body(fo, fo_state)
                        self._hop_end("backend_died",
                                      next_kind="stream_continuation")
                        log.warning("backend %s died pre-response (%s); "
                                    "re-issuing stream as continuation "
                                    "(%d tokens relayed)", addr, e,
                                    len(fo_state["token_ids"]))
                        continue
                    self._hop_end("backend_died")
                    log.warning("backend %s failed after accepting a request "
                                "body (%s); NOT retrying elsewhere", addr, e)
                    if fo_state["headers_sent"]:
                        self.close_connection = True
                        return
                    self.metrics.requests.inc(code="502")
                    self._respond_json(502, {"error": {
                        "message": f"backend failed mid-request: {e}",
                        "type": "router_error"}})
                    return
                self._hop_end("send_failed", next_kind="failover")
                log.warning("backend %s failed (%s); trying next", addr, e)
                continue
            # Phase 2.4: 503 + X-TPU-Draining = the replica shed at
            # admission because it is LEAVING (SIGTERM / preStop drain) —
            # nothing was generated, so re-routing is always safe, and the
            # replica is NOT dead-marked (no cooldown to serve out; the
            # poller excludes it until it stops draining).
            if resp.status == 503 and resp.headers.get("X-TPU-Draining"):
                drained = (resp.headers.get("Retry-After"), resp.read())
                conn.close()
                self.pool.note_draining(addr)
                self.metrics.draining_skips.inc()
                last_err = f"backend {addr} draining"
                self._hop_end("draining", next_kind="failover")
                log.info("backend %s draining; trying next", addr)
                continue
            # Phase 2.5: a 429 means the backend SHED the request at
            # admission — nothing was generated, so (unlike any other
            # post-send failure) retrying on the next replica is safe even
            # with a body. Jittered backoff, bounded budget; the replica is
            # NOT marked dead (it is healthy, just full). If every candidate
            # sheds, the last 429 (with its Retry-After) is the answer.
            if resp.status == 429:
                shed = (resp.headers.get("Retry-After"), resp.read())
                conn.close()
                if n_429 < RETRY_429_BUDGET and i < len(candidates) - 1:
                    n_429 += 1
                    self.metrics.retries_429.inc()
                    self._hop_end("shed_429", next_kind="retry_429")
                    import random as _random

                    time.sleep(RETRY_429_BACKOFF_S
                               * (0.5 + _random.random()))
                    continue
                self._hop_end("shed_429")
                if fo_state["headers_sent"]:
                    # a continuation shed everywhere: the open stream cannot
                    # become a 429 now — truncate
                    self.close_connection = True
                    return
                self._relay_shed(shed)
                return
            ctype = resp.headers.get("Content-Type", "application/json")
            if affinity_key is not None and resp.status < 500:
                # this replica now holds the prefix's pages — stick to it
                self.pool.note_affinity(affinity_key, addr)
            # Phase 3a: failover-capable SSE relay — COMPLETE events only
            # (the client must never hold half an event when the stream
            # switches replicas), each accounted into fo_state.
            if fo is not None and resp.status == 200 \
                    and "text/event-stream" in ctype:
                if not fo_state["headers_sent"]:
                    self.metrics.requests.inc(code="200")
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Connection", "close")
                    self.end_headers()
                    fo_state["headers_sent"] = True
                outcome = self._relay_sse(resp, addr, fo_state)
                conn.close()
                if outcome == "done":
                    if self._root_span is not None:
                        self._root_span.set_attribute("http.status_code",
                                                      200)
                    self._hop_end("stream_done")
                    return
                if outcome == "client_gone":
                    self._hop_end("client_gone")
                    # client disconnect, NOT a backend failure: no failover,
                    # no dead-mark (the backend cancels via broken pipe)
                    log.info("client disconnected mid-stream")
                    self.close_connection = True
                    return
                self.pool.mark_dead(addr)
                self.metrics.dead_marks.inc()
                if fo_state["failovers"] >= STREAM_FAILOVER_BUDGET \
                        or (fo_state["chars"] and not fo_state["tagged"]):
                    # can't (backend never tagged token ids) or won't
                    # (budget spent) continue: truncate, the pre-r8 behavior
                    self._hop_end("backend_died")
                    log.warning("backend %s died mid-stream; NOT failing "
                                "over (tagged=%s, failovers=%d)", addr,
                                fo_state["tagged"], fo_state["failovers"])
                    self.close_connection = True
                    return
                fo_state["failovers"] += 1
                self.metrics.stream_failovers.inc()
                cur_body = _continuation_body(fo, fo_state)
                self._hop_end("backend_died",
                              next_kind="stream_continuation")
                log.warning("backend %s died mid-stream after %d tokens / "
                            "%d chars; continuing on the next replica",
                            addr, len(fo_state["token_ids"]),
                            fo_state["chars"])
                continue
            if fo_state["headers_sent"]:
                # a continuation answered something that isn't a stream
                # (4xx/5xx app error): the open SSE response cannot change
                # status — truncate
                self._hop_end("unexpected_status")
                conn.close()
                log.warning("continuation on %s answered %s; truncating "
                            "stream", addr, resp.status)
                self.close_connection = True
                return
            # Phase 3b: plain relay. A 4xx/5xx status is the app's answer,
            # not a dead replica — passed through as-is. A failure while
            # relaying must NOT retry another replica (that would splice a
            # second status line into the body) and a client disconnect
            # (BrokenPipeError) must NOT mark the backend dead.
            try:
                self.metrics.requests.inc(code=str(resp.status))
                if self._root_span is not None:
                    self._root_span.set_attribute("http.status_code",
                                                  resp.status)
                self._hop_attr("http.status_code", resp.status)
                self.send_response(resp.status)
                self.send_header("Content-Type", ctype)
                if "text/event-stream" in ctype:
                    # SSE: stream chunks through unbuffered; connection close
                    # delimits the body.
                    self.send_header("Connection", "close")
                    self.end_headers()
                    # read1 returns as soon as ANY bytes arrive — read(4096)
                    # would buffer whole events and defeat token streaming.
                    read1 = getattr(resp, "read1", None) or resp.read
                    while True:
                        chunk = read1(4096)
                        if not chunk:
                            break
                        self.wfile.write(chunk)
                        self.wfile.flush()
                else:
                    data = resp.read()
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
            except BrokenPipeError:
                log.info("client disconnected mid-response")
                self.close_connection = True
            except OSError as e:
                # Backend died mid-body: response is unsalvageable; cut the
                # connection so the client sees a truncated body, not a corrupt one.
                self.pool.mark_dead(addr)
                self.metrics.dead_marks.inc()
                log.warning("backend %s died mid-response: %s", addr, e)
                self.close_connection = True
            finally:
                conn.close()
            self._hop_end("relayed")
            return
        if fo_state["headers_sent"]:
            # a mid-stream failover ran out of replicas: truncate
            log.warning("stream abandoned: no replica could continue it")
            self.close_connection = True
            return
        if shed is not None:
            # every connectable replica shed the request: the honest answer
            # is the overload signal itself, not a 502
            self._relay_shed(shed)
            return
        if drained is not None:
            # the whole pool is draining (rolling restart trough): the
            # honest answer is the draining 503 + Retry-After, not a 502
            self.metrics.requests.inc(code="503")
            self.send_response(503)
            self.send_header("Content-Type", "application/json")
            self.send_header("X-TPU-Draining", "1")
            if drained[0]:
                self.send_header("Retry-After", drained[0])
            self.send_header("Content-Length", str(len(drained[1])))
            self.end_headers()
            self.wfile.write(drained[1])
            return
        self.metrics.requests.inc(code="502")
        self._respond_json(502, {"error": {
            "message": f"all backends failed: {last_err}", "type": "router_error"}})

    def _relay_shed(self, shed):
        """Answer with the backend's own 429 (Retry-After preserved)."""
        self.metrics.requests.inc(code="429")
        self.send_response(429)
        self.send_header("Content-Type", "application/json")
        if shed[0]:
            self.send_header("Retry-After", shed[0])
        self.send_header("Content-Length", str(len(shed[1])))
        self.end_headers()
        self.wfile.write(shed[1])

    def _relay_sse(self, resp, addr: str, st: dict) -> str:
        """Relay COMPLETE SSE events to the client, accounting each into the
        failover state (token ids / chars / [DONE]). Whole-event forwarding
        is what makes a mid-stream death spliceable: the client never holds
        half an event when the stream switches replicas. Returns ``"done"``
        (stream ended cleanly), ``"backend_died"`` (socket error, premature
        EOF, or chunked-body truncation), or ``"client_gone"``."""
        ch = _chaos.get()
        read1 = getattr(resp, "read1", None) or resp.read
        buf = b""
        n_events = 0
        while True:
            try:
                if ch.enabled:
                    # router-side fault point: injected mid-stream read error
                    ch.check_stream_read(addr, n_events)
                data = read1(4096)
            except (OSError, http.client.HTTPException):
                return "backend_died"
            if not data:
                # clean EOF before [DONE] = the replica shut down mid-stream
                return "done" if st["done"] else "backend_died"
            buf += data
            while b"\n\n" in buf:
                event, buf = buf.split(b"\n\n", 1)
                try:
                    self.wfile.write(event + b"\n\n")
                    self.wfile.flush()
                except OSError:
                    return "client_gone"
                _track_sse_event(event, st)
                n_events += 1

    def do_GET(self):
        self._proxy("GET")

    def do_POST(self):
        self._proxy("POST")


def serve(backend_service: str, host: str, port: int,
          otlp_endpoint: str = "", trace_sample: float = 1.0,
          autoscale: bool = False, autoscale_launch_cmd: str = "",
          autoscale_kw: dict | None = None):
    RouterHandler.pool = BackendPool(backend_service)
    RouterHandler.metrics = RouterMetrics()
    RouterHandler.tracer = tracing.build_tracer(
        "tpu-serve-router", endpoint=otlp_endpoint or None,
        sample=trace_sample)
    start_load_poller(RouterHandler.pool, metrics=RouterHandler.metrics)
    if autoscale:
        a = autoscaler.configure(enabled=True, **(autoscale_kw or {}))
        launcher = None
        if autoscale_launch_cmd:
            launcher = autoscaler.CommandLauncher(autoscale_launch_cmd)
        a.install(pool=RouterHandler.pool, launcher=launcher)
        for addr in RouterHandler.pool.addrs():
            a.adopt(addr)
        a.start()
    httpd = ThreadingHTTPServer((host, port), RouterHandler)
    log.info("router listening on %s:%d -> %s", host, port, backend_service)
    httpd.serve_forever()


def main(argv=None):
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    p = argparse.ArgumentParser(description="TPU serving gateway router")
    p.add_argument("--backend-service", required=True,
                   help="host:port of the engine Service (DNS resolved to replicas)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--otlp-endpoint", default="",
                   help="OTLP/HTTP trace collector base URL; empty falls "
                        "back to $OTEL_EXPORTER_OTLP_ENDPOINT, neither = "
                        "spans stay local")
    p.add_argument("--trace-sample", type=float, default=1.0,
                   help="root-span sampling probability in [0, 1]")
    p.add_argument("--autoscale", type=int, default=0,
                   help="1 = run the replica autoscaler in this gateway: "
                        "consume /debug/capacity's fleet recommendation, "
                        "launch/drain replicas to match (serving/"
                        "autoscaler.py)")
    p.add_argument("--autoscale-launch-cmd", default="",
                   help="replica launch command template with a {port} "
                        "placeholder (CommandLauncher); empty = the "
                        "autoscaler can only drain/adopt, never launch")
    p.add_argument("--autoscale-min", type=int, default=1,
                   help="replica floor (0 enables scale-to-zero: an idle "
                        "fleet parks and the first request cold-starts it)")
    p.add_argument("--autoscale-max", type=int, default=8,
                   help="replica ceiling")
    p.add_argument("--autoscale-standby", type=int, default=-1,
                   help="prewarmed standby replicas kept ready out of "
                        "rotation (-1 = derive from the AOT ready-time)")
    p.add_argument("--autoscale-interval", type=float,
                   default=autoscaler.DEFAULT_INTERVAL_S,
                   help="reconcile tick seconds")
    p.add_argument("--autoscale-stable", type=float,
                   default=autoscaler.DEFAULT_STABLE_S,
                   help="hysteresis: a target change must persist this "
                        "long before it commits")
    p.add_argument("--autoscale-cooldown", type=float,
                   default=autoscaler.DEFAULT_COOLDOWN_S,
                   help="minimum seconds between direction reversals "
                        "(flap suppression)")
    p.add_argument("--autoscale-idle-timeout", type=float,
                   default=autoscaler.DEFAULT_IDLE_TIMEOUT_S,
                   help="idle seconds before scale-to-zero parks the "
                        "fleet (only with --autoscale-min 0)")
    args = p.parse_args(argv)
    serve(args.backend_service, args.host, args.port,
          otlp_endpoint=args.otlp_endpoint, trace_sample=args.trace_sample,
          autoscale=bool(args.autoscale),
          autoscale_launch_cmd=args.autoscale_launch_cmd,
          autoscale_kw=dict(min_replicas=args.autoscale_min,
                            max_replicas=args.autoscale_max,
                            standby=args.autoscale_standby,
                            interval_s=args.autoscale_interval,
                            stable_s=args.autoscale_stable,
                            cooldown_s=args.autoscale_cooldown,
                            idle_timeout_s=args.autoscale_idle_timeout))


if __name__ == "__main__":
    main()
