"""Inference gateway router: HTTP front door for N serving-engine replicas.

TPU-native replacement for the llm-d inference gateway (Go) that the reference
deploys via ``llmd-installer.sh`` and addresses at ``llm-d-test.yaml:14-26``.
The contract preserved:

- exposes the OpenAI surface (``/v1/*``) of the backends unchanged, so the L4
  test playbook's ephemeral curl pods work against the router exactly as they
  did against the llm-d gateway;
- load-balances across every replica behind the backend Service by resolving
  the DNS name to all A records per request batch (headless-Service friendly)
  and round-robining over them — the "latent DP" the reference hinted at with
  its two model PVCs (SURVEY.md §2.3);
- retries idempotent-safe failures on the next replica, taking a dead backend
  out of rotation for a cooldown window (the health-driven routing the
  reference delegated to the external gateway);
- streams responses through unbuffered (SSE passthrough for
  ``stream: true`` completions).

Stdlib-only (http.server + urllib) so the router container needs nothing
beyond the framework image.
"""

from __future__ import annotations

import argparse
import http.client
import itertools
import json
import logging
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from aws_k8s_ansible_provisioner_tpu.serving.metrics import (
    Counter, Gauge, Registry)

log = logging.getLogger("tpu_serve.router")

# Connect phase gets its own short timeout: a dead replica should fail over in
# seconds. The read timeout stays long (a non-streaming completion can
# legitimately generate for minutes). Keeping these distinct is what makes the
# retry policy safe — see _proxy (ADVICE r1: a single 600s timeout meant a
# slow POST could be replayed on a second backend while the first was still
# generating).
CONNECT_TIMEOUT_S = 5.0
READ_TIMEOUT_S = 600.0


class RouterMetrics:
    """Gateway-level request/failover counters for the L5 scrape (VERDICT r1
    weak #8: router requests were invisible to observability)."""

    def __init__(self):
        self.registry = Registry()
        r = self.registry
        self.requests = r.register(Counter(
            "tpu_router_requests_total", "Requests relayed, by response code",
            ("code",)))
        self.failovers = r.register(Counter(
            "tpu_router_failovers_total",
            "Requests retried on another replica after a connect failure"))
        self.dead_marks = r.register(Counter(
            "tpu_router_backend_dead_total",
            "Times a backend was taken out of rotation"))
        self.backends = r.register(Gauge(
            "tpu_router_backends", "Currently resolved backend replicas"))


class BackendPool:
    """Round-robin pool over the backend service's resolved replicas."""

    def __init__(self, backend_service: str, refresh_s: float = 10.0,
                 cooldown_s: float = 15.0):
        host, sep, port = backend_service.rpartition(":")
        if not sep or not host or not port.isdigit():
            raise ValueError(
                f"--backend-service must be host:port, got {backend_service!r}")
        self.host = host
        self.port = int(port)
        self.refresh_s = refresh_s
        self.cooldown_s = cooldown_s
        self._lock = threading.Lock()
        self._addrs: list[str] = []
        self._rr = itertools.count()
        self._dead: dict[str, float] = {}
        self._last_refresh = 0.0

    def _resolve(self) -> list[str]:
        try:
            infos = socket.getaddrinfo(self.host, self.port, socket.AF_INET,
                                       socket.SOCK_STREAM)
            return sorted({i[4][0] for i in infos})
        except socket.gaierror:
            return []

    def pick(self) -> list[str]:
        """Return candidate backends, healthiest-first (round-robin rotation)."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_refresh > self.refresh_s or not self._addrs:
                addrs = self._resolve()
                if addrs:
                    self._addrs = addrs
                self._last_refresh = now
            self._dead = {a: t for a, t in self._dead.items()
                          if now - t < self.cooldown_s}
            alive = [a for a in self._addrs if a not in self._dead]
            pool = alive or self._addrs  # all dead → try everything anyway
            if not pool:
                return []
            k = next(self._rr) % len(pool)
            return pool[k:] + pool[:k]

    def mark_dead(self, addr: str):
        with self._lock:
            self._dead[addr] = time.monotonic()

    def url(self, addr: str, path: str) -> str:
        return f"http://{addr}:{self.port}{path}"


class RouterHandler(BaseHTTPRequestHandler):
    pool: BackendPool = None       # injected by serve()
    metrics: RouterMetrics = None  # injected by serve()
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet; structured logging below
        log.debug(fmt, *args)

    def _respond_json(self, code: int, obj: dict):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _proxy(self, method: str):
        if self.path == "/health":
            self._respond_json(200, {"status": "ok",
                                     "backends": self.pool._addrs})
            return
        if self.path == "/metrics":
            # The router's OWN counters (not proxied): the engine pods are
            # scraped directly by pod discovery; this route makes the gateway
            # itself visible to L5.
            body = self.metrics.registry.render().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else None
        candidates = self.pool.pick()
        self.metrics.backends.set(len(self.pool._addrs))
        if not candidates:
            self.metrics.requests.inc(code="503")
            self._respond_json(503, {"error": {
                "message": "no serving backends resolved", "type": "router_error"}})
            return
        hdrs = {h: self.headers[h]
                for h in ("Content-Type", "Authorization", "Accept")
                if self.headers.get(h)}
        last_err = None
        for i, addr in enumerate(candidates):
            if i > 0:
                self.metrics.failovers.inc()
            # Phase 1: CONNECT, with its own short timeout. Connect-level
            # failures (refused, unreachable, DNS) are always safe to retry on
            # the next replica — the request never reached a server, so even a
            # non-idempotent POST cannot have started generating (ADVICE r1:
            # retrying POSTs after a long read timeout duplicated in-flight
            # generations).
            conn = http.client.HTTPConnection(addr, self.pool.port,
                                              timeout=CONNECT_TIMEOUT_S)
            try:
                conn.connect()
            except OSError as e:
                conn.close()
                self.pool.mark_dead(addr)
                self.metrics.dead_marks.inc()
                last_err = e
                log.warning("backend %s connect failed (%s); trying next",
                            addr, e)
                continue
            # Phase 2: send + await response under the long read timeout. The
            # backend HAS the request now; a timeout here may mean it is still
            # generating. Requests with a body are NOT retried past this point
            # (a retry would duplicate the generation on a second replica);
            # bodyless GETs are idempotent and may fail over.
            try:
                conn.sock.settimeout(READ_TIMEOUT_S)
                conn.request(method, self.path, body=body, headers=hdrs)
                resp = conn.getresponse()
            except OSError as e:
                conn.close()
                self.pool.mark_dead(addr)
                self.metrics.dead_marks.inc()
                last_err = e
                if body is not None:
                    log.warning("backend %s failed after accepting a request "
                                "body (%s); NOT retrying elsewhere", addr, e)
                    self.metrics.requests.inc(code="502")
                    self._respond_json(502, {"error": {
                        "message": f"backend failed mid-request: {e}",
                        "type": "router_error"}})
                    return
                log.warning("backend %s failed (%s); trying next", addr, e)
                continue
            # Phase 3: relay to the client. A 4xx/5xx status is the app's
            # answer, not a dead replica — passed through as-is. A failure
            # while relaying must NOT retry another replica (that would splice
            # a second status line into the body) and a client disconnect
            # (BrokenPipeError) must NOT mark the backend dead.
            try:
                self.metrics.requests.inc(code=str(resp.status))
                self.send_response(resp.status)
                ctype = resp.headers.get("Content-Type", "application/json")
                self.send_header("Content-Type", ctype)
                if "text/event-stream" in ctype:
                    # SSE: stream chunks through unbuffered; connection close
                    # delimits the body.
                    self.send_header("Connection", "close")
                    self.end_headers()
                    # read1 returns as soon as ANY bytes arrive — read(4096)
                    # would buffer whole events and defeat token streaming.
                    read1 = getattr(resp, "read1", None) or resp.read
                    while True:
                        chunk = read1(4096)
                        if not chunk:
                            break
                        self.wfile.write(chunk)
                        self.wfile.flush()
                else:
                    data = resp.read()
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
            except BrokenPipeError:
                log.info("client disconnected mid-response")
                self.close_connection = True
            except OSError as e:
                # Backend died mid-body: response is unsalvageable; cut the
                # connection so the client sees a truncated body, not a corrupt one.
                self.pool.mark_dead(addr)
                self.metrics.dead_marks.inc()
                log.warning("backend %s died mid-response: %s", addr, e)
                self.close_connection = True
            finally:
                conn.close()
            return
        self.metrics.requests.inc(code="502")
        self._respond_json(502, {"error": {
            "message": f"all backends failed: {last_err}", "type": "router_error"}})

    def do_GET(self):
        self._proxy("GET")

    def do_POST(self):
        self._proxy("POST")


def serve(backend_service: str, host: str, port: int):
    RouterHandler.pool = BackendPool(backend_service)
    RouterHandler.metrics = RouterMetrics()
    httpd = ThreadingHTTPServer((host, port), RouterHandler)
    log.info("router listening on %s:%d -> %s", host, port, backend_service)
    httpd.serve_forever()


def main(argv=None):
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    p = argparse.ArgumentParser(description="TPU serving gateway router")
    p.add_argument("--backend-service", required=True,
                   help="host:port of the engine Service (DNS resolved to replicas)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8080)
    args = p.parse_args(argv)
    serve(args.backend_service, args.host, args.port)


if __name__ == "__main__":
    main()
