"""Deterministic fault-injection harness for the serving path.

The serving stack's failure behavior is part of its contract (DeepServe,
PAPERS.md: serving at scale is dominated by the overload/failure paths, not
the steady-state kernel). This module makes every defined degradation path
*drivable* from a test — deterministically, with no timing races — so
`tests/test_chaos.py` can assert the documented behavior for each fault:

==========================  ==============================================
fault                       defined degradation behavior
==========================  ==============================================
``connect_refused``         router marks the replica dead, fails over to
                            the next candidate, serves the request (safe:
                            nothing was sent), recovers the replica via the
                            poller's health probe
``stalled_decode``          engine step wedges; /healthz flips to 503
                            "stalled"; the watchdog aborts the step and the
                            affected requests fail with "error" — the
                            process survives and keeps serving
``page_exhaustion``         page allocation fails; the engine preempts the
                            lowest-progress request (recompute-resume) or
                            requeues the admission instead of wedging;
                            slots/pages fully released, no crash
``slow_client``             one slow-reading stream consumer backpressures
                            only its own handler thread; the engine and
                            sibling requests keep full throughput
``mid_stream_disconnect``   server cancels the engine request; the slot and
                            its pages release exactly once
``kill_stream``             the REPLICA dies mid-stream from its peer's
                            point of view: after ``after_chunks`` relayed
                            content chunks the server hard-RSTs the
                            connection and cancels the engine request —
                            the router fails the stream over to another
                            replica as a deterministic continuation
                            (resume_token_ids), splicing only new chunks
``stream_read_error``       router-side fault point: the SSE relay's read
                            from the backend raises after ``after_events``
                            relayed events — drives the failover path
                            without any server cooperation
``pipeline_fetch_error``    the deferred fetch of a pipelined decode
                            dispatch fails (transfer/XLA fault at the
                            block point): the in-flight dispatch is
                            discarded, its requests fail with "error"
                            through the normal teardown (slots/pages
                            released exactly once) and the engine keeps
                            serving
``ragged_feature_error``    a FEATURE operand of a ragged dispatch fails —
                            the guided-mask device upload surfaces its error
                            at the deferred fetch (``kind=guided``), or a
                            spec-decode verify row is corrupted at its
                            synchronous read (``kind=spec``). Either way the
                            dispatch is discarded with nothing emitted, its
                            requests fail with "error" through the normal
                            teardown (slots/pages released exactly once) and
                            the engine keeps serving — the feature paths
                            inherit the pipeline's failure contract
``kv_offload_error``        a host-tier KV entry about to be restored is
                            corrupted (truncated payload — a bad PCIe copy
                            or host-RAM bit rot stand-in): the engine's
                            fetch-time verification drops the entry, the
                            restorable extension truncates there and the
                            span re-prefills — tokens are never wrong, the
                            drop is counted
                            (``tpu_serve_kv_restore_dropped_total``).
                            ``entries`` caps how many of the chain's
                            entries are corrupted per firing (default all)
``span_export``             the OTLP trace collector misbehaves — refuses
                            connections, hangs, or answers 5xx (``mode``) —
                            only the exporter's background thread sees it:
                            requests succeed unchanged and the spans are
                            dropped and counted
                            (``tpu_serve_spans_dropped_total``)
``flight_dump_error``       the flight-recorder spool write fails (disk
                            full) or hangs (``mode``) — only the recorder's
                            background writer thread sees it: requests
                            succeed unchanged and the dump is dropped and
                            counted (``tpu_serve_flight_drops_total``)
``capacity_export_error``   the capacity estimator's gauge refresh raises
                            inside a /metrics or /healthz render: the
                            render proceeds with the previous gauge values,
                            the drop is counted
                            (``tpu_capacity_export_drops_total``) and
                            requests succeed unchanged — the estimator can
                            never block a request
``autoscale_launch_error``  a replica launch fails. ``mode=transient``
                            (default) raises an error matching
                            miniansible's TRANSIENT_PATTERNS — the
                            autoscaler must retry on its deterministic
                            capped backoff schedule; ``mode=fatal`` raises
                            an unclassifiable error — the autoscaler must
                            journal the give-up and keep reconciling.
                            Either way the failure is counted
                            (``tpu_autoscale_launch_failures{class}``) and
                            never wedges the controller
``autoscale_drain_stuck``   a draining replica's inflight count never
                            reaches zero (a wedged stream): the autoscaler
                            must flag it stuck after ``drain_stuck_s``
                            (``tpu_autoscale_stuck_replicas``, journal
                            entry) and force-reap it at
                            ``drain_escalate_s`` — escalation through the
                            reconcile path, never a wedged controller
``deadline``                (engine-native, no injection needed) request
                            past its deadline is cancelled, slot/pages
                            released, client gets 408 deadline_exceeded
``drain``                   (engine-native, no injection needed) SIGTERM /
                            /admin/drain sheds new admissions (503
                            "draining", router re-routes), finishes
                            in-flight work, exits 0 within drain_timeout_s
==========================  ==============================================

Server-side faults are *injected* through hook points in engine.py /
router.py / paged_kv.py; client-side faults (slow reader, mid-stream
disconnect) are *driven* by the socket-level helpers at the bottom, which
the chaos suite uses as its misbehaving clients.

Injection is programmatic (``chaos.get().inject(...)``) or via env/config:
``TPU_SERVE_CHAOS="stalled_decode:duration_s=2,page_exhaustion:times=3"``
— each entry is ``fault[:key=value]*`` with the counting keys ``after``
(skip the first N trigger sites) and ``times`` (fire for M triggers;
-1 = forever). Counting is per-process and deterministic: the Nth call to
:meth:`ChaosController.fire` behaves identically on every run.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Dict, Optional

FAULTS = ("connect_refused", "stalled_decode", "page_exhaustion",
          "slow_client", "mid_stream_disconnect", "kill_stream",
          "stream_read_error", "span_export", "pipeline_fetch_error",
          "ragged_dispatch_error", "ragged_feature_error",
          "flight_dump_error", "kv_offload_error",
          "capacity_export_error", "autoscale_launch_error",
          "autoscale_drain_stuck")


class InjectedFault(RuntimeError):
    """Base for failures raised by an armed fault (never raised unarmed)."""


class InjectedStall(InjectedFault):
    """A chaos-stalled decode step aborted by the engine watchdog."""


class _FaultSpec:
    __slots__ = ("name", "after", "times", "params", "triggers", "fired")

    def __init__(self, name: str, after: int = 0, times: int = 1, **params):
        self.name = name
        self.after = int(after)     # trigger sites to skip before firing
        self.times = int(times)     # firings before disarming (-1 = forever)
        self.params = params
        self.triggers = 0           # total fire() consultations
        self.fired = 0              # actual firings


class ChaosController:
    """Process-wide fault registry with deterministic trigger counting."""

    def __init__(self, spec: str = ""):
        self._lock = threading.Lock()
        self._specs: Dict[str, _FaultSpec] = {}
        if spec:
            self._parse(spec)

    # -- arming --------------------------------------------------------------

    def _parse(self, spec: str):
        for entry in spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            name, *kvs = entry.split(":")
            kwargs: Dict[str, object] = {}
            for kv in kvs:
                k, _, v = kv.partition("=")
                try:
                    kwargs[k] = json.loads(v)
                except (ValueError, TypeError):
                    kwargs[k] = v
            self.inject(name, **kwargs)

    def inject(self, fault: str, after: int = 0, times: int = 1, **params):
        """Arm ``fault``: skip its first ``after`` trigger sites, then fire
        for ``times`` triggers (-1 = until cleared)."""
        if fault not in FAULTS:
            raise ValueError(f"unknown fault {fault!r}; known: {FAULTS}")
        with self._lock:
            self._specs[fault] = _FaultSpec(fault, after=after, times=times,
                                            **params)

    def clear(self, fault: Optional[str] = None):
        with self._lock:
            if fault is None:
                self._specs.clear()
            else:
                self._specs.pop(fault, None)

    @property
    def enabled(self) -> bool:
        return bool(self._specs)

    def active(self, fault: str) -> Optional[dict]:
        """The fault's params if armed (without consuming a trigger)."""
        with self._lock:
            s = self._specs.get(fault)
            return dict(s.params) if s is not None else None

    def fire(self, fault: str) -> Optional[dict]:
        """Consume one trigger of ``fault``. Returns its params when this
        trigger fires, else None. Deterministic: depends only on the call
        count, never on time."""
        with self._lock:
            s = self._specs.get(fault)
            if s is None:
                return None
            s.triggers += 1
            if s.triggers <= s.after:
                return None
            if s.times >= 0 and s.fired >= s.times:
                return None
            s.fired += 1
            params = dict(s.params)
        # Every fired fault lands in the flight-recorder ring (outside the
        # chaos lock — the recorder takes its own; the deferred import
        # breaks the chaos <- flightrec module cycle). Drop-on-overflow:
        # recording can never block or fail the faulting path either.
        from aws_k8s_ansible_provisioner_tpu.serving import flightrec

        flightrec.record("chaos_fault", None, fault=fault)
        return params

    def stats(self) -> dict:
        with self._lock:
            return {n: {"triggers": s.triggers, "fired": s.fired}
                    for n, s in self._specs.items()}

    # -- server-side hook points ---------------------------------------------

    def on_decode_step(self, engine) -> None:
        """engine._do_decode entry: an armed ``stalled_decode`` wedges the
        step (host-side busy-wait standing in for a hung device dispatch)
        until the watchdog's abort flag flips — then raises InjectedStall,
        which run_forever turns into failed requests, not a dead process.
        ``duration_s`` caps the stall so an un-watched engine self-heals."""
        p = self.fire("stalled_decode")
        if p is None:
            return
        duration = float(p.get("duration_s", 5.0))
        t0 = time.monotonic()
        while time.monotonic() - t0 < duration:
            if getattr(engine, "_stall_abort", False):
                raise InjectedStall(
                    "chaos: stalled decode step aborted by watchdog after "
                    f"{time.monotonic() - t0:.2f}s")
            time.sleep(0.005)

    def on_pipeline_fetch(self, engine) -> None:
        """EnginePrograms._decode_fetch entry: an armed
        ``pipeline_fetch_error`` raises in place of the blocking device
        read — standing in for a transfer/XLA failure that only surfaces at
        the deferred block point of an asynchronously-dispatched program.
        step() unwinds, run_forever's catch-all fails the affected requests
        (_fail_all discards the in-flight record first so nothing re-fetches
        the poisoned dispatch) and the engine keeps serving."""
        p = self.fire("pipeline_fetch_error")
        if p is None:
            return
        raise InjectedFault(
            "chaos: injected pipelined decode fetch failure")

    def on_mixed_fetch(self, engine) -> None:
        """EnginePrograms._decode_fetch entry for RAGGED MIXED records
        only: an armed ``ragged_dispatch_error`` raises at the blocking
        read of a mixed (prefill-chunk + decode) dispatch. The in-flight
        record is discarded, the chunk walk's error path releases the
        half-prefilled slot's pages exactly once (it clears ``_chunk``
        before re-raising, so _fail_all cannot release it a second time),
        and the engine keeps serving."""
        p = self.fire("ragged_dispatch_error")
        if p is None:
            return
        raise InjectedFault(
            "chaos: injected ragged mixed-dispatch failure")

    def on_feature_path(self, engine, kind: str) -> None:
        """Feature-operand fault sites of the ragged pipeline (ISSUE 16):
        ``kind="guided"`` fires at the deferred fetch of a dispatch that
        carried a grammar allow-mask operand (the one-step-ahead async
        upload surfacing a transfer error at its block point);
        ``kind="spec"`` fires at the synchronous read of a spec-decode
        verify result (a corrupted verify row). An armed
        ``ragged_feature_error`` raises InjectedFault — step() unwinds,
        run_forever's catch-all discards the in-flight record un-emitted
        and fails the affected requests with "error" (slots/pages released
        exactly once), and the engine keeps serving. ``kind=...`` in the
        fault params restricts firing to one feature path; trigger counting
        only consumes on matching sites, so after/times stay deterministic
        per path."""
        p = self.active("ragged_feature_error")
        if p is None:
            return
        want = p.get("kind")
        if want and str(want) != kind:
            return
        if self.fire("ragged_feature_error") is None:
            return
        raise InjectedFault(
            f"chaos: injected ragged feature-path failure ({kind})")

    def on_kv_restore(self, tier, host_keys) -> None:
        """engine._host_entries, before the host-tier payloads of a restore
        are fetched: an armed ``kv_offload_error`` truncates the entries'
        payloads in place (HostTier.corrupt) — standing in for a bad PCIe
        copy or host-RAM corruption discovered only at restore time. The
        engine's fetch-time shape verification then drops the entries and
        re-prefills the span: degraded latency, never wrong tokens.
        ``entries`` caps how many of the chain's entries are corrupted per
        firing (default: all of them)."""
        p = self.fire("kv_offload_error")
        if p is None:
            return
        n = int(p.get("entries", len(host_keys)))
        for key in list(host_keys)[:max(0, n)]:
            tier.corrupt(key)

    def on_engine_step(self, engine) -> None:
        """engine.step entry: an armed ``page_exhaustion`` makes the page
        allocators refuse the next ``allocs`` (default 1) allocation calls
        (paged_kv.PagePool.fail_next_allocs) — exercising the requeue and
        preempt-under-pressure paths with a pool that is *logically* dry."""
        p = self.fire("page_exhaustion")
        if p is None:
            return
        n = int(p.get("allocs", 1))
        for alloc in getattr(engine, "allocators", ()):
            alloc.fail_next_allocs += n

    def check_connect(self, addr: str) -> None:
        """router connect phase: an armed ``connect_refused`` raises the
        same ConnectionRefusedError a dead replica produces, before any
        bytes leave the router. ``addr_prefix`` restricts it to matching
        backends."""
        p = self.fire("connect_refused")
        if p is None:
            return
        prefix = str(p.get("addr_prefix", ""))
        if prefix and not addr.startswith(prefix):
            return
        raise ConnectionRefusedError(f"chaos: injected connect refusal "
                                     f"for backend {addr}")

    def on_stream_chunk(self, handler, n_chunks: int) -> None:
        """server _stream_response, after each relayed content chunk: an
        armed ``kill_stream`` hard-closes (SO_LINGER-0 RST) the client
        connection once the stream has emitted ``after_chunks`` chunks —
        the replica "dies" mid-stream from its peer's (the router's) point
        of view — then raises InjectedFault so the stream handler unwinds
        and cancels the engine request exactly like a real broken pipe.
        Per-STREAM chunk counting is the caller's (``n_chunks``); the
        controller's deterministic times/after budget decides which streams
        die."""
        p = self.active("kill_stream")
        if p is None or n_chunks < int(p.get("after_chunks", 1)):
            return
        if self.fire("kill_stream") is None:
            return
        import struct as _struct
        # RST, not FIN: a clean close is how SSE legitimately ENDS — a
        # crashed replica resets. The makefile objects hold fd refs, so
        # close them FIRST (idempotently re-closed by the handler's own
        # finish()), then the socket close actually sends the RST.
        handler.close_connection = True
        try:
            handler.connection.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER,
                _struct.pack("ii", 1, 0))
        except OSError:
            pass
        for f in (handler.wfile, handler.rfile, handler.connection):
            try:
                f.close()
            except OSError:
                pass
        # the http.server plumbing still flushes/closes wfile/rfile after
        # the handler unwinds — hand it harmless sinks, not the dead socket
        import io as _io
        handler.wfile = _io.BytesIO()
        handler.rfile = _io.BytesIO(b"")
        raise InjectedFault(f"chaos: replica killed mid-stream after "
                            f"{n_chunks} chunks")

    def check_stream_read(self, addr: str, n_events: int) -> None:
        """router SSE relay, before each backend read: an armed
        ``stream_read_error`` raises the ConnectionResetError a dying
        backend socket produces once ``after_events`` events were relayed —
        the failover path is drivable without any server cooperation.
        ``addr_prefix`` restricts it to matching backends."""
        p = self.active("stream_read_error")
        if p is None or n_events < int(p.get("after_events", 1)):
            return
        p = self.fire("stream_read_error")
        if p is None:
            return
        prefix = str(p.get("addr_prefix", ""))
        if prefix and not addr.startswith(prefix):
            return
        raise ConnectionResetError(f"chaos: injected mid-stream read "
                                   f"failure from backend {addr}")

    def on_span_export(self) -> None:
        """tracing.OTLPHTTPExporter._send entry (exporter background thread
        ONLY — never a request thread): an armed ``span_export`` makes the
        trace collector misbehave per ``mode``: ``refuse`` (default) raises
        the ConnectionRefusedError of a dead collector; ``hang`` sleeps
        ``hang_s`` (default 5.0, standing in for a wedged endpoint — still
        on the background thread, so request latency is untouched) then
        raises; ``5xx`` models a collector that answers but rejects. All
        three must resolve to dropped-and-counted spans, never a failed or
        stalled request — tests/test_tracing.py asserts that contract."""
        p = self.fire("span_export")
        if p is None:
            return
        mode = str(p.get("mode", "refuse"))
        if mode == "hang":
            time.sleep(float(p.get("hang_s", 5.0)))
            raise OSError("chaos: span export hung, then timed out")
        if mode == "5xx":
            raise InjectedFault("chaos: trace collector answered 503")
        raise ConnectionRefusedError("chaos: trace collector refused "
                                     "connection")

    def on_flight_dump(self) -> None:
        """flightrec.FlightRecorder._write entry (spool writer background
        thread ONLY — never a request thread): an armed ``flight_dump_error``
        makes the spool write misbehave per ``mode``: ``oserror`` (default)
        raises the OSError of a full disk; ``hang`` sleeps ``hang_s``
        (default 2.0 — still on the writer thread, so request latency is
        untouched) then raises. Both must resolve to a dropped-and-counted
        dump (``tpu_serve_flight_drops_total{reason="dump_error"}``), never
        a failed or stalled request — tests/test_flightrec.py asserts that
        contract, the mirror of the span_export one."""
        p = self.fire("flight_dump_error")
        if p is None:
            return
        mode = str(p.get("mode", "oserror"))
        if mode == "hang":
            time.sleep(float(p.get("hang_s", 2.0)))
        raise OSError("chaos: flight spool write failed (disk full)")

    def on_capacity_export(self) -> None:
        """capacity.CapacityEstimator.export entry (a /metrics or /healthz
        handler thread — observability reads, never a request path): an
        armed ``capacity_export_error`` raises in place of the gauge
        refresh. export() must swallow it, count the drop
        (``tpu_capacity_export_drops_total``) and let the render proceed
        with the previous gauge values — tests/test_capacity.py asserts
        that drop-not-fail contract."""
        p = self.fire("capacity_export_error")
        if p is None:
            return
        raise InjectedFault("chaos: injected capacity export failure")

    def on_autoscale_launch(self) -> None:
        """autoscaler._do_launch entry (the reconcile tick — never a
        request thread): an armed ``autoscale_launch_error`` raises in
        place of the launcher call. ``mode=transient`` (default) phrases
        the error so ``miniansible.classify_failure`` tags it transient —
        the controller must schedule a deterministic-backoff retry;
        ``mode=fatal`` phrases it unclassifiably — the controller must
        journal the give-up. tests/test_autoscaler.py asserts both arms
        of that drop-not-fail contract."""
        p = self.fire("autoscale_launch_error")
        if p is None:
            return
        if str(p.get("mode", "transient")) == "fatal":
            raise InjectedFault(
                "chaos: replica manifest rejected by admission webhook "
                "(invalid spec)")
        raise InjectedFault(
            "chaos: cloud API temporarily unavailable provisioning "
            "replica VM")

    def on_autoscale_drain(self, addr: str) -> bool:
        """autoscaler._progress_drains poll (the reconcile tick): an
        armed ``autoscale_drain_stuck`` makes ``addr``'s inflight read as
        permanently nonzero — a wedged stream that never finishes. Each
        poll consumes one trigger, so ``times`` is the number of ticks
        the drain stays wedged: armed long enough it drives the
        stuck-flag (``drain_stuck_s``) and force-reap
        (``drain_escalate_s``) escalation path. ``addr_prefix`` restricts
        it to matching replicas."""
        p = self.fire("autoscale_drain_stuck")
        if p is None:
            return False
        prefix = str(p.get("addr_prefix", ""))
        if prefix and not addr.startswith(prefix):
            return False
        return True


_controller: Optional[ChaosController] = None
_controller_lock = threading.Lock()


def get() -> ChaosController:
    """The process-wide controller (created from $TPU_SERVE_CHAOS once)."""
    global _controller
    with _controller_lock:
        if _controller is None:
            _controller = ChaosController(os.environ.get("TPU_SERVE_CHAOS",
                                                         ""))
        return _controller


def reset() -> ChaosController:
    """Fresh controller (tests; re-reads $TPU_SERVE_CHAOS)."""
    global _controller
    with _controller_lock:
        _controller = None
    return get()


def kill_replica_after_chunks(k: int, times: int = 1, after: int = 0):
    """Arm the replica-kill-mid-stream scenario (ROADMAP robustness
    follow-on): the next ``times`` streams to emit ``k`` content chunks die
    with an RST at that point (server-side ``kill_stream`` fault). Under a
    router this drives the mid-stream failover path: the router re-issues
    the request to another replica as a deterministic continuation and
    splices only new chunks — tests/test_router_e2e.py asserts the client
    stream stays byte-identical to an undisturbed run."""
    get().inject("kill_stream", after=after, times=times, after_chunks=k)


# ---------------------------------------------------------------------------
# Client-side fault drivers (the misbehaving clients the chaos suite runs)
# ---------------------------------------------------------------------------


def _raw_post(host: str, port: int, path: str, payload: dict,
              timeout: float = 60.0) -> socket.socket:
    """Open a raw socket and send a POST; returns the connected socket with
    the response unread — the caller controls read pacing and lifetime."""
    body = json.dumps(payload).encode()
    req = (f"POST {path} HTTP/1.1\r\nHost: {host}:{port}\r\n"
           f"Content-Type: application/json\r\n"
           f"Content-Length: {len(body)}\r\n\r\n").encode() + body
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.sendall(req)
    return sock


def stream_then_disconnect(host: str, port: int, payload: dict,
                           path: str = "/v1/completions",
                           after_bytes: int = 1,
                           timeout: float = 60.0) -> bytes:
    """Mid-stream disconnect driver: start a streaming completion, read at
    least ``after_bytes`` of the SSE body, then drop the connection with a
    RST-ish abrupt close. Returns the bytes read before the drop."""
    payload = {**payload, "stream": True}
    sock = _raw_post(host, port, path, payload, timeout=timeout)
    got = b""
    try:
        while len(got) < after_bytes:
            chunk = sock.recv(4096)
            if not chunk:
                break
            got += chunk
    finally:
        # SO_LINGER 0: close sends RST, the hard-kill variant of a client
        # vanishing (wifi drop, OOM-killed consumer)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                            b"\x01\x00\x00\x00\x00\x00\x00\x00")
        except OSError:
            pass
        sock.close()
    return got


def slow_client_stream(host: str, port: int, payload: dict,
                       path: str = "/v1/completions",
                       read_delay_s: float = 0.2,
                       read_size: int = 1,
                       timeout: float = 120.0) -> bytes:
    """Slow-consumer driver: stream a completion reading ``read_size`` bytes
    per ``read_delay_s`` — TCP backpressure against the handler thread.
    Returns the full body once the server finishes."""
    payload = {**payload, "stream": True}
    sock = _raw_post(host, port, path, payload, timeout=timeout)
    got = b""
    deadline = time.monotonic() + timeout
    try:
        while time.monotonic() < deadline:
            chunk = sock.recv(max(1, read_size))
            if not chunk:
                break
            got += chunk
            if b"data: [DONE]" in got:
                break
            time.sleep(read_delay_s)
    finally:
        sock.close()
    return got
