"""Compiled-program registry: the serving engine's jit layer.

Everything XLA-compiled lives here, split out of ``serving/engine.py`` with
zero behavior change (ROADMAP / VERDICT next #7):

- the five jitted step functions — ``prefill_step`` (one program per prompt
  bucket), ``prefill_batch_step``, ``prefill_chunk_step``, ``decode_steps``
  (fused horizon), ``spec_decode_step`` — plus their pure helpers (logit
  bias, penalties, bans, logprob extraction);
- the decode batch-block autotune (``pick_decode_bblock`` and the per-config
  ``_BBLOCK_CACHE``);
- ``EnginePrograms``, the mixin ``Engine`` inherits: program-operand
  construction (dtype/quantize/shard/LoRA, paged pool + dense cache),
  prefill/decode/spec dispatch, and the ``warmup`` plan that enumerates and
  compiles every program variant a config can dispatch.

``serving/aot.py`` compiles the same enumeration ahead-of-time against an
abstract topology and writes the committed manifest; ``serving/engine.py``
keeps the host-side scheduler — admission, slots, paged-pool bookkeeping,
drain, streaming queues.
"""

from __future__ import annotations

import os
import time
from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from aws_k8s_ansible_provisioner_tpu.config import ModelConfig
from aws_k8s_ansible_provisioner_tpu.models.layers import (
    lora_context,
    model_forward,
    model_forward_carry,
)
from aws_k8s_ansible_provisioner_tpu.ops.attention import (
    make_chunk_prefill_attend,
    make_chunk_prefill_attend_paged_carry,
    make_decode_attend_carry,
    make_decode_attend_carry_paged,
    make_mixed_attend_carry_paged,
    make_prefill_attend,
    make_prefill_attend_batch,
    make_prefill_attend_batch_paged_carry,
    make_prefill_attend_paged_carry,
    make_spec_attend_carry,
    make_spec_attend_carry_paged,
)
from aws_k8s_ansible_provisioner_tpu.ops.sampling import (apply_allow,
                                                           apply_penalties,
                                                           per_slot_keys,
                                                           sample)
from aws_k8s_ansible_provisioner_tpu.serving import chaos as _chaos
from aws_k8s_ansible_provisioner_tpu.serving import devmon as _devmon
from aws_k8s_ansible_provisioner_tpu.serving import flightrec as _flight
from aws_k8s_ansible_provisioner_tpu.serving import kv_cache as kvc
from aws_k8s_ansible_provisioner_tpu.serving import metrics as _metrics
from aws_k8s_ansible_provisioner_tpu.serving import slo as _slo


# ---------------------------------------------------------------------------
# Pure jitted step functions
# ---------------------------------------------------------------------------


# Static top-k width for OpenAI ``logprobs`` responses (vLLM caps similarly);
# per-request k <= this is sliced on the host.
LOGPROB_K = 8

# Static width of the per-slot banned-token list (min_tokens stop
# suppression): eos set + stop_token_ids must fit. Rows pad with an
# out-of-vocab id, which the masking scatter DROPS.
BAN_K = 8

# Static width of the per-slot OpenAI ``logit_bias`` list (OpenAI caps the
# map at 300 entries; vLLM-grade clients rarely exceed a few dozen — the
# server rejects beyond this). Padding ids are out-of-vocab and DROP.
BIAS_K = 64

# Candidate batch-block sizes for the double-buffered paged decode kernel
# (ops/pallas_attention._paged_db_body): BB slots share one grid step, so
# each step issues BBx larger page DMAs and the per-substep grid-step count
# divides by BB. The best BB depends on (batch, page_size, kv_dtype) — the
# engine microbenches these at startup (PALLAS_DECODE_BBLOCK's off-by-default
# env gate, promoted to a first-class autotuned parameter in r6).
BBLOCK_CANDIDATES = (1, 4, 8)
# (batch, page_size, kv_dtype) -> chosen bb. Module-level so a second engine
# start in the same process (replica respawn, tests, bench retries) reuses
# the choice instead of re-running the microbench.
_BBLOCK_CACHE: dict = {}


def pick_decode_bblock(candidates, bench_once, timer=time.perf_counter,
                       reps: int = 3) -> int:
    """Deterministic selection: for each candidate (ascending), one untimed
    warmup call (compile + cache fill), then ``reps`` timed calls; the
    candidate with the lowest MEDIAN wins, ties going to the SMALLER block
    (strict < — so a fixed timer sequence always yields the same choice,
    and noise can only flip a decision across a real gap, not a tie)."""
    best_bb, best_t = None, None
    for bb in candidates:
        bench_once(bb)                      # warmup: compile outside timing
        times = []
        for _ in range(max(1, reps)):
            t0 = timer()
            bench_once(bb)
            times.append(timer() - t0)
        med = sorted(times)[len(times) // 2]
        if best_t is None or med < best_t:
            best_bb, best_t = bb, med
    return best_bb


def _apply_logit_bias(logits: jnp.ndarray, bias_ids, bias_vals) -> jnp.ndarray:
    """OpenAI ``logit_bias``: add per-request offsets to selected token
    logits before any sampling (greedy included — -100/+100 act as ban/
    force, the documented semantics). Always-on scatter-add: unbiased slots
    carry out-of-vocab ids that drop. bias_ids: [B, BIAS_K] int32;
    bias_vals: [B, BIAS_K] f32."""
    if bias_ids is None:
        return logits
    B = logits.shape[0]
    return logits.at[jnp.arange(B)[:, None], bias_ids].add(
        bias_vals.astype(logits.dtype), mode="drop")


def _apply_prefill_repetition(logits: jnp.ndarray, tokens, true_lens,
                              rep) -> jnp.ndarray:
    """repetition_penalty for the PREFILL-sampled first token: the seen-set
    is the prompt itself (tokens [N, T] with true_lens [N] masking the right
    padding). Always-on (no program variant): rep == 1.0 divides/multiplies
    by exactly 1.0, an exact no-op — same design as the ban/bias rows.
    Without this the first generated token escaped the penalty (review r4),
    diverging from HF/vLLM, whose processors see the prompt from token 0."""
    if rep is None:
        return logits
    N, V = logits.shape
    T = tokens.shape[1]
    cols = jnp.arange(T, dtype=jnp.int32)[None, :]
    ids = jnp.where(cols < true_lens[:, None], tokens, jnp.int32(2**31 - 1))
    seen = jnp.zeros((N, V), jnp.bool_)
    seen = seen.at[jnp.arange(N)[:, None], ids].set(True, mode="drop")
    r = rep[:, None].astype(jnp.float32)
    out = logits.astype(jnp.float32)
    return jnp.where(seen, jnp.where(out > 0, out / r, out * r), out)


def _mask_banned(logits: jnp.ndarray, ban_ids, ban_until, lens) -> jnp.ndarray:
    """vLLM ``min_tokens`` semantics: while a slot's context length is below
    ``ban_until`` (prompt_len + min_tokens), its stop tokens are masked to
    -inf BEFORE sampling — a suppressed eos is never produced, never
    streamed, never conditions later tokens. Always-on (no program variant):
    slots with nothing to ban carry out-of-vocab ids, and the scatter drops
    them. logits: [B, V]; ban_ids: [B, BAN_K] int32; ban_until/lens: [B]."""
    if ban_ids is None:
        return logits
    B = logits.shape[0]
    active = (lens < ban_until)[:, None]
    ids = jnp.where(active, ban_ids, jnp.int32(2**31 - 1))
    return logits.at[jnp.arange(B)[:, None], ids].set(-jnp.inf, mode="drop")


def _apply_allow(logits: jnp.ndarray, allow) -> jnp.ndarray:
    """Guided-decoding allow-bitmask (serving/guided.py): token v is allowed
    iff bit (v & 31) of ``allow[b, v >> 5]`` is set; everything else drops to
    the ban floor. ``allow`` is a program variant (None = compiled out):
    unguided traffic never pays the [B, V] bit-gather. Rows for unguided
    slots are all-ones. Applied AFTER bias/ban — a +100 bias must not
    resurrect a grammar-rejected token. logits: [B, V]; allow: [B, ceil(V/32)]
    uint32."""
    if allow is None:
        return logits
    return apply_allow(logits, allow)


def _logprob_topk(logits: jnp.ndarray, chosen: jnp.ndarray):
    """(chosen logprob [B], top-k logprobs [B, K], top-k ids [B, K]) from
    raw logits [B, V] — the OpenAI ``logprobs`` payload, computed on-device
    only in the logprob program variants (log_softmax + top_k over a 152k
    vocab is real VPU work the default hot path must not pay)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    sel = jnp.take_along_axis(logp, chosen[:, None].astype(jnp.int32),
                              axis=1)[:, 0]
    vals, ids = jax.lax.top_k(logp, min(LOGPROB_K, logp.shape[-1]))
    return sel, vals, ids.astype(jnp.int32)


def _prompt_logprobs(logits, tokens):
    """Per-position PROMPT logprobs (vLLM ``prompt_logprobs`` / OpenAI
    legacy echo+logprobs): entry t scores prompt token t+1 given tokens
    <= t (position 0 has no logprob, the OpenAI None convention).

    Sequential ``lax.map`` over positions: one [N, V] log_softmax + top-k
    at a time — materializing the full [N, T, V] f32 log-softmax would hold
    gigabytes at large buckets. Returns (sel [N, T-1], vals [N, T-1, K],
    ids [N, T-1, K])."""
    lg = jnp.swapaxes(logits[:, :-1], 0, 1)      # [T-1, N, V]
    nxt = jnp.swapaxes(tokens[:, 1:], 0, 1)      # [T-1, N]

    def per_pos(args):
        lg_t, tok = args
        lp = jax.nn.log_softmax(lg_t.astype(jnp.float32), -1)
        sel = jnp.take_along_axis(lp, tok[:, None].astype(jnp.int32),
                                  1)[:, 0]
        vals, ids = jax.lax.top_k(lp, min(LOGPROB_K, lp.shape[-1]))
        return sel, vals, ids.astype(jnp.int32)

    sel, vals, ids = jax.lax.map(per_pos, (lg, nxt))
    return (jnp.swapaxes(sel, 0, 1), jnp.swapaxes(vals, 0, 1),
            jnp.swapaxes(ids, 0, 1))


def _host_lp(lp_t, row: int, k: int):
    """Slice one row of a device (sel, vals, ids) triple into the host-side
    per-token logprob record: (own_logprob, [(token_id, logprob) x k])."""
    sel, vals, ids = lp_t
    sel = float(np.asarray(sel[row]))
    vals = np.asarray(vals[row])
    ids = np.asarray(ids[row])
    k = min(k, len(ids))
    return (sel, [(int(ids[j]), float(vals[j])) for j in range(k)])


@partial(jax.jit, donate_argnums=(0,))
def _reset_count_row(counts, slot, token):
    """Zero a recycled slot's generated-token counts and count its first
    token (penalties apply over GENERATED text; the prefill-sampled token is
    generated)."""
    counts = jax.lax.dynamic_update_slice(
        counts, jnp.zeros((1, counts.shape[1]), counts.dtype),
        (slot, jnp.int32(0)))
    return counts.at[slot, token].add(1)


@partial(jax.jit, donate_argnums=(0,))
def _set_mask_row(mask, slot, row):
    """Overwrite one slot's prompt-token presence row (repetition_penalty
    covers prompt tokens; set at activation, stale rows no-op at rep=1)."""
    return jax.lax.dynamic_update_slice(mask, row[None], (slot, jnp.int32(0)))


@partial(jax.jit, donate_argnums=(0,))
def _restore_count_row(counts, slot, row):
    """Overwrite one slot's counts row with a precomputed [V] histogram —
    restores a preempted request's penalty state on resume (its prior
    generated tokens are re-prefilled as CONTEXT, but penalties count them
    as GENERATED; without this the penalty would forget everything before
    the preemption)."""
    return jax.lax.dynamic_update_slice(
        counts, row[None].astype(counts.dtype), (slot, jnp.int32(0)))


@partial(jax.jit, static_argnums=(0,),
         static_argnames=("logprobs", "prompt_logprobs"),
         donate_argnums=(2,))
def prefill_step(cfg: ModelConfig, params, cache, tokens, true_len, slot, rng,
                 temperature, top_k, top_p, logprobs: bool = False,
                 pages=None, seed=None, ban_ids=None, ban_until=None,
                 bias_ids=None, bias_vals=None, rep=None, allow=None,
                 lora_idx=None, prompt_logprobs: bool = False):
    """Prefill one prompt into one slot; returns (cache, first sampled token).

    tokens: [1, T] right-padded to a bucket; true_len: scalar valid length;
    slot: scalar slot index. With ``pages`` ([max_pages] int32) the cache is
    the paged pool and rows scatter through the slot's block table
    (serving/paged_kv.py) — ``slot`` is then unused by the writer.
    """
    T = tokens.shape[1]
    positions = jnp.arange(T, dtype=jnp.int32)[None, :]
    with lora_context(lora_idx):
        if pages is not None:
            # carry path: the pool stays in the layer scan's carry — the
            # xs→ys restack buffer OOMed the batch-128 paged program on
            # chip (r5)
            attend = make_prefill_attend_paged_carry(
                pages, true_len, window=cfg.sliding_window)
            logits, cache = model_forward_carry(params, cfg, tokens,
                                                positions, cache, attend)
        else:
            attend = make_prefill_attend(slot, true_len,
                                         window=cfg.sliding_window)
            logits, cache = model_forward(params, cfg, tokens, positions,
                                          cache, attend)
    last = jnp.take(logits[0], true_len - 1, axis=0)[None]   # [1, V]
    last = _apply_prefill_repetition(last, tokens, true_len[None],
                                     rep[None] if rep is not None else None)
    if bias_ids is not None:
        last = _apply_logit_bias(last, bias_ids[None], bias_vals[None])
    if ban_ids is not None:
        last = _mask_banned(last, ban_ids[None], ban_until[None],
                            true_len[None])
    last = _apply_allow(last, allow)
    # Per-request seeded draw: key = (seed, position), so the stream is
    # reproducible across restarts/preemption (OpenAI `seed`). ``rng`` is
    # the legacy fallback when no seed rides the dispatch.
    keys = per_slot_keys(seed[None], true_len[None]) if seed is not None \
        else rng
    token = sample(last, keys, temperature[None], top_k[None],
                   top_p[None])[0]
    out = [cache, token]
    if logprobs:
        out.append(_logprob_topk(last, token[None]))
    if prompt_logprobs:
        out.append(_prompt_logprobs(logits[:1], tokens))
    return tuple(out)


@partial(jax.jit, static_argnums=(0,),
         static_argnames=("logprobs", "prompt_logprobs"),
         donate_argnums=(2,))
def prefill_batch_step(cfg: ModelConfig, params, cache, tokens, true_lens,
                       slots, rng, temperature, top_k, top_p,
                       logprobs: bool = False, tables=None, seeds=None,
                       ban_ids=None, ban_until=None,
                       bias_ids=None, bias_vals=None, reps=None, allow=None,
                       lora_idx=None, prompt_logprobs: bool = False):
    """Prefill N prompts into N slots in ONE dispatch.

    tokens: [N, T] right-padded to a (row, length) bucket; true_lens/slots/
    sampling params: [N]. Padding rows carry slot index == num_slots (their
    cache writes drop) — the host ignores their sampled tokens. Returns
    (cache, first tokens [N]). One program per (N-bucket, T-bucket) pair;
    under a burst this turns N serialized prefill dispatches into
    ceil(N/batch) (VERDICT r1 missing #4). With ``tables`` ([N, max_pages]
    int32; padding rows all OOB_PAGE) rows scatter through the paged pool.
    """
    N, T = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (N, T))
    with lora_context(lora_idx):
        if tables is not None:
            # carry path — see prefill_step's paged branch
            attend = make_prefill_attend_batch_paged_carry(
                tables, true_lens, window=cfg.sliding_window)
            logits, cache = model_forward_carry(params, cfg, tokens,
                                                positions, cache, attend)
        else:
            attend = make_prefill_attend_batch(slots, true_lens,
                                               window=cfg.sliding_window)
            logits, cache = model_forward(params, cfg, tokens, positions,
                                          cache, attend)
    last = logits[jnp.arange(N), true_lens - 1]            # [N, V]
    last = _apply_prefill_repetition(last, tokens, true_lens, reps)
    if bias_ids is not None:
        last = _apply_logit_bias(last, bias_ids, bias_vals)
    if ban_ids is not None:
        last = _mask_banned(last, ban_ids, ban_until, true_lens)
    last = _apply_allow(last, allow)
    keys = per_slot_keys(seeds, true_lens) if seeds is not None else rng
    toks = sample(last, keys, temperature, top_k, top_p)
    out = [cache, toks]
    if logprobs:
        out.append(_logprob_topk(last, toks))
    if prompt_logprobs:
        out.append(_prompt_logprobs(logits, tokens))
    return tuple(out)


@partial(jax.jit, static_argnums=(0,), static_argnames=("logprobs",),
         donate_argnums=(2,))
def prefill_chunk_step(cfg: ModelConfig, params, cache, tokens, start, slot,
                       chunk_len, rng, temperature, top_k, top_p,
                       logprobs: bool = False, pages=None, seed=None,
                       ban_ids=None, ban_until=None,
                       bias_ids=None, bias_vals=None, rep=None,
                       rep_seen=None, allow=None, lora_idx=None):
    """Prefill ONE chunk of a long prompt; decode interleaves between chunks.

    tokens: [1, C] (the chunk, right-padded on the final chunk); start: row
    offset of this chunk in the slot; chunk_len: valid tokens in this chunk.
    Returns (cache, sampled token from the chunk's last valid row) — the host
    uses the token only after the FINAL chunk (it is the request's first
    generated token); for earlier chunks it is discarded. One compiled
    program for all chunks (C static), versus one program per prompt-length
    bucket for whole-prompt prefill.
    """
    C = tokens.shape[1]
    positions = start + jnp.arange(C, dtype=jnp.int32)[None, :]
    with lora_context(lora_idx):
        if pages is not None:
            # carry path — see prefill_step's paged branch
            attend = make_chunk_prefill_attend_paged_carry(
                pages, start, window=cfg.sliding_window)
            logits, cache = model_forward_carry(params, cfg, tokens,
                                                positions, cache, attend)
        else:
            attend = make_chunk_prefill_attend(slot, start,
                                               window=cfg.sliding_window)
            logits, cache = model_forward(params, cfg, tokens, positions,
                                          cache, attend)
    last = jnp.take(logits[0], chunk_len - 1, axis=0)[None]  # [1, V]
    if rep is not None and rep_seen is not None:
        # chunks only carry a slice of the prompt: the seen-set over the
        # WHOLE context comes precomputed from the host ([V] bool)
        r = rep.astype(jnp.float32)
        lf = last.astype(jnp.float32)
        last = jnp.where(rep_seen[None],
                         jnp.where(lf > 0, lf / r, lf * r), lf)
    if bias_ids is not None:
        last = _apply_logit_bias(last, bias_ids[None], bias_vals[None])
    if ban_ids is not None:
        last = _mask_banned(last, ban_ids[None], ban_until[None],
                            (start + chunk_len)[None])
    last = _apply_allow(last, allow)
    # ctr = start + chunk_len = the full context length at the FINAL chunk
    # (the only one whose sample survives) — matching what decode/prefill
    # would use for the same position, so seeded streams are chunking-layout
    # independent.
    keys = per_slot_keys(seed[None], (start + chunk_len)[None]) \
        if seed is not None else rng
    token = sample(last, keys, temperature[None], top_k[None],
                   top_p[None])[0]
    if logprobs:
        return cache, token, _logprob_topk(last, token[None])
    return cache, token


@partial(jax.jit, static_argnums=(0, 1), static_argnames=("mesh", "impl",
                                                          "logprobs",
                                                          "penalties",
                                                          "bblock"),
         donate_argnums=(3, 4, 5), donate_argnames=("counts",))
def decode_steps(cfg: ModelConfig, n_steps: int, params, cache, tokens,
                 lengths, rng, temperature, top_k, top_p, mesh=None,
                 impl: str = "auto", logprobs: bool = False,
                 counts=None, presence=None, frequency=None,
                 repetition=None, prompt_mask=None,
                 penalties: bool = False, table=None, seeds=None,
                 ban_ids=None, ban_until=None, bias_ids=None,
                 bias_vals=None, allow=None, lora_idx=None,
                 bblock: int = 1):
    """``n_steps`` fused decode steps for every slot, one device dispatch.

    tokens/lengths/sampling params: [B]. Returns
    (cache, counts, out [n_steps, B], last_tok [B], lens [B]) — the final
    token/length carry stays device-resident so a pipelined engine can feed
    dispatch N's carry straight into dispatch N+1 (donated, no host
    round-trip; see EnginePrograms._decode_dispatch).

    Fusing the token loop into one ``lax.scan`` is a TPU-first scheduling
    decision: per-dispatch host→device latency (worst over a network-attached
    chip) is paid once per *horizon* instead of once per token, and XLA keeps
    the KV cache resident in HBM across all substeps (donated carry). The
    scheduler only uses a horizon > 1 when no prefill is waiting, so TTFT is
    not taxed. Slots that hit a stop condition mid-horizon generate a few
    surplus tokens which the host discards; surplus K/V writes past
    ``max_len`` are dropped (cache_write_row masks rows outside [0, S); the
    XLA fallback's scatter drops them natively) — never corrupt memory.
    """

    def body(carry, rng_i):
        cache, cnts, tok, lens = carry
        positions = lens[:, None]
        # Carry-path forward: the cache stays in place in the scan carry and
        # attention reads it layer-indexed — no per-layer xs→ys copy (the
        # copy cost dominated decode at ~24 ms/token on v5e; see
        # model_forward_carry's docstring). With a block ``table`` the cache
        # is the paged pool and the kernels address pages through it.
        if table is not None:
            attend = make_decode_attend_carry_paged(
                lens, table, impl=impl, mesh=mesh, window=cfg.sliding_window,
                bblock=bblock)
        else:
            attend = make_decode_attend_carry(lens, impl=impl, mesh=mesh,
                                              window=cfg.sliding_window,
                                              bblock=bblock)
        logits, cache = model_forward_carry(params, cfg, tok[:, None],
                                            positions, cache, attend)
        step_logits = logits[:, 0, :]
        if penalties:
            # presence/frequency/repetition over the [B, V] generated-token
            # counts that ride the carry (updated per sampled token, so a
            # mid-horizon repeat is penalized immediately, not at the next
            # dispatch); repetition additionally covers the prompt mask
            step_logits = apply_penalties(step_logits, cnts, presence,
                                          frequency, repetition, prompt_mask)
        # OpenAI logit_bias: additive on logits before every sampling
        # decision, then min_tokens stop suppression (mask wins: a +100 bias
        # on eos must not resurrect a banned stop token). The ban evaluates
        # PER SUBSTEP (lens rides the carry), so it can expire mid-horizon
        # exactly when vLLM's would.
        step_logits = _apply_logit_bias(step_logits, bias_ids, bias_vals)
        step_logits = _mask_banned(step_logits, ban_ids, ban_until, lens)
        # Guided mask is computed for substep 0's state only: in mixed
        # batches the host emits just that substep for guided slots and
        # discards the rest (penalized guided slots force horizon 1 so the
        # per-substep count updates above never cover discarded tokens —
        # see _do_decode).
        step_logits = _apply_allow(step_logits, allow)
        # ctr = lens + 1 = the context length this draw extends TO: distinct
        # from the prefill draw's ctr (= prompt length) and equal to what a
        # preemption-resume prefill of the same position would use — the
        # seed contract's cross-resume reproducibility hangs on this
        # alignment (review r3).
        keys = per_slot_keys(seeds, lens + 1) if seeds is not None else rng_i
        nxt = sample(step_logits, keys, temperature, top_k, top_p)
        if penalties:
            cnts = cnts.at[jnp.arange(cnts.shape[0]), nxt].add(1)
        if logprobs:
            return (cache, cnts, nxt, lens + 1), (
                nxt, _logprob_topk(step_logits, nxt))
        return (cache, cnts, nxt, lens + 1), nxt

    if counts is None:
        counts = jnp.zeros((tokens.shape[0], 1), jnp.int32)  # unused dummy
    rngs = jax.random.split(rng, n_steps)
    with lora_context(lora_idx):
        (cache, counts, tok, lens), out = jax.lax.scan(
            body, (cache, counts, tokens, lengths), rngs)
    return cache, counts, out, tok, lens


@partial(jax.jit, static_argnums=(0,),
         static_argnames=("mesh", "impl", "logprobs", "chunk_logprobs",
                          "penalties", "bblock"),
         donate_argnums=(2, 3, 4), donate_argnames=("counts",))
def mixed_step(cfg: ModelConfig, params, cache, tokens, lengths, ptokens,
               pslot, pstart, plen, prep, prep_seen, pseed, ptemp, ptop_k,
               ptop_p, rng, temperature, top_k, top_p, mesh=None,
               impl: str = "auto", logprobs: bool = False,
               chunk_logprobs: bool = False, counts=None, presence=None,
               frequency=None, repetition=None, prompt_mask=None,
               penalties: bool = False, table=None, seeds=None,
               ban_ids=None, ban_until=None, bias_ids=None, bias_vals=None,
               allow=None, pallow=None, lora_idx=None, bblock: int = 1):
    """ONE ragged dispatch serving a mixed batch: a decode step for every
    active slot AND one prefill chunk of slot ``pslot`` — the program that
    lets the one-deep pipeline ride across prefill admissions instead of
    draining on every chunk edge (ISSUE 14 / ROADMAP open item 2; the
    variable-length-rows layout follows Ragged Paged Attention, arxiv
    2604.15464).

    Layout: the forward pass runs ONCE over a query-token-packed sequence
    ``[1, B + C]`` — B decode rows (token ``tokens[b]`` at position
    ``lengths[b]``), then the C chunk rows of ``ptokens`` at positions
    ``pstart + j``. MLP/norm/projections are per-token, so packing changes
    nothing; attention goes through make_mixed_attend_carry_paged, whose
    per-row (write row, live-column limit, page-table row) metadata gives
    each packed row exactly the view the separate decode/chunk programs
    gave it — byte-identical streams either way (pinned by
    tests/test_decode_pipeline.py's ragged parity cases).

    ``pslot``'s own decode row is a dead passenger while it chunks: its
    K/V write is DROPPED (write row -1), it attends nothing (limit 0), and
    the returned carry overrides its lanes with the chunk's sample
    (``tok_out[pslot] = chunk token``, ``lens_out[pslot] = pstart + plen``)
    so the device carry matches the host mirrors a final-chunk activation
    produces — the generation-stamped carry extended to cover
    prefill-admitted slots.

    Sampling matches the programs it replaces exactly: decode rows take the
    decode_steps transform order (penalties → bias → ban(lens) → allow →
    seeded key at lens + 1); the chunk's last valid row takes
    prefill_chunk_step's (host rep_seen → bias → ban at pstart + plen →
    allow → seeded key at pstart + plen). Only the FINAL chunk's sample
    survives on the host.

    Feature operands (ISSUE 16 — no feature de-pipelines the batch):
    ``allow`` [B, ceil(V/32)] uint32 masks the decode rows (guided slots'
    FSM bitsets, all-ones elsewhere); ``pallow`` [1, ceil(V/32)] masks the
    chunk row when the CHUNKING request itself is guided. Both are program
    variants (None = compiled out). ``lora_idx`` [B] per-slot adapter
    indices are packed in-program to per-TOKEN indices over the [1, B + C]
    layout (the chunk rows inherit ``lora_idx[pslot]``), selecting each
    row's A/B delta inside one shared program (models/layers._linear's
    per-token branch).

    Returns (cache, counts, out [1, B] (+logprobs), chunk token [1]
    (+chunk logprobs), tok_carry [B], lens_carry [B]).
    """
    B = tokens.shape[0]
    C = ptokens.shape[1]
    is_p = jnp.arange(B, dtype=jnp.int32) == pslot
    crows = pstart + jnp.arange(C, dtype=jnp.int32)
    write_rows = jnp.concatenate(
        [jnp.where(is_p, jnp.int32(-1), lengths), crows])
    row_limits = jnp.concatenate(
        [jnp.where(is_p, jnp.int32(0), lengths + 1), crows + 1])
    row_tables = jnp.concatenate(
        [table, jnp.broadcast_to(table[pslot][None], (C, table.shape[1]))])
    packed = jnp.concatenate([tokens[None], ptokens], axis=1)     # [1, B+C]
    positions = jnp.concatenate(
        [jnp.where(is_p, jnp.int32(0), lengths)[None], crows[None]], axis=1)
    attend = make_mixed_attend_carry_paged(
        write_rows, row_limits, row_tables, impl=impl, mesh=mesh,
        window=cfg.sliding_window, bblock=bblock)
    # Per-TOKEN adapter indices over the packed layout: decode row b keeps
    # its slot's adapter, every chunk row runs the chunking slot's — one
    # program serves any adapter mix (models/layers._linear gathers factors
    # per token when the index rank matches x's row rank).
    packed_lora = None
    if lora_idx is not None:
        packed_lora = jnp.concatenate(
            [lora_idx, jnp.broadcast_to(lora_idx[pslot], (C,))])[None]
    with lora_context(packed_lora):
        logits, cache = model_forward_carry(params, cfg, packed, positions,
                                            cache, attend)
    # -- decode rows: the decode_steps substep body, verbatim order --------
    dec_logits = logits[0, :B]
    if penalties:
        dec_logits = apply_penalties(dec_logits, counts, presence, frequency,
                                     repetition, prompt_mask)
    dec_logits = _apply_logit_bias(dec_logits, bias_ids, bias_vals)
    dec_logits = _mask_banned(dec_logits, ban_ids, ban_until, lengths)
    dec_logits = _apply_allow(dec_logits, allow)
    keys = per_slot_keys(seeds, lengths + 1) if seeds is not None else rng
    nxt = sample(dec_logits, keys, temperature, top_k, top_p)
    if penalties:
        # pslot's lane counts a garbage sample; _activate's count-row
        # reset/restore at the final chunk wipes it (same policy as its
        # stale-occupant rows)
        counts = counts.at[jnp.arange(counts.shape[0]), nxt].add(1)
    # -- chunk row: the prefill_chunk_step tail, verbatim order ------------
    plast = jnp.take(logits[0, B:], plen - 1, axis=0)[None]       # [1, V]
    if prep is not None and prep_seen is not None:
        r = prep.astype(jnp.float32)
        lf = plast.astype(jnp.float32)
        plast = jnp.where(prep_seen[None],
                          jnp.where(lf > 0, lf / r, lf * r), lf)
    plast = _apply_logit_bias(plast, bias_ids[pslot][None],
                              bias_vals[pslot][None])
    plast = _mask_banned(plast, ban_ids[pslot][None], ban_until[pslot][None],
                         (pstart + plen)[None])
    plast = _apply_allow(plast, pallow)
    pkeys = per_slot_keys(pseed[None], (pstart + plen)[None]) \
        if pseed is not None else rng
    ptok = sample(plast, pkeys, ptemp[None], ptop_k[None], ptop_p[None])
    # -- regenerated carry: pslot's lanes become the chunk frontier --------
    tok_out = jnp.where(is_p, ptok[0], nxt)
    lens_out = jnp.where(is_p, pstart + plen, lengths + 1)
    if counts is None:
        counts = jnp.zeros((B, 1), jnp.int32)  # unused dummy (decode_steps)
    out = (nxt[None], tuple(a[None] for a in _logprob_topk(dec_logits, nxt))) \
        if logprobs else nxt[None]
    pout = (ptok, _logprob_topk(plast, ptok)) if chunk_logprobs else ptok
    return cache, counts, out, pout, tok_out, lens_out


@partial(jax.jit, static_argnums=(0, 1), static_argnames=("impl", "mesh",
                                                          "bblock"),
         donate_argnums=(3,))
def spec_decode_step(cfg: ModelConfig, R: int, params, cache, tokens,
                     lengths, rng, temperature, top_k, top_p,
                     impl: str = "auto", table=None, seeds=None, mesh=None,
                     lora_idx=None, bblock: int = 1):
    """Speculative verify: R tokens per slot in ONE dispatch.

    tokens: [B, R] = [last accepted token, spec_k prompt-lookup drafts];
    returns (cache, out [B, R], accepted [B]) where out[b, :accepted[b]] are
    the emitted tokens (accepted draft prefix + one correction/bonus token
    from the target model). Greedy-lossless: a greedy slot's emitted tokens
    are exactly the plain-decode sequence — the verify pass computes the
    target model's argmax at every draft position and accepts only the
    matching prefix. Sampled slots (temperature > 0) accept nothing and
    sample one token from position 0, preserving their distribution.

    K/V rows for all R positions are written in place; rows past the
    accepted prefix are garbage BEYOND the slot's new length and get
    overwritten when those positions are next processed (the engine's
    standard surplus-write invariant — see decode_steps).
    """
    B = tokens.shape[0]
    positions = lengths[:, None] + jnp.arange(R, dtype=jnp.int32)[None, :]
    if table is not None:
        attend = make_spec_attend_carry_paged(lengths, table, impl=impl,
                                              mesh=mesh,
                                              window=cfg.sliding_window,
                                              bblock=bblock)
    else:
        attend = make_spec_attend_carry(lengths, impl=impl, mesh=mesh,
                                        window=cfg.sliding_window)
    with lora_context(lora_idx):
        logits, cache = model_forward_carry(params, cfg, tokens, positions,
                                            cache, attend)
    preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)      # [B, R]
    drafts = tokens[:, 1:]                                     # [B, R-1]
    match = (drafts == preds[:, :-1]).astype(jnp.int32)
    m = jnp.cumprod(match, axis=-1).sum(axis=-1)               # [B]
    greedy = temperature <= 0.0
    m = jnp.where(greedy, m, 0)
    # same ctr convention as decode_steps: this draw extends the context to
    # lengths + 1
    keys = per_slot_keys(seeds, lengths + 1) if seeds is not None else rng
    sampled0 = sample(logits[:, 0], keys, temperature, top_k, top_p)
    correction = jnp.where(greedy, preds[jnp.arange(B), m], sampled0)
    pos = jnp.arange(R - 1, dtype=jnp.int32)[None, :]
    out = jnp.where(pos < m[:, None], drafts, 0)
    out = jnp.concatenate([out, jnp.zeros((B, 1), jnp.int32)], axis=1)
    out = out.at[jnp.arange(B), m].set(correction)
    return cache, out, m + 1


# ---------------------------------------------------------------------------
# EnginePrograms — the per-engine compiled-program surface
# ---------------------------------------------------------------------------


class EnginePrograms:
    """Mixin holding the Engine's compiled-program surface: operand
    construction, program dispatch, bblock autotune, and warmup. ``Engine``
    (serving/engine.py) inherits this; the split keeps the scheduler
    host-side logic and the jit layer in separate files with zero behavior
    change. Methods here reach scheduler state (``self.sched``, slot arrays,
    page allocators) through the subclass."""

    # -- decode batch-block autotune ----------------------------------------

    # injectable for the deterministic-selection tests (fake timer)
    _bblock_timer = staticmethod(time.perf_counter)

    def _fit_bblock(self, req: int) -> int:
        """Largest divisor of the slot count not exceeding the request."""
        bb = max(1, min(int(req), self.num_slots))
        while self.num_slots % bb:
            bb -= 1
        return bb

    def _bblock_autotune_supported(self) -> bool:
        """The microbench dispatches the real paged kernel, so it needs the
        paged TPU path: never under JAX_PLATFORMS=cpu (tier-1 must stay
        fast — interpret-mode timing is meaningless anyway). Single-device
        engines call the kernel directly (_bblock_bench_once); tp/dp meshes
        bench through the same shard_map wrapper the decode program uses
        (_bblock_bench_once_mesh), so the timing includes each chip's head/
        page slice and the dp table rebase — closing the ROADMAP gap where
        meshes pinned bb=1 until tuned explicitly."""
        return self.paged and jax.default_backend() == "tpu"

    def _bblock_bench_once(self, bb: int) -> None:
        """One steady-state decode-attention dispatch at block size ``bb``:
        full-window lengths (every page live — the worst-case stream the
        served config must sustain) over a synthetic table cycling the
        pool's real pages. Blocks until the result is ready so the timer
        wraps device time, not dispatch issue."""
        from aws_k8s_ansible_provisioner_tpu.ops import pallas_attention

        cfg = self.cfg
        ps = self.serving.page_size
        q = jnp.zeros((self.num_slots, 1, cfg.num_heads, cfg.head_dim),
                      jnp.bfloat16 if self.serving.dtype == "bfloat16"
                      else jnp.float32)
        lengths = jnp.full((self.num_slots,), self.pages_per_slot * ps,
                           jnp.int32)
        total = self.cache["k"].shape[1]
        tab = (np.arange(self.num_slots * self.pages_per_slot,
                         dtype=np.int32).reshape(self.num_slots,
                                                 self.pages_per_slot)
               % max(1, total - 1)) + 1          # skip the scratch page
        kw = {}
        if self.kv_quant:
            kw = dict(pool_ks=self.cache["ks"], pool_vs=self.cache["vs"])
        out = pallas_attention.decode_attend_pallas_paged(
            q, self.cache["k"], self.cache["v"], lengths, jnp.int32(0),
            jnp.asarray(tab), bblock=bb, window=self.cfg.sliding_window,
            **kw)
        jax.block_until_ready(out)

    def _bblock_synthetic_table(self) -> np.ndarray:
        """Full-window synthetic block table with GLOBAL page ids: each
        slot's pages cycle inside its dp group's pool partition, skipping
        the group's scratch page (allocators hand out first_page=1), so the
        shard_map body's global→local rebase lands in range on every chip.
        dp=1 reduces to the single-pool case."""
        total = self.cache["k"].shape[1]
        dp = self.mesh.shape.get("dp", 1) if self.mesh is not None else 1
        gp = total // dp                      # pages per dp-group partition
        spg = self.num_slots // dp            # slots per group
        tab = np.empty((self.num_slots, self.pages_per_slot), np.int32)
        for s in range(self.num_slots):
            base = (s // spg) * gp
            tab[s] = base + (np.arange(self.pages_per_slot, dtype=np.int32)
                             % max(1, gp - 1)) + 1
        return tab

    def _bblock_bench_once_mesh(self, bb: int) -> None:
        """One steady-state decode-attention dispatch under the mesh: the
        same shard_map wrapper the decode program uses
        (make_decode_attend_carry_paged), so each chip runs the kernel on
        its head/page slice of the sharded pool and dp tables rebase —
        timing the path the served config actually dispatches. The pool
        rides through untouched (the returned copy is dropped; warmup
        re-dispatches on the real state later)."""
        cfg = self.cfg
        lengths = jnp.full((self.num_slots,),
                           self.pages_per_slot * self.serving.page_size,
                           jnp.int32)
        tab = jnp.asarray(self._bblock_synthetic_table())
        attend = make_decode_attend_carry_paged(
            lengths, tab, impl="pallas", mesh=self.mesh,
            window=cfg.sliding_window, bblock=bb)
        acc = jnp.bfloat16 if self.serving.dtype == "bfloat16" \
            else jnp.float32
        q = jnp.zeros((self.num_slots, 1, cfg.num_heads, cfg.head_dim), acc)
        kv = jnp.zeros((self.num_slots, 1, cfg.num_kv_heads, cfg.head_dim),
                       acc)
        ctx, _ = attend(q, kv, kv, (self.cache, jnp.int32(0)))
        jax.block_until_ready(ctx)

    def _bblock_cache_key(self) -> tuple:
        """Per-config winner key; meshes append their axis shape so a tp=8
        winner never leaks onto a dp=2 engine (or single-device, whose key
        stays the historical 3-tuple)."""
        key = (self.num_slots, self.serving.page_size,
               "int8" if self.kv_quant else "bf16")
        if self.mesh is not None:
            key += (tuple(sorted(self.mesh.shape.items())),)
        return key

    def _resolve_decode_bblock(self) -> int:
        env = os.environ.get("PALLAS_DECODE_BBLOCK", "")
        req = int(env) if env.strip() else int(self.serving.decode_bblock)
        if req > 0:
            return self._fit_bblock(req)     # explicit pin wins, no bench
        key = self._bblock_cache_key()
        if key in _BBLOCK_CACHE:
            return self._fit_bblock(_BBLOCK_CACHE[key])
        if not self._bblock_autotune_supported():
            return 1
        cands = [b for b in BBLOCK_CANDIDATES
                 if b <= self.num_slots and self.num_slots % b == 0]
        bench = self._bblock_bench_once if self.mesh is None \
            else self._bblock_bench_once_mesh
        choice = pick_decode_bblock(cands or [1], bench,
                                    timer=self._bblock_timer)
        _BBLOCK_CACHE[key] = choice
        return choice

    def _init_params_and_cache(self, mesh, lora):
        """Program-operand construction, moved verbatim from
        ``Engine.__init__``: dtype resolution, weight quantization, mesh
        build + parameter sharding, LoRA attach, draft-model wiring, and the
        paged KV pool / dense cache allocation. Runs between the scheduler
        sizing above it and the host slot-state arrays below it."""
        cfg, params, serving = self.cfg, self.params, self.serving
        dtype = jnp.bfloat16 if serving.dtype == "bfloat16" else jnp.float32
        if serving.weights_dtype not in ("auto", "bf16", "int8"):
            # "int8" is the SHIPPED default (PERF.md: the weight stream is the
            # dominant bytes/token term at small batch); "bf16" (alias
            # "auto") is the explicit opt-out that keeps the load dtype.
            raise ValueError(f"weights_dtype={serving.weights_dtype!r}: "
                             f"expected 'int8' (default), 'bf16', or 'auto'")
        if serving.weights_dtype == "int8":
            # Weights-only int8 (models/quant.py): quantized on host/device
            # BEFORE the mesh sharding below, so each chip receives the
            # int8 shard (half the transfer and half the resident bytes).
            from aws_k8s_ansible_provisioner_tpu.models.quant import (
                quantize_params, weights_quantized)

            if weights_quantized(params):
                # Already-quantized tree (e.g. restored from an int8
                # checkpoint): re-quantizing would treat the int8 kernels as
                # values and overwrite the scale leaves — silent corruption,
                # not an error. Skip; sharding handles quantized trees.
                pass
            else:
                # host=True under a mesh: leaf-wise numpy quantization so no
                # single chip ever holds the full unquantized tree (the
                # jitted path would device_put it whole — the 8B-on-v5e-8
                # OOM the sharded loader exists to avoid)
                self.params = params = quantize_params(
                    params, cfg,
                    host=mesh is not None or serving.mesh.num_devices > 1)
        if serving.kv_dtype not in ("auto", "int8"):
            # An unrecognized value (e.g. "fp8", "INT8") must not silently
            # degrade to the unquantized cache — capacity would halve with no
            # error until an OOM much later.
            raise ValueError(f"kv_dtype={serving.kv_dtype!r}: expected "
                             f"'auto' or 'int8'")
        self.kv_quant = serving.kv_dtype == "int8"

        # Multi-chip serving: a (dp, tp) mesh shards params (Megatron TP),
        # slots over dp, and kv heads over tp (parallel/sharding.py). The
        # comms backend is XLA collectives over ICI — GSPMD partitions the
        # matmuls, shard_map runs the Pallas kernel per-shard (SURVEY.md §2.3:
        # every parallelism capability is net-new on the TPU side).
        self.mesh = mesh if mesh is not None else self._build_mesh(serving)
        if self.mesh is not None:
            from aws_k8s_ansible_provisioner_tpu.parallel.sharding import (
                cache_pspecs, check_tp_divisibility, shard_params)

            tp = self.mesh.shape["tp"]
            dp = self.mesh.shape["dp"]
            sp = self.mesh.shape.get("sp", 1)
            check_tp_divisibility(cfg, tp, self.mesh.shape.get("ep", 1))
            if cfg.num_experts > 0 and cfg.moe_impl != "gshard":
                # Distributed MoE must use the GSPMD-partitionable dispatch
                # formulation; ragged_dot's data-dependent groups would make
                # the compiler all-gather every expert (ops/moe.py). This
                # trades the exact no-drop impl for capacity-limited dispatch
                # — say so, loudly, or a quality difference vs single-device
                # serving is undiagnosable.
                import logging

                logging.getLogger(__name__).warning(
                    "MoE under a mesh: switching moe_impl ragged -> gshard "
                    "(capacity_factor=%s; tokens past an expert's capacity "
                    "fall back to the residual stream)",
                    cfg.moe_capacity_factor)
                cfg = self.cfg = cfg.scaled(moe_impl="gshard")
            if self.num_slots % dp:
                raise ValueError(f"max_decode_slots={self.num_slots} must be "
                                 f"divisible by dp={dp}")
            if sp > 1 and cfg.sliding_window > 0:
                raise ValueError(
                    "sequence-parallel serving (sp > 1) does not compose "
                    "with sliding-window attention: the window straddles "
                    "shard boundaries (shard by dp/tp instead, or serve "
                    "the model with full attention)")
            if sp > 1 and self.max_len % (sp * 8):
                raise ValueError(
                    f"cache window {self.max_len} must split into 8-row-"
                    f"aligned sequence shards; not divisible by sp={sp} * 8")
            self.params = params = shard_params(params, self.mesh, cfg)
        # Multi-LoRA (models/lora.py): adapters stack along a leading
        # adapter axis and attach beside their target kernels, AFTER
        # quantization (int8 kernels keep f32-loaded LoRA factors separate)
        # — one compiled program serves every adapter mix via the per-slot
        # index vector the dispatches carry.
        self.lora_names: List[str] = []
        if lora:
            if self.mesh is not None:
                raise ValueError("multi-LoRA under a mesh is not wired yet "
                                 "(adapter-axis pspecs)")
            from aws_k8s_ansible_provisioner_tpu.models import lora as _lora

            items = list(lora.items())
            loaded = [_lora.load_adapter(path) for _, path in items]
            stacked = _lora.stack_adapters(loaded, cfg.num_layers, dtype)
            self.params = params = _lora.attach(params, stacked)
            self.lora_names = [name for name, _ in items]
        # True paged KV: shared page pool + block tables. Composes with tp
        # (and ep) meshes — the pool shards only its KV-HEAD axis, so page
        # identity, tables, and the host allocator are shard-invariant
        # (parallel/sharding.pool_pspecs) — AND with dp meshes (VERDICT r3
        # next #6): the pool's PAGE axis shards over dp, giving each
        # dp group its own pool partition with a per-group host allocator
        # (slots are dp-sharded, so a slot's pages always live in its own
        # group's partition; prefix sharing is group-local). Only sp keeps
        # the dense layout: it shards the SEQUENCE axis, and a page is a
        # contiguous row run — splitting pages across sp shards would
        # reintroduce the cross-shard row addressing paging exists to avoid.
        self.paged = bool(serving.paged) and (
            self.mesh is None or self.mesh.shape.get("sp", 1) == 1)
        # Speculation composes with tp meshes (every tp shard executes the
        # identical token stream, so the data-dependent accept length is
        # shard-invariant — vLLM runs spec decode under TP; VERDICT r3
        # missing #2) AND with dp meshes (VERDICT r4 next #6: dp shards the
        # SLOT axis, and both the verify attend's shard_map specs and the
        # paged table rebase carry the dp dimension — accept lengths are
        # per-slot host state exactly like plain decode's variable lengths,
        # so groups never desync; parity pinned by
        # tests/test_spec_decode.py::test_spec_parity_under_dp_mesh and
        # dryrun_multichip). Only sp keeps plain decode: the sequence-axis
        # partial-softmax merge has no multi-query spec variant.
        self._spec_mesh_ok = (
            self.mesh is None or self.mesh.shape.get("sp", 1) == 1)
        # Alternation flag: after a spec dispatch that skipped ineligible
        # slots (logprobs/penalties/min_tokens — _slot_spec_ineligible), the
        # next dispatch takes the plain fused path so those slots advance
        # every other step instead of starving.
        self._spec_plain_due = False
        # Draft-model proposer (serving/draft.py): replaces prompt-lookup as
        # the proposal source when spec_method="draft". The draft runs
        # UNSHARDED (it is small by design); everything else about the spec
        # path (verify program, per-slot eligibility, mesh gating) is shared.
        self.draft = None
        if serving.spec_method not in ("prompt_lookup", "draft"):
            raise ValueError(f"spec_method={serving.spec_method!r}: expected "
                             f"'prompt_lookup' or 'draft'")
        if serving.spec_method == "draft" and serving.spec_decode:
            if self._draft_src is None:
                raise ValueError("spec_method='draft' requires draft="
                                 "(draft_cfg, draft_params)")
            from aws_k8s_ansible_provisioner_tpu.serving.draft import (
                DraftModel)

            dcfg, dparams = self._draft_src
            if dcfg.vocab_size < cfg.vocab_size:
                raise ValueError(
                    f"draft vocab ({dcfg.vocab_size}) must cover the target "
                    f"vocab ({cfg.vocab_size}) — drafts are target token ids")
            self.draft = DraftModel(dcfg, dparams, self.num_slots,
                                    self.max_len, dtype)
        # Tier-2 host store handle (paged mode only; None = tier off or
        # dense layout). /healthz and the fit ledger read it.
        self.host_tier = None
        if self.paged:
            from aws_k8s_ansible_provisioner_tpu.serving import paged_kv as pkv

            ps = serving.page_size
            # the Pallas row-write kernels touch 8-row (bf16) / 32-row (int8)
            # sub-blocks that must divide the page
            align = 32 if self.kv_quant else 8
            if ps % align:
                raise ValueError(f"page_size={ps} must be a multiple of "
                                 f"{align} for the "
                                 f"{'int8' if self.kv_quant else 'bf16'} "
                                 f"paged kernels")
            self.pages_per_slot = -(-self.max_len // ps)
            # dp groups: slots split evenly over dp (divisibility enforced
            # above); each group owns one partition of the pool's page axis
            # and its own host allocator working in LOCAL page ids. The
            # device-side table holds GLOBAL ids (local + group * partition),
            # so the GSPMD paths address the full pool directly and the
            # shard_map kernels subtract their own partition base.
            self.dp_groups = (self.mesh.shape.get("dp", 1)
                              if self.mesh is not None else 1)
            self._slots_per_group = self.num_slots // self.dp_groups
            pool_pages = serving.kv_pool_pages \
                or self.num_slots * self.pages_per_slot
            if serving.kv_pool_pages and pool_pages % self.dp_groups:
                # an explicit pool size must split exactly — silently
                # dropping the remainder would skew the operator's capacity
                # math by up to dp-1 pages (review r4)
                raise ValueError(
                    f"kv_pool_pages={pool_pages} must be divisible by the "
                    f"dp group count ({self.dp_groups})")
            group_pages = pool_pages // self.dp_groups
            if group_pages < self.pages_per_slot:
                # a lone max-length request must always be able to grow to
                # the window IN ITS OWN GROUP, or preemption would spin on
                # itself
                raise ValueError(
                    f"kv_pool_pages={pool_pages} over {self.dp_groups} dp "
                    f"group(s) gives {group_pages}/group < pages for one "
                    f"full window ({self.pages_per_slot})")
            # +1 per group: local physical page 0 is that group's SCRATCH
            # page — every idle slot's table points at its group's scratch,
            # so the decode programs' per-slot garbage row writes can never
            # land in a page another slot owns.
            self._group_pages = group_pages + 1     # pool partition size
            total_pages = self.dp_groups * self._group_pages
            if self.mesh is not None:
                # born sharded (pages over dp, heads over tp): no device ever
                # holds the full pool — same rationale as the dense mesh
                # cache below
                from jax.sharding import NamedSharding

                from aws_k8s_ansible_provisioner_tpu.parallel.sharding import (
                    pool_pspecs)

                out_sh = {name: NamedSharding(self.mesh, spec)
                          for name, spec in
                          pool_pspecs(self.kv_quant).items()}
                self.cache = jax.jit(
                    lambda: pkv.init_pool(cfg, total_pages, ps, dtype,
                                          quant=self.kv_quant),
                    out_shardings=out_sh)()
            else:
                self.cache = pkv.init_pool(cfg, total_pages, ps, dtype,
                                           quant=self.kv_quant)
            self.allocators = [pkv.PagePool(self._group_pages, ps,
                                            first_page=1)
                               for _ in range(self.dp_groups)]
            # Tier-2 KV (ISSUE 20): ONE host-RAM store shared by every dp
            # group's allocator — chain-hash keys are group-agnostic, so a
            # prefix evicted from one group's partition can restore into any
            # group's fresh pages. Budget 0 leaves the tier off entirely:
            # no spill log, no host walk in lookup_prefix — the
            # byte-identity escape hatch.
            if serving.kv_host_tier_bytes > 0:
                self.host_tier = pkv.HostTier(serving.kv_host_tier_bytes)
                for a in self.allocators:
                    a.host_tier = self.host_tier
            # host metadata for spill/restore accounting (never touches the
            # device): per-page payload bytes across all leaves, and each
            # leaf's expected per-page shape [L, Hkv, page, (D)] — the
            # fetch-time truncation check behind chaos kv_offload_error
            self._page_bytes = sum(
                cfg.num_layers * int(np.prod(arr.shape[2:]))
                * arr.dtype.itemsize for arr in self.cache.values())
            self._page_shapes = {
                name: (cfg.num_layers,) + tuple(arr.shape[2:])
                for name, arr in self.cache.items()}
            # slot -> scheduled-but-unsettled restore record (timing +
            # byte accounting; correctness rides XLA data dependencies)
            self._restore_pending: dict = {}
            # per-slot global id of its group's scratch page (group 0's is 0,
            # preserving the single-device layout)
            self._scratch = np.repeat(
                np.arange(self.dp_groups, dtype=np.int32)
                * self._group_pages, self._slots_per_group)
            self.table = np.broadcast_to(
                self._scratch[:, None],
                (self.num_slots, self.pages_per_slot)).copy()
            self._slot_pages: List[List[int]] = [[] for _ in
                                                 range(self.num_slots)]
            # req id -> prompt+generated context for preemption resume.
            # tpulint: disable=R5 per-key happens-before — submit() installs a key BEFORE sched.submit publishes the id, the step thread touches it only after; dict ops are GIL-atomic
            self._resume_ctx: dict = {}
            # admission recency per slot: preemption victims are newest-first
            self._admit_seq = np.zeros(self.num_slots, np.int64)
            self._seq_counter = 0
        elif self.mesh is not None:
            # Allocate the cache DIRECTLY sharded (jit with out_shardings):
            # each device materializes only its own shard. Building unsharded
            # and re-sharding with device_put would peak one device's HBM at
            # the FULL cache size — defeating the capacity scaling the dp/tp
            # mesh exists to provide (ADVICE r1, medium).
            from jax.sharding import NamedSharding

            from aws_k8s_ansible_provisioner_tpu.parallel.sharding import (
                cache_pspecs)

            out_sh = {name: NamedSharding(self.mesh, spec)
                      for name, spec in cache_pspecs(self.kv_quant).items()}
            self.cache = jax.jit(
                lambda: kvc.init_cache(cfg, self.num_slots, self.max_len,
                                       dtype, quant=self.kv_quant),
                out_shardings=out_sh)()
        else:
            self.cache = kvc.init_cache(cfg, self.num_slots, self.max_len,
                                        dtype, quant=self.kv_quant)

    # -- scheduling ---------------------------------------------------------

    def _want_logprobs(self, reqs) -> bool:
        return any(r is not None and r.logprobs is not None for r in reqs)

    def _ban_set(self, req: Request) -> set:
        """Tokens suppressed for this request while min_tokens is unmet —
        exactly the set _emit would stop on."""
        base = set() if req.ignore_eos else set(self._eos_set)
        return base | set(req.stop_token_ids)

    def _fill_sampling_rows(self, req: Request, slot: int):
        """Populate the slot's min_tokens ban and logit_bias rows from the
        request. Called BEFORE the prefill dispatch (so the FIRST sampled
        token already honors both — filling only at _activate would let it
        escape suppression/bias) and again at _activate (idempotent; covers
        the preemption-resume path)."""
        self._op_dirty_sampling = True
        self.ban_ids[slot, :] = 2**31 - 1
        if req.min_tokens > 0:
            bs = sorted(self._ban_set(req))[:BAN_K]
            self.ban_ids[slot, :len(bs)] = bs
            self.ban_until[slot] = len(req.prompt_ids) + req.min_tokens
        else:
            self.ban_until[slot] = 0
        self.lora_idx[slot] = (self.lora_names.index(req.lora) + 1
                               if req.lora is not None else 0)
        self.bias_ids[slot, :] = 2**31 - 1
        self.bias_vals[slot, :] = 0.0
        n = len(req.logit_bias)
        self._bias_n[slot] = n
        if n:
            self.bias_ids[slot, :n] = [t for t, _ in req.logit_bias]
            self.bias_vals[slot, :n] = [v for _, v in req.logit_bias]

    @staticmethod
    def _fill_allow(aw: np.ndarray, i: int, req: Request) -> None:
        """Overwrite row ``i`` of an allow-words array with the request's
        grammar mask. Grammar words for a smaller tokenizer vocab pad with
        zero bits — out-of-tokenizer model rows are never sampleable under
        guidance."""
        words = req.guided.mask_words()
        aw[i, :] = 0
        aw[i, :len(words)] = words

    def _lora_vec(self):
        return jnp.asarray(self.lora_idx) if self.lora_names else None

    def _lora_salt(self, idx: int):
        """Prefix-cache identity component for a slot's adapter: KV rows
        computed under adapter A must never prefix-hit a request running
        adapter B or the base model — wq/wk/wv project differently per
        adapter (review r5; vLLM folds lora_int_id into its block hash for
        the same reason). None for the base keeps pre-LoRA hash chains
        byte-compatible."""
        return ("lora", int(idx)) if idx else None

    def _allow_row(self, req: Request):
        """[1, ceil(V/32)] guided allow-bitmask device array for one request,
        or None (no-variant) when the request is unguided.

        One-entry device cache keyed on the request's FSM fingerprint
        (serving/guided.py): a guided CHUNKING request's state never
        advances mid-walk, so every mixed dispatch of the walk reuses the
        same device-resident mask — zero rebuild, zero re-upload (the
        mask-upload-overlap term in PERF.md's mixed-feature cost model).
        The upload itself is ``jnp.asarray`` — async enqueue, no blocking
        read (this helper is on the tpulint R8 dispatch path)."""
        if req.guided is None:
            return None
        key = (req.id, req.guided.fingerprint())
        cached = self._allow_dev
        if cached is not None and cached[0] == key:
            return cached[1]
        row = np.zeros((1, (self.cfg.vocab_size + 31) // 32), np.uint32)
        self._fill_allow(row, 0, req)
        arr = jnp.asarray(row)
        self._allow_dev = (key, arr)
        return arr

    def _allow_words(self, gslots: List[int]):
        """[B, ceil(V/32)] allow-bitmask covering all slots (unguided rows
        all-ones), or None when no guided slot is active.

        Same one-entry device cache as _allow_row, keyed on every guided
        slot's (slot, FSM fingerprint): consecutive dispatches whose
        grammar states did not advance (e.g. decode steps interleaved
        around a neighbor's chunk walk) skip both the numpy rebuild and
        the re-upload."""
        if not gslots:
            return None
        key = tuple((s, self.slot_req[s].guided.fingerprint())
                    for s in gslots)
        cached = self._allow_batch_dev
        if cached is not None and cached[0] == key:
            return cached[1]
        aw = np.full((self.num_slots, (self.cfg.vocab_size + 31) // 32),
                     0xFFFFFFFF, np.uint32)
        for s in gslots:
            self._fill_allow(aw, s, self.slot_req[s])
        arr = jnp.asarray(aw)
        self._allow_batch_dev = (key, arr)
        return arr

    def _free_slots(self) -> List[int]:
        return [i for i, r in enumerate(self.slot_req) if r is None]

    def _active_slots(self) -> List[int]:
        return [i for i, r in enumerate(self.slot_req) if r is not None]

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _activate(self, req: Request, slot: int, token: int, lp=None,
                  ids: Optional[List[int]] = None, resumed: bool = False):
        """Shared post-prefill bookkeeping: slot state + TTFT + first token.

        ``ids`` overrides the cache-resident token sequence when it differs
        from the request prompt — a preemption resume re-prefills
        prompt + generated-so-far, so lengths and page indexing must track
        THAT sequence. A resume (``resumed``) is a pure CACHE REBUILD: the
        prefill-sampled token is DISCARDED (prefill applies no penalties and
        its draw position belongs to the already-emitted stream); the next
        decode dispatch produces the continuation with penalties and the
        seeded key it would have used without the preemption — bit-identical
        streams either way."""
        ids = list(req.prompt_ids) if ids is None else ids
        # an in-flight decode dispatch's device carry (token/length) no
        # longer describes the batch once this slot joins it; its sampling
        # operand rows change too
        self._carry_gen += 1
        self._op_dirty_sampling = True
        now = time.monotonic()
        if not req.t_first_token:     # don't re-observe on preemption resume
            req.t_first_token = now
            self.metrics.ttft.observe(now - req.t_submit,
                                      trace_id=req.trace_id or None)
            _slo.get().observe_ttft(now - req.t_submit)
        _flight.record("admit", req.id, slot=slot, resumed=resumed,
                       queue_wait_s=round(max(0.0, (req.t_prefill_start
                                                    or now) - req.t_submit),
                                          6))
        if not resumed:
            # a resume's context tokens were all counted at first admission
            self.metrics.prompt_tokens.inc(len(ids))
        if self.paged:
            self._index_prompt_pages(slot, ids)
        else:
            self._slot_tokens[slot] = tuple(req.prompt_ids)
            self._slot_lora[slot] = self.lora_idx[slot]
        self.slot_req[slot] = req
        # Resume: decode's next dispatch RE-writes last_token's K/V at row
        # ``lengths`` before attending, so point it at the last real token's
        # own row (its recomputed K/V is identical) — lengths = len(ids)
        # would duplicate that row at len(ids) and shift every later write,
        # and the seeded draw counter (lens + 1) aligns with the
        # unpreempted stream exactly at len(ids) - 1.
        self.lengths[slot] = len(ids) - 1 if resumed else len(ids)
        self.temps[slot] = req.temperature
        self.top_ks[slot] = req.top_k
        self.top_ps[slot] = req.top_p
        self.seeds[slot] = req.eff_seed
        self._fill_sampling_rows(req, slot)
        self.pres_pens[slot] = req.presence_penalty
        self.freq_pens[slot] = req.frequency_penalty
        self.rep_pens[slot] = req.repetition_penalty or 1.0
        if req.repetition_penalty and req.repetition_penalty != 1.0:
            if self.prompt_mask is None:
                self.prompt_mask = jnp.zeros(
                    (self.num_slots, self.cfg.vocab_size), jnp.bool_)
            row = np.zeros(self.cfg.vocab_size, bool)
            row[np.asarray(req.prompt_ids, np.int64)] = True
            self.prompt_mask = _set_mask_row(self.prompt_mask,
                                             jnp.int32(slot),
                                             jnp.asarray(row))
        if (req.presence_penalty or req.frequency_penalty
                or (req.repetition_penalty
                    and req.repetition_penalty != 1.0)):
            # Only penalized occupants touch the counts array: a stale row
            # under a zero-penalty occupant is multiplied by zero, so
            # un-penalized prefills never pay this extra device dispatch.
            if self.counts is None:
                self.counts = jnp.zeros(
                    (self.num_slots, self.cfg.vocab_size), jnp.int32)
            if self.prompt_mask is None:
                # allocated WITH counts (not only for repetition requests):
                # the penalized decode program's signature always carries
                # the mask, so pres/freq-only traffic reuses the program
                # warmup compiled instead of compiling a mask-less variant
                self.prompt_mask = jnp.zeros(
                    (self.num_slots, self.cfg.vocab_size), jnp.bool_)
            if resumed:
                # restore the full pre-preemption penalty state (the
                # discarded prefill token contributes nothing)
                row = np.bincount(np.asarray(req.generated, np.int64),
                                  minlength=self.cfg.vocab_size)
                self.counts = _restore_count_row(
                    self.counts, jnp.int32(slot), jnp.asarray(row, jnp.int32))
            else:
                # zero the recycled slot's row, then count the first token
                self.counts = _reset_count_row(self.counts, jnp.int32(slot),
                                               jnp.int32(token))
        self.sched.note_prefill(slot, int(self.lengths[slot]))
        self.metrics.active_requests.set(len(self._active_slots()))
        if resumed:
            # rebuild complete; decode continues from the last REAL token
            self.last_token[slot] = ids[-1]
            if self.draft is not None:
                # resumes always arrive via the chunk walk (paged admit
                # forces it), which never rebuilds the draft cache; this is
                # the same stale mark _start_chunk applied, kept for the
                # invariant "resumed slot => stale" independent of path
                self.draft.mark_stale(slot)
        else:
            self._emit(slot, token, lp)

    @staticmethod
    def _host_prompt_lp(req: Request, plp, row: int, n_prompt: int) -> None:
        """Format one row of a device (sel, vals, ids) prompt-logprob
        triple into req.prompt_logprob_data ([None, (own, [(id, lp) x k]),
        ...]) — ONE bulk transfer, pure numpy slicing after."""
        sel, vals, ids = (np.asarray(a) for a in plp)
        k = int(req.prompt_logprobs)
        data: List = [None]
        for t in range(1, n_prompt):
            pairs = [(int(ids[row, t - 1, j]), float(vals[row, t - 1, j]))
                     for j in range(k)]
            data.append((float(sel[row, t - 1]), pairs))
        req.prompt_logprob_data = data

    def _do_prefill(self, req: Request, slot: int):
        if not self.paged:
            self._slot_tokens[slot] = ()   # rows about to be overwritten
        ids = req.prompt_ids
        bucket = self._bucket_for(len(ids))
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :len(ids)] = ids
        self._fill_sampling_rows(req, slot)
        t0 = time.monotonic()
        out = prefill_step(
            self.cfg, self.params, self.cache,
            jnp.asarray(tokens), jnp.int32(len(ids)), jnp.int32(slot),
            self._next_rng(), jnp.float32(req.temperature),
            jnp.int32(req.top_k), jnp.float32(req.top_p),
            logprobs=req.logprobs is not None,
            pages=jnp.asarray(self.table[slot]) if self.paged else None,
            seed=jnp.uint32(req.eff_seed),
            ban_ids=jnp.asarray(self.ban_ids[slot]),
            ban_until=jnp.int32(self.ban_until[slot]),
            bias_ids=jnp.asarray(self.bias_ids[slot]),
            bias_vals=jnp.asarray(self.bias_vals[slot]),
            rep=jnp.float32(req.repetition_penalty or 1.0),
            allow=self._allow_row(req),
            lora_idx=(jnp.asarray(self.lora_idx[slot:slot + 1])
                      if self.lora_names else None),
            prompt_logprobs=req.prompt_logprobs is not None)
        items = list(out)
        self.cache, token = items[0], items[1]
        pos = 2
        lp = None
        if req.logprobs is not None:
            lp = _host_lp(items[pos], 0, req.logprobs)
            pos += 1
        if req.prompt_logprobs is not None:
            self._host_prompt_lp(req, items[pos], 0, len(ids))
        token = int(token)  # device sync
        dt = time.monotonic() - t0
        self.metrics.device_busy_seconds.inc(dt)
        _devmon.note("prefill", dt, batch=1, tokens=len(ids))
        if self.draft is not None:
            self.draft.prefill(self, tokens, np.asarray([len(ids)], np.int32),
                               np.asarray([slot], np.int32))
        self._activate(req, slot, token, lp)

    def _do_prefill_batch(self, batch: List):
        """Prefill N waiting prompts in one dispatch (rows padded to a power
        of two, lengths to the largest member's bucket)."""
        n_bucket = 1
        while n_bucket < len(batch):
            n_bucket *= 2
        t_bucket = self._bucket_for(max(len(r.prompt_ids) for r, _ in batch))
        tokens = np.zeros((n_bucket, t_bucket), np.int32)
        true_lens = np.ones(n_bucket, np.int32)
        # padding rows scatter to slot index == num_slots: dropped (OOB)
        slots = np.full(n_bucket, self.num_slots, np.int32)
        temps = np.zeros(n_bucket, np.float32)
        top_ks = np.zeros(n_bucket, np.int32)
        top_ps = np.ones(n_bucket, np.float32)
        seeds = np.zeros(n_bucket, np.uint32)
        for i, (req, slot) in enumerate(batch):
            if not self.paged:
                self._slot_tokens[slot] = ()   # rows about to be overwritten
            ids = req.prompt_ids
            tokens[i, :len(ids)] = ids
            true_lens[i] = len(ids)
            slots[i] = slot
            temps[i] = req.temperature
            top_ks[i] = req.top_k
            top_ps[i] = req.top_p
            seeds[i] = req.eff_seed
        tables = None
        if self.paged:
            from aws_k8s_ansible_provisioner_tpu.serving.paged_kv import (
                OOB_PAGE)

            tb = np.full((n_bucket, self.pages_per_slot), OOB_PAGE, np.int32)
            for i, (_, slot) in enumerate(batch):
                tb[i] = self.table[slot]
            tables = jnp.asarray(tb)
        ban_ids = np.full((n_bucket, BAN_K), 2**31 - 1, np.int32)
        ban_until = np.zeros(n_bucket, np.int32)
        bias_ids = np.full((n_bucket, BIAS_K), 2**31 - 1, np.int32)
        bias_vals = np.zeros((n_bucket, BIAS_K), np.float32)
        reps = np.ones(n_bucket, np.float32)
        row_lora = np.zeros(n_bucket, np.int32)
        for i, (req, slot) in enumerate(batch):
            self._fill_sampling_rows(req, slot)
            ban_ids[i] = self.ban_ids[slot]
            ban_until[i] = self.ban_until[slot]
            bias_ids[i] = self.bias_ids[slot]
            bias_vals[i] = self.bias_vals[slot]
            reps[i] = req.repetition_penalty or 1.0
            row_lora[i] = self.lora_idx[slot]
        allow = None
        if any(req.guided is not None for req, _ in batch):
            aw = np.full((n_bucket, (self.cfg.vocab_size + 31) // 32),
                         0xFFFFFFFF, np.uint32)
            for i, (req, _) in enumerate(batch):
                if req.guided is not None:
                    self._fill_allow(aw, i, req)
            allow = jnp.asarray(aw)
        t0 = time.monotonic()
        want_lp = self._want_logprobs([r for r, _ in batch])
        want_plp = any(r.prompt_logprobs is not None for r, _ in batch)
        out = prefill_batch_step(
            self.cfg, self.params, self.cache, jnp.asarray(tokens),
            jnp.asarray(true_lens), jnp.asarray(slots), self._next_rng(),
            jnp.asarray(temps), jnp.asarray(top_ks), jnp.asarray(top_ps),
            logprobs=want_lp, tables=tables, seeds=jnp.asarray(seeds),
            ban_ids=jnp.asarray(ban_ids), ban_until=jnp.asarray(ban_until),
            bias_ids=jnp.asarray(bias_ids), bias_vals=jnp.asarray(bias_vals),
            reps=jnp.asarray(reps), allow=allow,
            lora_idx=(jnp.asarray(row_lora) if self.lora_names else None),
            prompt_logprobs=want_plp)
        items = list(out)
        self.cache, toks = items[0], items[1]
        pos = 2
        lp_t = None
        if want_lp:
            lp_t = tuple(np.asarray(a) for a in items[pos])  # ONE transfer
            pos += 1
        plp_t = tuple(np.asarray(a) for a in items[pos]) \
            if want_plp else None                        # ONE bulk transfer
        toks = np.asarray(toks)  # device sync
        dt = time.monotonic() - t0
        self.metrics.device_busy_seconds.inc(dt)
        _devmon.note("prefill_batch", dt, batch=len(batch),
                     tokens=int(true_lens.sum()))
        if self.draft is not None:
            self.draft.prefill(self, tokens, true_lens, slots)
        for i, (req, slot) in enumerate(batch):
            lp = _host_lp(lp_t, i, req.logprobs) \
                if req.logprobs is not None else None
            if req.prompt_logprobs is not None:
                self._host_prompt_lp(req, plp_t, i, len(req.prompt_ids))
            self._activate(req, slot, int(toks[i]), lp)

    def _start_chunk(self, req: Request, slot: int, pref):
        """Begin chunked prefill of ``req`` into ``slot``.

        Dense mode: with a prefix-cache hit (``pref = (src_slot, n)``), first
        copy the n resident rows from the source slot and start the chunk
        walk at the suffix. Paged mode (``pref = ("paged", ids, off)``): the
        reused pages are already in the slot's table (hash-chain sharing, no
        copy); the walk starts at the reuse offset, over ``ids`` — which is
        prompt + generated for a preemption resume.
        """
        self._fill_sampling_rows(req, slot)   # before the first chunk dispatch
        # Route the WHOLE walk once, here: the ragged mixed program pays for
        # itself only when there are live decode rows to pack alongside (or
        # an in-flight dispatch to keep open) — an idle engine's chunk walk
        # uses the plain chunk program it already compiled, paying neither a
        # mixed_step compile nor packed-row arithmetic for zero decode rows.
        # Frozen at walk start: no admission/activation can happen mid-walk
        # (engine.step services _chunk before admissions), so the conditions
        # cannot flip under the walk — except draining, which both branches
        # tolerate.
        mixed = (self._ragged_on()
                 and (req.guided is None
                      or self.serving.ragged_features > 0)
                 and (self._inflight is not None
                      or bool(self._active_slots())))
        if not mixed:
            # chunking rewrites the slot's length out of band of any decode
            # carry (admission already drained the pipeline; belt-and-braces)
            self._carry_gen += 1
        # else: ragged mixed walk — the in-flight carry STAYS valid. The
        # chunking slot was inactive, so the in-flight dispatch's garbage
        # row for it lands in scratch (its old device-side table row), and
        # every mixed dispatch overrides the slot's carry lanes in-program
        # (mixed_step's is_p masking) — nothing the carry describes changed.
        if self.draft is not None:
            # the draft has no chunk walk; the slot serves the plain path
            self.draft.mark_stale(slot)
        # repetition_penalty seen-set over the WHOLE context the chunk walk
        # will have written (chunk dispatches only see their slice) — only
        # the final chunk's sample survives, and it must be penalized over
        # all of it (review r4: the first token escaped the penalty)
        rep_seen = np.zeros(self.cfg.vocab_size, bool)
        ids_all = (pref[1] if self.paged and pref is not None
                   else list(req.prompt_ids))
        rep_seen[np.asarray(ids_all, np.int64)] = True
        if self.paged:
            _, ids, off, resumed = pref if pref is not None \
                else ("paged", list(req.prompt_ids), 0, False)
            # settle any scheduled host-tier restore before the first suffix
            # chunk dispatch (the paged analogue of the dense prefix-copy
            # sync below) — timing/byte accounting only; XLA data
            # dependencies already order the restore scatter ahead of every
            # program reading these pages
            self._settle_restore(slot)
            self.lengths[slot] = off
            self._chunk = {"req": req, "slot": slot, "off": off,
                           "C": self._chunk_size, "ids": ids,
                           "resumed": resumed, "rep_seen": rep_seen,
                           "mixed": mixed}
            return
        self._slot_tokens[slot] = ()   # rows about to be overwritten
        off = 0
        if pref is not None:
            src, n = pref
            if src != slot:   # reusing the same slot: rows already in place
                t0 = time.monotonic()
                self.cache = kvc.copy_prefix(self.cache, src, slot, n)
                # sync before reading the clock: the copy is async, and an
                # unsynced window would record ~0 busy time for the device
                # work this feature adds
                jax.block_until_ready(self.cache["k"])
                dt = time.monotonic() - t0
                self.metrics.device_busy_seconds.inc(dt)
                _devmon.note("prefix_copy", dt, tokens=n)
            off = n
            self.metrics.prefix_cache_hits.inc()
            self.metrics.prefix_tokens_reused.inc(n)
        self.lengths[slot] = off
        self._chunk = {"req": req, "slot": slot, "off": off,
                       "C": self._chunk_size, "rep_seen": rep_seen,
                       "mixed": False}   # dense mode: _ragged_on is paged-only

    def _advance_chunk(self):
        """Dispatch the next chunk of the in-progress chunked prefill."""
        st = self._chunk
        req, slot = st["req"], st["slot"]
        if req.cancelled:
            # settle any in-flight mixed dispatch BEFORE releasing this
            # slot's pages: its deferred emits still reference the batch
            self._drain_decode_pipeline("chunk")
            self._chunk = None
            self._release_slot_pages(slot)
            self.sched.release(slot)
            req.finish_reason = "cancelled"
            self.metrics.mark_request("cancelled",
                                      time.monotonic() - req.t_submit)
            _flight.record("cancel_reap", req.id, phase="prefill_chunk")
            _flight.finish(req.id, "cancelled", ok=False)
            req.out_queue.put(None)
            return
        if st.get("mixed"):
            self._advance_chunk_mixed(st)
            return
        if self._inflight is not None:
            # legacy walk with a dispatch in flight (ragged off, or the
            # walk was routed legacy at start): settle it before the sync
            # chunk dispatch rewrites slot state out from under its carry
            self._drain_decode_pipeline("chunk")
        C = st["C"]
        ids = st.get("ids") or req.prompt_ids
        off = st["off"]
        chunk = ids[off:off + C]
        _flight.record("prefill_chunk", req.id, off=off, n=len(chunk))
        tokens = np.zeros((1, C), np.int32)
        tokens[0, :len(chunk)] = chunk
        t0 = time.monotonic()
        lp_t = None
        try:
            out = prefill_chunk_step(
                self.cfg, self.params, self.cache, jnp.asarray(tokens),
                jnp.int32(off), jnp.int32(slot), jnp.int32(len(chunk)),
                self._next_rng(), jnp.float32(req.temperature),
                jnp.int32(req.top_k), jnp.float32(req.top_p),
                logprobs=(req.logprobs is not None
                          and not st.get("resumed")
                          and off + len(chunk) >= len(ids)),
                pages=jnp.asarray(self.table[slot]) if self.paged else None,
                seed=jnp.uint32(req.eff_seed),
                ban_ids=jnp.asarray(self.ban_ids[slot]),
                ban_until=jnp.int32(self.ban_until[slot]),
                bias_ids=jnp.asarray(self.bias_ids[slot]),
                bias_vals=jnp.asarray(self.bias_vals[slot]),
                rep=jnp.float32(req.repetition_penalty or 1.0),
                rep_seen=jnp.asarray(st["rep_seen"]),
                allow=self._allow_row(req),
                lora_idx=(jnp.asarray(self.lora_idx[slot:slot + 1])
                          if self.lora_names else None))
            if req.logprobs is not None and not st.get("resumed") \
                    and off + len(chunk) >= len(ids):
                self.cache, token, lp_t = out
            else:
                self.cache, token = out
        except Exception:
            self._chunk = None
            self._release_slot_pages(slot)
            self.sched.release(slot)
            req.finish_reason = "error"
            self.metrics.mark_request("error", 0.0)
            req.out_queue.put(None)
            raise
        dt = time.monotonic() - t0
        self.metrics.device_busy_seconds.inc(dt)
        _devmon.note("prefill_chunk", dt, tokens=len(chunk))
        st["off"] = off + len(chunk)
        # Interleaved decode dispatches write a (garbage) k/v row for every
        # slot at its host length; keeping this slot's length at the chunk
        # frontier means that row is exactly where the NEXT chunk writes.
        self.lengths[slot] = st["off"]
        if st["off"] >= len(ids):
            self._chunk = None
            lp = _host_lp(lp_t, 0, req.logprobs) \
                if req.logprobs is not None and lp_t is not None else None
            self._activate(req, slot, int(token), lp, ids=list(ids),
                           resumed=st.get("resumed", False))

    def _advance_chunk_mixed(self, st: dict) -> None:
        """One RAGGED mixed dispatch: this walk's next prefill chunk packed
        alongside the whole decode batch, served by a single program
        (``mixed_step``). The dispatch rides the one-deep pipeline exactly
        like a plain decode — the in-flight record it leaves behind IS a
        decode record (plus the chunk outputs), so the pipeline never
        drains on a chunk edge. The legacy path pays one drain per
        admission plus a serialized chunk dispatch per chunk; here both
        costs go to zero.

        The final chunk is the one exception: activation needs the sampled
        first token immediately, so that dispatch settles synchronously.
        Nothing is discarded early — both it and any in-flight predecessor
        are fully emitted — so the drain counter does NOT move.
        """
        req, slot = st["req"], st["slot"]
        C = st["C"]
        ids = st.get("ids") or req.prompt_ids
        off = st["off"]
        chunk = ids[off:off + C]
        final = off + len(chunk) >= len(ids)
        _flight.record("prefill_chunk", req.id, off=off, n=len(chunk),
                       mixed=True)
        prev = self._inflight
        if prev is not None and not self._carry_valid():
            # a slot activated/preempted under the in-flight dispatch —
            # same invalidation rule as _do_decode
            self._drain_decode_pipeline("prefill")
            prev = None
        # Page headroom for the decode rows' writes (the chunk slot's pages
        # were fully allocated at admission, and it is NOT in the active
        # set, so _ensure_pages never preempts it). The bool return (any
        # active slots left) is deliberately ignored: the chunk must
        # proceed even with zero active decode rows.
        grow = 1 + (prev["horizon"] if prev is not None else 0)
        self._ensure_pages(grow)
        if prev is not None and not self._carry_valid():
            # _ensure_pages preempted under the in-flight dispatch
            self._drain_decode_pipeline("prefill")
            prev = None
        if (prev is not None
                and any(r is not None and r.guided is not None
                        for r in self.slot_req)):
            # A guided DECODE row rides this mixed dispatch and its allow
            # mask must reflect the post-emit FSM state: settle the
            # predecessor first (same rule as _do_decode's guided path —
            # carry retained, no drain counted). The steady-state
            # dispatch-then-fetch overlap below is kept for unguided
            # traffic, where no mask depends on the predecessor's emits.
            # The CHUNKING request's own pallow needs no settle: its FSM
            # never advances mid-walk (only the final chunk's token is
            # emitted, at activation).
            self._settle_inflight()
            prev = None
        if self._carry_valid():
            # valid after a settle too (prev is None, carry retained)
            tok_in, len_in = self._pipe_carry[0], self._pipe_carry[1]
        else:
            tok_in = self._donatable(self.last_token)
            len_in = self._donatable(self.lengths)
        try:
            rec = self._mixed_dispatch(st, chunk, tok_in, len_in)
            st["off"] = off + len(chunk)
            self.lengths[slot] = st["off"]
            if not final:
                # steady state: leave the mixed dispatch in flight, settle
                # its predecessor while the device runs this one
                self._inflight = rec
                self.metrics.pipeline_depth.set(1.0)
                if prev is not None:
                    self._decode_fetch(prev, tail=False)
                return
            # final chunk: settle in order — predecessor first, then this
            # dispatch (whose chunk token activates the slot below)
            self._pipe_carry = None
            if prev is not None:
                self._inflight = None
                self.metrics.pipeline_depth.set(0.0)
                self._decode_fetch(prev, tail=False)
            self._decode_fetch(rec, tail=True)
        except Exception:
            # exactly-once release: clearing _chunk BEFORE the raise means
            # the engine's failover (_fail_all) sees no chunk in progress
            # and cannot release this slot a second time
            self._chunk = None
            self._release_slot_pages(slot)
            self.sched.release(slot)
            req.finish_reason = "error"
            self.metrics.mark_request("error", 0.0)
            req.out_queue.put(None)
            raise
        lp = _host_lp(rec["chunk_lp_t"], 0, req.logprobs) \
            if rec["chunk_lp"] else None
        self._chunk = None
        self._activate(req, slot, rec["chunk_token"], lp, ids=list(ids),
                       resumed=st.get("resumed", False))

    def _mixed_dispatch(self, st: dict, chunk, tok_in, len_in) -> dict:
        """Enqueue ONE ragged mixed dispatch (prefill chunk + decode batch)
        and return its in-flight record. Async half only — no blocking
        device reads here (tpulint R8); the transfer and emits happen in
        _decode_fetch, which also unpacks the chunk-row outputs."""
        req, slot, off = st["req"], st["slot"], st["off"]
        ids = st.get("ids") or req.prompt_ids
        active = [s for s in self._active_slots() if s != slot]
        # Feature operands (ISSUE 16): guided decode rows carry their FSM
        # allow-bitmask, a guided CHUNKING request carries its own over the
        # chunk row (constant across the walk — the one-entry device cache
        # in _allow_row makes re-dispatching it free). Both are async
        # uploads on the enqueue half (tpulint R8 covers this fn).
        gslots = [s for s in active
                  if self.slot_req[s] is not None
                  and self.slot_req[s].guided is not None]
        allow = self._allow_words(gslots)
        pallow = self._allow_row(req)
        oc = self._decode_operands()
        want_lp = self._want_logprobs(self.slot_req)
        want_pen = self.counts is not None and bool(
            self.pres_pens.any() or self.freq_pens.any()
            or (self.rep_pens != 1.0).any())
        chunk_lp = (req.logprobs is not None and not st.get("resumed")
                    and off + len(chunk) >= len(ids))
        tokens = np.zeros((1, st["C"]), np.int32)
        tokens[0, :len(chunk)] = chunk
        t0 = time.monotonic()
        if self._last_ready > 0.0:
            self.metrics.decode_bubble_seconds.inc(
                max(0.0, t0 - self._last_ready))
            self._last_ready = 0.0
        real_counts = self.counts
        self.cache, new_counts, out, pout, tok, lens = mixed_step(
            self.cfg, self.params, self.cache, tok_in, len_in,
            jnp.asarray(tokens), jnp.int32(slot), jnp.int32(off),
            jnp.int32(len(chunk)),
            jnp.float32(req.repetition_penalty or 1.0),
            jnp.asarray(st["rep_seen"]), jnp.uint32(req.eff_seed),
            jnp.float32(req.temperature), jnp.int32(req.top_k),
            jnp.float32(req.top_p), self._next_rng(),
            oc["temps"], oc["top_ks"], oc["top_ps"],
            mesh=self.mesh, impl=self.serving.attention_impl,
            logprobs=want_lp, chunk_logprobs=chunk_lp,
            counts=self.counts if want_pen else None,
            presence=oc["pres"] if want_pen else None,
            frequency=oc["freq"] if want_pen else None,
            repetition=oc["rep"] if want_pen else None,
            prompt_mask=self.prompt_mask if want_pen else None,
            penalties=want_pen,
            table=oc["table"],
            seeds=oc["seeds"],
            ban_ids=oc["ban_ids"],
            ban_until=oc["ban_until"],
            bias_ids=oc["bias_ids"],
            bias_vals=oc["bias_vals"],
            allow=allow,
            pallow=pallow,
            lora_idx=oc["lora"],
            bblock=self.decode_bblock)
        self.counts = new_counts if want_pen else real_counts
        self._pipe_carry = (tok, lens, self._carry_gen)
        _metrics.pipeline.dispatches.inc()
        _flight.record("pipeline_dispatch", None, horizon=1,
                       batch=len(active), mixed=True)
        return {"mixed": True, "out": out, "pout": pout, "horizon": 1,
                "active": active, "gset": frozenset(gslots),
                "gslots": gslots,
                "want_lp": want_lp, "chunk_lp": chunk_lp,
                "want_pen": want_pen, "chunk_n": len(chunk), "t0": t0}

    def _propose_drafts(self, active: List[int]):
        """Proposal source for the verify dispatch. With a draft model
        attached (spec_method="draft"), the DraftModel rolls out spec_k
        greedy tokens per up-to-date slot (serving/draft.py); otherwise
        prompt-lookup: match the context's trailing
        spec_ngram against its own history (numpy sliding-window compare,
        rightmost hit wins) and propose the following spec_k tokens. Returns
        [num_slots, spec_k] int32, or None when nothing matched anywhere
        (the step then falls back to plain fused decode)."""
        K = self.serving.spec_k
        if self.draft is not None:
            # sampled slots accept nothing (spec_decode_step preserves their
            # distribution by sampling position 0 only) — don't draft them
            eligible = [s for s in active
                        if self.slot_req[s] is not None
                        and self.slot_req[s].temperature <= 0.0]
            return self.draft.propose(self, eligible, K)
        n = self.serving.spec_ngram
        drafts = np.zeros((self.num_slots, K), np.int32)
        # {slot: true draft count} — drafts shorter than spec_k are
        # zero-padded for the verify dispatch, and the verify argmax can
        # "accept" a padding zero; the metrics below clamp to these counts
        # so the reported acceptance rate covers only real proposed tokens
        # (ADVICE r2).
        proposed: dict = {}
        for slot in active:
            req = self.slot_req[slot]
            # Only greedy slots can accept drafts (sampled slots always fall
            # back to one token); proposing for them would burn verify FLOPs.
            if req.temperature > 0.0:
                continue
            ctx = req.prompt_ids + req.generated
            if len(ctx) < n + 2:
                continue
            arr = np.asarray(ctx[-2048:], np.int32)
            tgt = arr[-n:]
            win = np.lib.stride_tricks.sliding_window_view(arr[:-1], n)
            hits = np.nonzero((win == tgt).all(axis=1))[0]
            if hits.size == 0:
                continue
            cont = arr[int(hits[-1]) + n:][:K]
            if cont.size == 0:
                continue
            drafts[slot, :cont.size] = cont
            proposed[slot] = int(cont.size)
        return (drafts, proposed) if proposed else None

    def _slot_spec_ineligible(self, slot: int) -> bool:
        """True when this slot's request needs a plain-path-only feature:
        logprobs (verify computes no logprob tensors), active presence/
        frequency penalties (verify sampling applies none), an active
        min_tokens ban (verify has no stop-suppression masking), or a
        logit_bias (verify argmax ignores it), or guided decoding (verify
        emits multiple tokens per dispatch; the grammar mask needs the host
        FSM between every token). Such slots
        are skipped by the verify dispatch and served by the alternating
        plain step — per-slot fallback, not batch-wide."""
        req = self.slot_req[slot]
        return (req.logprobs is not None
                or req.guided is not None
                or (self.counts is not None
                    and bool(self.pres_pens[slot] or self.freq_pens[slot]
                             or self.rep_pens[slot] != 1.0))
                or self.ban_until[slot] > self.lengths[slot]
                or self._bias_n[slot] > 0)

    def _do_spec_decode(self, active: List[int], drafts,
                        proposed: dict, skip=frozenset()) -> None:
        """One speculative verify dispatch: up to spec_k + 1 tokens per slot.

        ``skip`` slots participate in the dispatch (the batch shape is fixed
        and their surplus K/V row writes follow the standard rewrite
        invariant) but emit nothing — their tokens come from the next plain
        step, which applies the features the verify pass lacks."""
        t0 = time.monotonic()
        R = self.serving.spec_k + 1
        tokens = np.concatenate([self.last_token[:, None], drafts], axis=1)
        self.cache, out, accepted = spec_decode_step(
            self.cfg, R, self.params, self.cache, jnp.asarray(tokens),
            jnp.asarray(self.lengths), self._next_rng(),
            jnp.asarray(self.temps), jnp.asarray(self.top_ks),
            jnp.asarray(self.top_ps), impl=self.serving.attention_impl,
            table=jnp.asarray(self.table) if self.paged else None,
            seeds=jnp.asarray(self.seeds), mesh=self.mesh,
            lora_idx=self._lora_vec(),
            bblock=self.decode_bblock)
        ch = _chaos.get()
        if ch.enabled:
            # an armed "ragged_feature_error" raises here, standing in for
            # a corrupted verify-row transfer: nothing below has emitted, so
            # the failover path discards the whole dispatch un-emitted and
            # releases every slot exactly once (engine._fail_all)
            ch.on_feature_path(self, kind="spec")
        out = np.asarray(out)
        accepted = np.asarray(accepted)
        dt = time.monotonic() - t0
        self.metrics.device_busy_seconds.inc(dt)
        _devmon.note("spec_decode", dt, batch=len(active),
                     tokens=R * len(active),
                     ctx_rows=float(np.mean(self.lengths[list(active)]))
                     if active else 0.0)
        emitted = 0
        for slot in active:
            if slot in skip:
                continue
            acc = int(accepted[slot])
            if slot in proposed:  # acceptance rate over REAL proposals
                # clamp both sides to the slot's true draft count: the verify
                # pass can "accept" zero-padding past a short draft, which
                # would otherwise inflate the acceptance rate (ADVICE r2)
                n_drafted = proposed[slot]
                self.metrics.spec_drafted_tokens.inc(n_drafted)
                self.metrics.spec_accepted_tokens.inc(
                    min(max(acc - 1, 0), n_drafted))
                d = self.metrics.spec_drafted_tokens.total()
                if d > 0:
                    self.metrics.spec_acceptance_rate.set(
                        self.metrics.spec_accepted_tokens.total() / d)
            slot_emitted = 0
            for i in range(acc):
                if self.slot_req[slot] is None:
                    break  # hit a stop condition mid-prefix
                self.lengths[slot] += 1
                self.sched.note_decode(slot, 1)
                self._emit(slot, int(out[slot, i]))
                emitted += 1
                slot_emitted += 1
            if self.draft is not None and slot in proposed:
                # newest token + accepted drafts are now true draft context
                self.draft.note_emitted(slot, slot_emitted)
        self.metrics.decode_step_duration.observe(
            dt / max(1.0, emitted / max(1, len(active))))
        self._tok_times.append((t0, emitted))
        if len(self._tok_times) >= 2:
            span = time.monotonic() - self._tok_times[0][0]
            toks = sum(n for _, n in self._tok_times)
            if span > 0:
                self.metrics.tokens_per_second.set(toks / span)
        # The verify advanced lengths/last_token on the HOST (accept counts
        # are data-dependent); a carry retained across the preceding settle
        # no longer matches the mirrors but _carry_gen never moved — drop
        # it explicitly so the next dispatch re-uploads the synced mirrors
        # instead of feeding a stale device carry (_carry_valid would
        # otherwise say yes).
        self._pipe_carry = None

    def _pipeline_on(self) -> bool:
        """May a decode dispatch be left in flight after this step?

        Chunked prefill interleaves horizon-1 decodes against a half-built
        slot and a draining engine must hit "nothing in flight" the moment
        its last emit goes out — both always force sync. Spec decode used
        to as well (its proposer reads host mirrors); with
        ``ragged_features`` on, the spec branch instead SETTLES the
        in-flight dispatch (``_settle_inflight`` — carry retained, no drain
        counted) right before proposing, so plain dispatches between verify
        rounds keep the pipeline open.
        """
        return (self.serving.decode_pipeline > 0
                and (self.serving.ragged_features > 0
                     or not self.serving.spec_decode)
                and self._chunk is None
                and not self.draining)

    def _ragged_on(self) -> bool:
        """May chunked prefill ride the ragged mixed-batch program?

        Requires the paged pool (the ragged kernel gathers through per-row
        page tables) and the pipeline itself (the whole point is keeping it
        open). Always gated off for multi-group meshes (the packed batch
        spans dp/sp shards) and a draining engine. With ``ragged_features``
        (the default) the feature paths COMPOSE with the mixed program
        (ISSUE 16): guided slots ride as a per-row allow-mask operand, LoRA
        as a per-token adapter-index operand, and spec decode settles (not
        drains) around its verify dispatches. ``ragged_features=0``
        restores the PR-14 fallback: spec decode, LoRA, and any active
        guided slot de-pipeline to the sync floor (the byte-identity A/B
        arm in tests/test_decode_pipeline.py)."""
        feats = self.serving.ragged_features > 0
        if not (self.serving.ragged_attention > 0 and self.paged
                and self.serving.decode_pipeline > 0
                and (feats or not self.serving.spec_decode)
                and (feats or not self.lora_names)
                and not self.draining):
            return False
        if self.mesh is not None and (self.mesh.shape.get("dp", 1) > 1
                                      or self.mesh.shape.get("sp", 1) > 1):
            return False
        return feats or not any(r is not None and r.guided is not None
                                for r in self.slot_req)

    def _carry_valid(self) -> bool:
        """True while the device-resident token/length carry of the
        in-flight dispatch still describes the batch — no slot was
        activated, preempted, or otherwise rewritten since it was
        enqueued (every such transition bumps ``_carry_gen``)."""
        return (self._pipe_carry is not None
                and self._pipe_carry[2] == self._carry_gen)

    def _drain_decode_pipeline(self, reason: str = "drain") -> None:
        """Fetch + emit the in-flight decode dispatch, if any.

        Every transition that reads or rewrites slot state out of band of
        the device carry must drain first: prefill admission (slot reuse
        would mis-route the deferred emits), chunk start, spec decode,
        drain/failover. The device carry is dropped with it; the next
        dispatch re-uploads token/length from the now-fresh host mirrors.

        ``reason`` feeds tpu_serve_pipeline_drains_total (prefill/chunk/
        spec/guided/drain/fail) — the production-visible count of how often
        the pipeline is forced shut, which the ragged mixed-batch path
        (ISSUE 14) exists to drive to ~zero under mixed traffic.
        """
        rec = self._inflight
        if rec is None:
            return
        _metrics.pipeline.drains.inc(reason=reason)
        self._inflight = None
        self._pipe_carry = None
        self.metrics.pipeline_depth.set(0.0)
        self._decode_fetch(rec, tail=True)

    def _settle_inflight(self) -> None:
        """Fetch + emit the in-flight dispatch WITHOUT counting a drain and
        WITHOUT dropping the device carry.

        The carry-generation handoff (ISSUE 16): a feature path that needs
        the host mirrors current (spec decode's proposer) or the emits
        applied (a guided slot's FSM must see token N before masking token
        N+1) settles the predecessor instead of draining it. Finishing a
        slot mid-fetch does NOT bump ``_carry_gen`` (the carry's surplus
        lanes for a finished slot are discarded on emit — see
        engine._finish), so ``_pipe_carry`` remains valid and the next
        dispatch feeds it straight back in, device-resident: no host
        re-upload, no ``tpu_serve_pipeline_drains_total`` increment. Only
        transitions that REWRITE slot state (activate/preempt/spec-verify
        host advance) invalidate the carry.
        """
        rec = self._inflight
        if rec is None:
            return
        self._inflight = None
        self.metrics.pipeline_depth.set(0.0)
        self._decode_fetch(rec, tail=True)

    @staticmethod
    def _donatable(mirror: np.ndarray):
        """Device upload of a host mirror that is SAFE to pass in a donated
        argument position.

        ``jnp.asarray`` of an aligned numpy array is zero-copy on the CPU
        backend — the jax.Array is a *view of the engine's mirror buffer*.
        ``decode_steps`` donates its token/length carry, so XLA may alias
        that buffer for an output and write the final device-side lengths
        straight into ``self.lengths``: the mirror then advances once in
        place by the kernel and again (+1/token) by the emit loop, and the
        double-counted rows exhaust the cache window at half budget with a
        premature "length" finish. Copying first hands the device a buffer
        nothing else references, which donation may then consume freely.
        """
        return jnp.asarray(np.array(mirror))

    def _decode_operands(self):
        """Device-resident sampling/table operands for decode dispatches.

        Re-uploaded only when the host mirrors changed (dirty flags set on
        slot activate/finish/preempt and at every block-table write) —
        re-``jnp.asarray``-ing ~10 arrays per dispatch put serial host
        uploads on the critical path of every decode, visible at the
        89.5 ms-RTT class latencies of a network-attached chip.
        """
        oc = self._op_cache
        if self._op_dirty_sampling or "temps" not in oc:
            oc["temps"] = jnp.asarray(self.temps)
            oc["top_ks"] = jnp.asarray(self.top_ks)
            oc["top_ps"] = jnp.asarray(self.top_ps)
            oc["seeds"] = jnp.asarray(self.seeds)
            oc["ban_ids"] = jnp.asarray(self.ban_ids)
            oc["ban_until"] = jnp.asarray(self.ban_until)
            oc["bias_ids"] = jnp.asarray(self.bias_ids)
            oc["bias_vals"] = jnp.asarray(self.bias_vals)
            oc["pres"] = jnp.asarray(self.pres_pens)
            oc["freq"] = jnp.asarray(self.freq_pens)
            oc["rep"] = jnp.asarray(self.rep_pens)
            oc["lora"] = self._lora_vec()
            self._op_dirty_sampling = False
        if self.paged and (self._op_dirty_table or "table" not in oc):
            oc["table"] = jnp.asarray(self.table)
            self._op_dirty_table = False
        return oc

    def _do_decode(self, max_horizon: Optional[int] = None,
                   fair_horizon: bool = False):
        ch = _chaos.get()
        if ch.enabled:
            # an armed "stalled_decode" wedges here (standing in for a hung
            # device dispatch) until the watchdog aborts it — see chaos.py
            ch.on_decode_step(self)
        self._prefill_streak = 0
        prev = self._inflight
        if prev is not None and not self._carry_valid():
            # Slot lifecycle changed under the in-flight dispatch (activate/
            # preempt): its device carry no longer describes the batch, and
            # the host mirrors are stale until its tokens land — fetch
            # FIRST, then dispatch from the refreshed mirrors.
            self._drain_decode_pipeline("prefill")
            prev = None
        active = self._active_slots()
        # Fused horizon unless a waiting prompt could actually prefill next
        # step (pending AND a free slot): then take a single step so TTFT
        # isn't taxed. Under saturation (pending but no free slot) a prefill
        # is impossible anyway, so keep the fused horizon — dropping to
        # horizon=1 there would disable the amortization exactly at peak load.
        # A fairness-forced decode (``fair_horizon``) takes the FULL horizon
        # even though a prefill is possible: that is the point — one real
        # decode dispatch per prefill_fairness prefills.
        st = self.sched.stats()
        prefill_possible = st.queue_depth > 0 and st.active_slots < st.num_slots
        horizon = 1 if (prefill_possible and not fair_horizon) \
            else max(1, self.serving.decode_horizon)
        if max_horizon is not None:
            horizon = min(horizon, max_horizon)
        # Draft-model speculation keeps plain-path horizons within one
        # catch-up dispatch (R = spec_k + 1 rows): a full fused horizon
        # would put the draft cache R+ tokens behind, needing multiple
        # teacher-forcing rounds to recover (serving/draft.py).
        if (self.draft is not None and self.serving.spec_decode
                and self._spec_mesh_ok):
            horizon = min(horizon, self.serving.spec_k + 1)
        if self.paged:
            # The device cannot allocate: every active slot's pages must
            # cover its whole write horizon (incl. the spec path's R rows)
            # BEFORE the dispatch. May preempt the newest requests when the
            # pool runs dry — recompute the active set afterwards.
            grow = max(horizon, (self.serving.spec_k + 1)
                       if self.serving.spec_decode else 1)
            if prev is not None:
                # the unfetched dispatch writes its own horizon of rows
                # before the one about to be enqueued
                grow += prev["horizon"]
            if not self._ensure_pages(grow):
                return
            active = self._active_slots()
            if prev is not None and not self._carry_valid():
                # _ensure_pages preempted under the in-flight dispatch
                self._drain_decode_pipeline("prefill")
                prev = None
                active = self._active_slots()
        if not active:
            # cancel/deadline reaps emptied the batch since the last
            # dispatch; nothing to decode — just settle the pipeline
            self._drain_decode_pipeline()
            return
        # Speculative path: only when nothing is waiting (prefill priority
        # stands) and the mesh is spec-safe (None or pure-tp — see
        # _spec_mesh_ok). Eligibility is PER SLOT: a logprobs, penalized, or
        # min_tokens-banned request is skipped by the verify dispatch (those
        # features live only in the plain path) WITHOUT disabling speculation
        # for its neighbors; the skipped slots advance on the alternating
        # plain step (_spec_plain_due), so one logprobs request costs the
        # batch one interleaved plain dispatch, not the whole spec win
        # (VERDICT r3 weak #4: the old global .any() gates gave a single
        # request a batch-wide blast radius). Falls back when no context
        # matched.
        if (self.serving.spec_decode and self._spec_mesh_ok and horizon > 1
                and not self._spec_plain_due):
            if prev is not None:
                # Carry-generation handoff (ISSUE 16): the proposer and the
                # length bound below read host mirrors, so the in-flight
                # dispatch is SETTLED first — its emits sync the mirrors,
                # the carry stays valid, and no drain is counted. The old
                # mandatory pre-spec drain is gone (with ragged_features=0,
                # _pipeline_on keeps spec traffic sync and prev is None).
                self._settle_inflight()
                prev = None
                active = self._active_slots()
                if not active:
                    return
            # the verify dispatch writes spec_k + 1 rows for EVERY slot,
            # so the bound stays global over the active set
            if (self.lengths[active].max(initial=0) + self.serving.spec_k
                    + 1 < self.max_len):
                skip = {s for s in active if self._slot_spec_ineligible(s)}
                proposal = self._propose_drafts([s for s in active
                                                 if s not in skip])
                if proposal is not None:
                    self._do_spec_decode(active, *proposal, skip=skip)
                    self._spec_plain_due = bool(skip)
                    return
        self._spec_plain_due = False
        # Guided decoding: the grammar mask is valid for ONE token (the host
        # FSM must see token N before masking token N+1), but capping the
        # whole batch at horizon 1 would collapse every unguided neighbor to
        # per-token dispatches (review r5: one response_format request would
        # cost the batch ~an order of magnitude at the measured 89.5 ms
        # dispatch RTT). Instead, MIXED batches keep the fused horizon and
        # guided slots emit only substep 0's token — their surplus substeps
        # sample against the (stale) mask and are discarded on the host,
        # with the surplus K/V rows following the standard rewrite
        # invariant. Pure-guided batches drop to horizon 1 for per-token
        # latency. Evaluated after the spec branch (a guided request rides
        # the _slot_spec_ineligible skip set, not an engine-wide disable)
        # and after _ensure_pages, whose preemption may have just cleared a
        # guided slot.
        gset = frozenset(
            s for s in active
            if self.slot_req[s] is not None
            and self.slot_req[s].guided is not None)
        feats = self.serving.ragged_features > 0
        if feats and gset and prev is not None:
            # Guided mask freshness: _decode_dispatch builds the allow rows
            # from each guided slot's host FSM, which only advances when the
            # predecessor's tokens are EMITTED — settle it first (fetch +
            # emit, carry retained, NO drain counted), then dispatch against
            # the post-advance grammar states. The mask upload itself is
            # async (jnp.asarray on the dispatch half — tpulint R8 allows
            # enqueue-side uploads; only blocking READS are banned), so the
            # per-row operand rides one step ahead of the device exactly
            # like the token carry.
            self._settle_inflight()
            prev = None
            active = self._active_slots()
            if not active:
                # the settle's emits finished every slot (EOS mid-stream)
                return
            gset = frozenset(
                s for s in active
                if self.slot_req[s] is not None
                and self.slot_req[s].guided is not None)
        if gset and not any(self.slot_req[s] is not None and s not in gset
                            for s in active):
            horizon = 1
        gslots = list(gset)
        want_lp = self._want_logprobs(self.slot_req)
        want_pen = self.counts is not None and bool(
            self.pres_pens.any() or self.freq_pens.any()
            or (self.rep_pens != 1.0).any())
        if self._carry_valid():
            # device-resident carry: dispatch N's final token/length arrays
            # feed dispatch N+1 directly (donated) — no host round-trip.
            # Still valid after a settle (prev is None but the carry
            # survives — _settle_inflight's contract).
            tok_in, len_in = self._pipe_carry[0], self._pipe_carry[1]
        else:
            tok_in = self._donatable(self.last_token)
            len_in = self._donatable(self.lengths)
        rec = self._decode_dispatch(horizon, active, gset, gslots, want_lp,
                                    want_pen, tok_in, len_in)
        if self._pipeline_on() and (feats or not gset):
            # leave the new dispatch in flight: its fetch is deferred to
            # the next decode step (or a pipeline drain), so the entire
            # emit/SSE/scheduling gap between dispatches overlaps device
            # compute instead of idling the chip for ~an RTT
            self._inflight = rec
            self.metrics.pipeline_depth.set(1.0)
            if prev is not None:
                self._decode_fetch(prev, tail=False)
        else:
            # synchronous path (decode_pipeline=0, guided, chunk, spec,
            # draining): settle everything before returning, in order. prev
            # IS self._inflight — retire it before fetching, or the next
            # step would fetch-and-emit the same dispatch twice (the
            # double emit advances the length mirrors two rows per real
            # token and exhausts the cache window at half budget).
            self._pipe_carry = None
            if prev is not None:
                _metrics.pipeline.drains.inc(reason=(
                    "chunk" if self._chunk is not None
                    else "guided" if gset
                    else "spec" if self.serving.spec_decode
                    else "drain"))
                self._inflight = None
                self.metrics.pipeline_depth.set(0.0)
                self._decode_fetch(prev, tail=False)
            self._decode_fetch(rec, tail=True)

    def _decode_dispatch(self, horizon: int, active: List[int], gset,
                         gslots: List[int], want_lp: bool, want_pen: bool,
                         tok_in, len_in) -> dict:
        """Enqueue ONE fused decode dispatch and return its in-flight
        record. JAX async dispatch: this returns as soon as the program is
        enqueued — no blocking device reads on this half (tpulint R8; they
        belong in _decode_fetch), so the host is free to emit the previous
        dispatch's tokens while the device runs this one."""
        oc = self._decode_operands()
        t0 = time.monotonic()
        if self._last_ready > 0.0:
            # the device has sat idle since the previous fetch completed
            # with nothing enqueued behind it; the gap until THIS enqueue
            # is pure host-side bubble — the cost the one-deep pipeline
            # exists to hide (and the sync path pays every dispatch)
            self.metrics.decode_bubble_seconds.inc(
                max(0.0, t0 - self._last_ready))
            self._last_ready = 0.0
        real_counts = self.counts
        self.cache, new_counts, out, tok, lens = decode_steps(
            self.cfg, horizon, self.params, self.cache, tok_in, len_in,
            self._next_rng(), oc["temps"], oc["top_ks"], oc["top_ps"],
            mesh=self.mesh, impl=self.serving.attention_impl,
            logprobs=want_lp,
            counts=self.counts if want_pen else None,
            presence=oc["pres"] if want_pen else None,
            frequency=oc["freq"] if want_pen else None,
            repetition=oc["rep"] if want_pen else None,
            prompt_mask=self.prompt_mask if want_pen else None,
            penalties=want_pen,
            table=oc["table"] if self.paged else None,
            seeds=oc["seeds"],
            ban_ids=oc["ban_ids"],
            ban_until=oc["ban_until"],
            bias_ids=oc["bias_ids"],
            bias_vals=oc["bias_vals"],
            allow=self._allow_words(gslots),
            lora_idx=oc["lora"],
            bblock=self.decode_bblock)
        # un-penalized dispatches return a dummy counts array — keep ours
        self.counts = new_counts if want_pen else real_counts
        self._pipe_carry = (tok, lens, self._carry_gen)
        _metrics.pipeline.dispatches.inc()
        # ring-only flight event (no per-request timeline work): a pure
        # deque append, safe on the async-dispatch half (tpulint R8)
        _flight.record("pipeline_dispatch", None, horizon=horizon,
                       batch=len(active))
        return {"out": out, "horizon": horizon, "active": list(active),
                "gset": gset, "gslots": gslots, "want_lp": want_lp,
                "want_pen": want_pen, "t0": t0}

    def _decode_fetch(self, rec: dict, tail: bool) -> None:
        """Blocking half of a decode dispatch: transfer the sampled tokens,
        update the host mirrors, emit. The ONLY place the decode path may
        block on program output (tpulint R8 sanctions exactly this helper).

        ``tail``: nothing is enqueued behind this dispatch, so the device
        goes idle when it completes — mark the completion time and let the
        next enqueue account the gap as host bubble. A non-tail fetch (the
        steady-state pipelined case) already has the next dispatch queued:
        no mark, no bubble.

        A slot that finished (EOS/deadline/cancel) after this dispatch was
        enqueued was still computed speculatively on the device; its
        surplus tokens are discarded here by the ``slot_req is None``
        guard, under the same rewrite invariant the guided/chunk surplus
        paths rely on.
        """
        ch = _chaos.get()
        if ch.enabled:
            # an armed "pipeline_fetch_error" raises here, standing in for
            # a transfer/XLA failure surfacing at the deferred block point
            ch.on_pipeline_fetch(self)
            if rec.get("mixed"):
                # an armed "ragged_dispatch_error" targets only mixed
                # dispatches — the in-flight record is discarded and the
                # chunk walk's error path releases its slot exactly once
                ch.on_mixed_fetch(self)
            if rec.get("gslots"):
                # an armed "ragged_feature_error" targets dispatches whose
                # allow-mask operand was live (guided rows), standing in
                # for a corrupted mask upload: the record is discarded
                # UN-EMITTED (no token below ever reached a stream) and
                # the failover path releases pages/slots exactly once
                ch.on_feature_path(self, kind="guided")
        out = rec["out"]
        lp_t = None
        if rec["want_lp"]:
            out, lp_t = out          # ([h, B], ([h,B], [h,B,K], [h,B,K]))
            # ONE bulk transfer; per-token slicing below is pure numpy (3
            # tiny device gathers per emitted token would round-trip the
            # network-attached chip thousands of times per dispatch)
            lp_t = tuple(np.asarray(a) for a in lp_t)
        out = np.asarray(out)  # [horizon, B] — blocks until device-complete
        if rec.get("mixed"):
            # chunk-row outputs ride the same record: the sampled token of
            # the chunk's last position (only meaningful on the final
            # chunk, where _advance_chunk_mixed activates with it)
            pout = rec["pout"]
            if rec["chunk_lp"]:
                ptok_arr, plp = pout
                rec["chunk_token"] = int(np.asarray(ptok_arr)[0])
                rec["chunk_lp_t"] = tuple(np.asarray(a) for a in plp)
            else:
                rec["chunk_token"] = int(np.asarray(pout)[0])
        t_ready = time.monotonic()
        horizon = rec["horizon"]
        # Device-time attribution: the busy window opens at this dispatch's
        # enqueue or the previous dispatch's completion, whichever is later
        # — overlapped dispatches must not double-count device seconds, and
        # decode_step_duration reports device time now that wall time
        # includes pipeline overlap.
        busy_start = max(rec["t0"], self._busy_watermark)
        dev_dt = max(0.0, t_ready - busy_start)
        self._busy_watermark = t_ready
        self.metrics.device_busy_seconds.inc(dev_dt)
        self.metrics.decode_step_duration.observe(dev_dt / horizon)
        _devmon.note("mixed_step" if rec.get("mixed") else "decode", dev_dt,
                     batch=len(rec["active"]) + (1 if rec.get("mixed")
                                                 else 0),
                     tokens=horizon * len(rec["active"])
                     + rec.get("chunk_n", 0),
                     ctx_rows=float(np.mean(self.lengths[
                         list(rec["active"])])) if rec["active"] else 0.0,
                     steps=horizon, guided_rows=len(rec["gslots"]))
        gset = rec["gset"]
        emitted = 0
        for s in range(horizon):
            for slot in rec["active"]:
                if self.slot_req[slot] is None:
                    # finished earlier in this horizon — or after the
                    # dispatch was enqueued (pipelined surplus discard)
                    continue
                if s > 0 and slot in gset:
                    # guided slots advance one grammar-checked token per
                    # dispatch; substeps past 0 are unconstrained surplus
                    continue
                req = self.slot_req[slot]
                lp = None
                if req.logprobs is not None and lp_t is not None:
                    lp = _host_lp(tuple(a[s] for a in lp_t), slot,
                                  req.logprobs)
                self.lengths[slot] += 1
                self.sched.note_decode(slot, 1)
                self._emit(slot, int(out[s, slot]), lp)
                emitted += 1
        if rec["want_pen"] and rec["gslots"] and horizon > 1:
            # the fused dispatch incremented guided slots' device-side
            # penalty-count rows for EVERY substep, but only substep 0 was
            # emitted — resync those rows from the authoritative host
            # stream (review r5: the first fix dropped the whole batch to
            # horizon 1 for one penalized guided request; this one costs a
            # single [V]-row scatter per guided slot instead)
            for slot in rec["gslots"]:
                req = self.slot_req[slot]
                if req is None or not (self.pres_pens[slot]
                                       or self.freq_pens[slot]
                                       or self.rep_pens[slot] != 1.0):
                    continue
                row = np.bincount(np.asarray(req.generated, np.int64),
                                  minlength=self.cfg.vocab_size)
                self.counts = _restore_count_row(
                    self.counts, jnp.int32(slot),
                    jnp.asarray(row, jnp.int32))
        if tail and any(r is not None for r in self.slot_req):
            self._last_ready = t_ready
        _flight.record("pipeline_fetch", None, horizon=horizon,
                       emitted=emitted, tail=tail)
        self._tok_times.append((rec["t0"], emitted))
        if len(self._tok_times) >= 2:
            span = time.monotonic() - self._tok_times[0][0]
            toks = sum(n for _, n in self._tok_times)
            if span > 0:
                self.metrics.tokens_per_second.set(toks / span)

    def warmup(self, scope: str = "full"):
        """Pre-compile programs so the first real request doesn't pay 20-40s
        of XLA compile time per program.

        scope="full" (serving): every variant — each prefill bucket, batched/
        chunked prefill, prefix cache, speculative, penalties, logprobs, both
        decode horizons. ~10 programs; over a network-attached chip this is
        minutes of XLA time, which is fine at server startup (the readiness
        probe gates traffic) but NOT inside a bounded benchmark window.

        scope="bench": only the two programs the benchmark path executes —
        the full-width batched prefill and the fused-horizon decode (bench
        prompts sit below the prefix-cache min length, spec decode is off,
        and the fill loop admits batches until the queue drains, so no other
        program is ever dispatched). This is what lets bench.py fit warmup +
        measurement inside the driver's ~900s budget (BENCH_r02 postmortem:
        serial full warmup plausibly consumed the whole window).
        """
        t0 = time.monotonic()
        try:
            self._warmup(scope)
        finally:
            # Cold-start observability: with a warm persistent compilation
            # cache (or an AOT-populated one) this stays near zero; minutes
            # here mean every respawn re-pays XLA (serving/aot.py).
            self.metrics.compile_seconds.inc(time.monotonic() - t0)

    def load_aot_manifest(self, path: str) -> dict:
        """Adopt an AOT manifest (serving/aot.py) for THIS engine.

        The manifest carries no executables — binaries come from the
        persistent compilation cache the AOT run populated. Adoption checks
        the manifest was built for this exact program set (config
        fingerprint) and that its HBM ledger fits, then surfaces the ledger
        on ``tpu_serve_hbm_compiled_bytes`` and ``/healthz``. A mismatched
        or no-fit manifest raises: serving silently without the AOT
        guarantee is exactly the cold-start/OOM surprise the artifact
        exists to rule out.
        """
        import json

        from aws_k8s_ansible_provisioner_tpu.serving.aot import verify_manifest

        with open(path, encoding="utf-8") as f:
            manifest = json.load(f)
        verify_manifest(manifest)
        dp = self.mesh.shape.get("dp", 1) if self.mesh is not None else 1
        tp = self.mesh.shape.get("tp", 1) if self.mesh is not None else 1
        want = {
            "model": self.cfg.name,
            "num_slots": self.num_slots,
            "max_len": self.max_len,
            "page_size": self.serving.page_size if self.paged else 0,
            "buckets": list(self.buckets),
            "weights_dtype": self.serving.weights_dtype,
            "kv_dtype": self.serving.kv_dtype,
            "paged": self.paged,
            "dp": dp, "tp": tp,
        }
        got = manifest["config"]
        bad = {k: (got.get(k), v) for k, v in want.items()
               if got.get(k) != v}
        if bad:
            raise ValueError(
                f"AOT manifest {path} was built for a different program "
                "set: " + "; ".join(
                    f"{k}: manifest={a!r} engine={b!r}"
                    for k, (a, b) in sorted(bad.items())))
        ledger = manifest["hbm_ledger"]
        if not ledger["fit"]:
            raise RuntimeError(
                f"AOT manifest {path} verdict is NO-FIT: "
                f"{ledger['total_bytes']} accounted bytes/chip vs "
                f"{ledger['capacity_bytes_per_chip']} capacity "
                f"(headroom {ledger['headroom_bytes']})")
        self.aot = {
            "path": path,
            "platform": manifest["platform"],
            "topology": manifest.get("topology", ""),
            "programs": len(manifest["programs"]),
            "total_compile_seconds": manifest["total_compile_seconds"],
            "hbm_total_bytes": ledger["total_bytes"],
            "hbm_headroom_bytes": ledger["headroom_bytes"],
            "fit": True,
        }
        self.metrics.hbm_compiled_bytes.set(float(ledger["total_bytes"]))
        return self.aot

    def _warmup(self, scope: str) -> None:
        # Runtime import: Request lives with the scheduler (engine.py), which
        # imports this module at load time — resolve the cycle at call time.
        from aws_k8s_ansible_provisioner_tpu.serving.engine import Request

        def drain():
            while (any(s is not None for s in self.slot_req) or self.pending
                   or self._chunk is not None):
                self.step()

        horizon = max(1, self.serving.decode_horizon)
        if scope == "bench":
            nb = min(self.serving.max_prefill_batch, self.num_slots)
            rs = [Request(prompt_ids=[0] * 4, max_tokens=1, ignore_eos=True)
                  for _ in range(max(1, nb))]
            for r in rs:
                self.submit(r)
            drain()
            if horizon > 1:
                self.cache, _, _, _, _ = decode_steps(
                    self.cfg, horizon, self.params, self.cache,
                    self._donatable(self.last_token),
                    self._donatable(self.lengths),
                    self._next_rng(), jnp.asarray(self.temps),
                    jnp.asarray(self.top_ks), jnp.asarray(self.top_ps),
                    mesh=self.mesh, impl=self.serving.attention_impl,
                    table=jnp.asarray(self.table) if self.paged else None,
                    seeds=jnp.asarray(self.seeds),
                    ban_ids=jnp.asarray(self.ban_ids),
                    ban_until=jnp.asarray(self.ban_until),
                    bias_ids=jnp.asarray(self.bias_ids),
                    bias_vals=jnp.asarray(self.bias_vals),
                    lora_idx=self._lora_vec(),
                    bblock=self.decode_bblock)
            return

        # Distinct token values per warmup request — identical prompts would
        # prefix-cache-match each other and warm the WRONG program.
        for i, b in enumerate(self.buckets):
            r = Request(prompt_ids=[(2 * i + 1) % (self.cfg.vocab_size - 1)]
                        * min(b, self.max_len - 2),
                        max_tokens=1, ignore_eos=True)
            self.submit(r)
            drain()
        # Batched-prefill program for the full batch width at the smallest
        # bucket (the burst-of-short-prompts case the batching exists for;
        # other (N, T) combos compile lazily on first use).
        nb = min(self.serving.max_prefill_batch, self.num_slots)
        if nb > 1:
            rs = [Request(prompt_ids=[0] * 4, max_tokens=1, ignore_eos=True)
                  for _ in range(nb)]
            for r in rs:
                self.submit(r)
            drain()
        # Chunk-prefill program (one program serves every chunk).
        if self.serving.prefill_chunk > 0 \
                and self.max_len - 2 > self.serving.prefill_chunk:
            r = Request(prompt_ids=[97 % (self.cfg.vocab_size - 1)]
                        * (self.serving.prefill_chunk + 1),
                        max_tokens=1, ignore_eos=True)
            self.submit(r)
            drain()
        # Prefix-cache programs (slot-to-slot copy + suffix chunk): a seed
        # prompt, then an extension of it, so the second takes the hit path.
        # The seed must clear BOTH gates (min_len and payback rows); when
        # that doesn't fit the prompt limit, the programs compile lazily on
        # the first real hit instead.
        n_seed = max(1, self.serving.prefix_cache_min_len,
                     self.serving.prefix_cache_payback_rows) + 1
        if self.serving.prefix_cache and n_seed + 8 <= self.prompt_limit:
            tok = 43 % (self.cfg.vocab_size - 1)
            seed = [tok] * n_seed
            self.submit(Request(prompt_ids=list(seed), max_tokens=1,
                                ignore_eos=True))
            drain()
            self.submit(Request(prompt_ids=list(seed) + [tok + 1] * 8,
                                max_tokens=1, ignore_eos=True))
            drain()
        # Speculative-verify program: a self-repeating prompt guarantees the
        # prompt-lookup proposer fires, compiling spec_decode_step.
        if self.serving.spec_decode and self._spec_mesh_ok:
            n = self.serving.spec_ngram
            pat = [11, 12, 13][:max(1, min(3, n))]
            r = Request(prompt_ids=(pat * (2 + (2 * n) // len(pat)))[:self.prompt_limit],
                        max_tokens=self.serving.spec_k + 2, ignore_eos=True)
            self.submit(r)
            drain()
        # compile the fused decode program too (horizon path), and its
        # penalties variant ('penalties' is a static arg — a distinct
        # program): the first penalized request must not pay a 20-40s XLA
        # compile inside step(), freezing every in-flight stream (and
        # burning most of the /health stall budget).
        if horizon > 1:
            r = Request(prompt_ids=[0] * 4, max_tokens=horizon + 1,
                        ignore_eos=True)
            self.submit(r)
            drain()
        # Penalties variants compile against THROWAWAY buffers so warmup does
        # not permanently allocate the [num_slots, vocab] counts array (~78 MB
        # int32 at Qwen3 vocab x 128 slots) an engine whose clients never use
        # penalties would otherwise carry — self.counts stays None until the
        # first real penalized request (ADVICE r2). Both device calls donate
        # their counts input, so the scratch buffer is freed on return.
        cnts = jnp.zeros((self.num_slots, self.cfg.vocab_size), jnp.int32)
        cnts = _reset_count_row(cnts, jnp.int32(0), jnp.int32(0))
        mask = jnp.zeros((self.num_slots, self.cfg.vocab_size), jnp.bool_)
        self.cache, _, _, _, _ = decode_steps(
            self.cfg, horizon, self.params, self.cache,
            self._donatable(self.last_token), self._donatable(self.lengths),
            self._next_rng(), jnp.asarray(self.temps),
            jnp.asarray(self.top_ks), jnp.asarray(self.top_ps),
            mesh=self.mesh, impl=self.serving.attention_impl,
            counts=cnts, presence=jnp.asarray(self.pres_pens),
            frequency=jnp.asarray(self.freq_pens),
            repetition=jnp.asarray(self.rep_pens), prompt_mask=mask,
            penalties=True,
            table=jnp.asarray(self.table) if self.paged else None,
            seeds=jnp.asarray(self.seeds),
            ban_ids=jnp.asarray(self.ban_ids),
            ban_until=jnp.asarray(self.ban_until),
            bias_ids=jnp.asarray(self.bias_ids),
            bias_vals=jnp.asarray(self.bias_vals),
                    lora_idx=self._lora_vec(),
                    bblock=self.decode_bblock)
        del cnts, mask
        # Logprobs program variants ('logprobs' is a static arg on every step
        # fn — distinct programs): one isolated request compiles the
        # single-prefill + fused-decode logprob programs, one burst compiles
        # the batched-prefill logprob program. Without these, the first
        # logprobs=N request pays the same all-streams XLA freeze the
        # penalties warmup exists to prevent (ADVICE r2, medium).
        self.submit(Request(prompt_ids=[3] * 4, max_tokens=max(2, horizon + 1),
                            ignore_eos=True, logprobs=0, prompt_logprobs=0))
        drain()
        if nb > 1:
            # one plp row in the burst also compiles the batched
            # prompt-logprob variant (echo+logprobs implies it — review r5)
            rs = [Request(prompt_ids=[5] * 4, max_tokens=1, ignore_eos=True,
                          logprobs=0, prompt_logprobs=0 if i == 0 else None)
                  for i in range(nb)]
            for r in rs:
                self.submit(r)
            drain()
        # The horizon=1 decode variant (selected whenever a prefill is
        # possible) is a distinct compiled program (n_steps is static);
        # compile it now so the first decode overlapping a queued request
        # doesn't stall all in-flight streams on XLA. Direct call, no slot
        # state touched: writes land at position 0 of idle slots and are
        # overwritten by real prefills.
        self.cache, _, _, _, _ = decode_steps(
            self.cfg, 1, self.params, self.cache,
            self._donatable(self.last_token), self._donatable(self.lengths),
            self._next_rng(), jnp.asarray(self.temps),
            jnp.asarray(self.top_ks), jnp.asarray(self.top_ps),
            mesh=self.mesh, impl=self.serving.attention_impl,
            table=jnp.asarray(self.table) if self.paged else None,
            seeds=jnp.asarray(self.seeds),
            ban_ids=jnp.asarray(self.ban_ids),
            ban_until=jnp.asarray(self.ban_until),
            bias_ids=jnp.asarray(self.bias_ids),
            bias_vals=jnp.asarray(self.bias_vals),
                    lora_idx=self._lora_vec(),
                    bblock=self.decode_bblock)
