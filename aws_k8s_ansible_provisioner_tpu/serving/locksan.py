"""LockSan: a deterministic lock-order / shared-write sanitizer (test-only).

tpulint R5 proves statically that writes to cross-thread attributes sit
under ``self._lock``; LockSan is the dynamic half of that contract.  Under
``TPU_LOCKSAN=1`` (tests/conftest.py installs it for the whole session,
``make locksan-smoke`` runs the e2e/drain/chaos subsets with it on) it

* wraps every ``threading.Lock``/``threading.RLock`` **constructed from
  serving/ code** — stdlib callers (queue, threading.Event, http.server)
  keep real primitives, so only our locks pay the bookkeeping tax;
* keeps a per-thread stack of held wrapped locks and grows a global
  acquisition-order graph keyed by construction *site* (``file:line#seq``);
* flags a **lock-order inversion** the moment an acquire closes a cycle in
  that graph — the classic A→B vs B→A deadlock is caught on the first
  interleaving that exhibits both orders, no timing luck required;
* optionally guards attributes (``watch_attrs``): every write to a watched
  attribute is checked — held class lock ⇒ fine; otherwise two *distinct*
  threads writing the same attribute unguarded is flagged (the dynamic
  analogue of an R5 finding).

Violations are **recorded, not raised** (the code under test keeps its
real semantics; nothing deadlocks or aborts mid-request) and reports are
deterministic: sorted by site, independent of thread scheduling.  The
session fixture in tests/conftest.py fails the run if any were recorded.

Overhead is a few hundred nanoseconds per acquire/release (measured in
PERF.md) — fine for tests, which is why this module is test-only and the
install is explicitly opt-in via the environment.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

# directory fragments whose call sites get wrapped locks; everything else
# (stdlib, third-party, non-serving repo code) gets the real primitive
_WRAP_DIRS = (os.sep + "serving" + os.sep,)


def _relsite(filename: str, lineno: int) -> str:
    parts = filename.replace("\\", "/").split("/")
    tail = "/".join(parts[-2:]) if len(parts) >= 2 else parts[-1]
    return f"{tail}:{lineno}"


class _State:
    """Global sanitizer state. All mutation under a REAL (unwrapped) lock —
    the sanitizer must never observe itself."""

    def __init__(self) -> None:
        self._mu = _REAL_LOCK()
        self._tl = threading.local()
        # site -> set of sites acquired while holding it (direct edges)
        self.edges: Dict[str, Set[str]] = {}
        self.violations: List[dict] = []
        self._seen_keys: Set[str] = set()
        self._site_seq: Dict[str, int] = {}
        # (obj id, attr) -> set of thread idents that wrote unguarded
        self._writers: Dict[Tuple[int, str], Set[int]] = {}
        self.n_acquires = 0
        self.n_attr_checks = 0

    # -- held-lock stack (per thread) ---------------------------------------

    def _stack(self) -> List["_SanLock"]:
        st = getattr(self._tl, "stack", None)
        if st is None:
            st = self._tl.stack = []
        return st

    # -- graph --------------------------------------------------------------

    def _reachable(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS path src ->* dst over direct edges (None if unreachable)."""
        seen = {src}
        path = [src]

        def walk(node: str) -> bool:
            if node == dst:
                return True
            for nxt in sorted(self.edges.get(node, ())):
                if nxt in seen:
                    continue
                seen.add(nxt)
                path.append(nxt)
                if walk(nxt):
                    return True
                path.pop()
            return False

        return path if walk(src) else None

    def _record(self, kind: str, key: str, detail: str, sites: List[str]):
        if key in self._seen_keys:      # one report per distinct shape
            return
        self._seen_keys.add(key)
        self.violations.append(
            {"kind": kind, "detail": detail, "sites": sorted(sites)})

    def on_acquire(self, lk: "_SanLock") -> None:
        st = self._stack()
        with self._mu:
            self.n_acquires += 1
            for held in st:
                if held is lk:          # RLock re-entry: no new ordering
                    continue
                a, b = held.site, lk.site
                if a == b:
                    continue
                if b in self.edges.setdefault(a, set()):
                    continue
                # would a -> b close a cycle?  i.e. does b already reach a?
                cyc = self._reachable(b, a)
                self.edges[a].add(b)
                if cyc is not None:
                    cycle = cyc + [b]
                    key = "cycle:" + "->".join(sorted(set(cycle)))
                    self._record(
                        "lock-order-inversion", key,
                        "acquired %s while holding %s, but the acquisition-"
                        "order graph already orders %s before %s (cycle: %s)"
                        % (b, a, b, a, " -> ".join(cycle)),
                        cycle)
        st.append(lk)

    def on_release(self, lk: "_SanLock") -> None:
        st = self._stack()
        # release order need not be LIFO; drop the newest matching entry
        for i in range(len(st) - 1, -1, -1):
            if st[i] is lk:
                del st[i]
                break

    def holds(self, lk: "_SanLock") -> bool:
        return any(h is lk for h in self._stack())

    # -- watched attributes -------------------------------------------------

    def on_attr_write(self, obj, name: str, lock_name: str) -> None:
        self.n_attr_checks += 1
        lk = getattr(obj, lock_name, None)
        if isinstance(lk, _SanLock) and self.holds(lk):
            return                      # guarded write: fine
        ident = threading.get_ident()
        key = (id(obj), name)
        with self._mu:
            writers = self._writers.setdefault(key, set())
            writers.add(ident)
            if len(writers) >= 2:
                self._record(
                    "unguarded-shared-write",
                    f"attr:{type(obj).__name__}.{name}",
                    f"attribute '{name}' of {type(obj).__name__} written "
                    f"without holding '{lock_name}' from "
                    f"{len(writers)} distinct threads",
                    [f"{type(obj).__name__}.{name}"])

    def site_for(self, filename: str, lineno: int) -> str:
        base = _relsite(filename, lineno)
        with self._mu:
            n = self._site_seq.get(base, 0)
            self._site_seq[base] = n + 1
        return base if n == 0 else f"{base}#{n}"


class _SanLock:
    """Wrapper around a real Lock/RLock feeding the order graph.

    Supports the full context-manager + acquire/release/locked surface;
    ``threading.Condition`` built on one works through its documented
    acquire/release fallbacks."""

    __slots__ = ("_inner", "site", "_reentrant")

    def __init__(self, inner, site: str, reentrant: bool):
        self._inner = inner
        self.site = site
        self._reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got and _state is not None:
            _state.on_acquire(self)
        return got

    def release(self):
        if _state is not None:
            _state.on_release(self)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):          # pragma: no cover - debugging nicety
        kind = "RLock" if self._reentrant else "Lock"
        return f"<locksan.{kind} site={self.site}>"


class _Guarded:
    """Data descriptor installed by watch_attrs: checks every ``sample``-th
    write, stores the value in the instance __dict__ as usual."""

    __slots__ = ("name", "lock_name", "sample", "_n")

    def __init__(self, name: str, lock_name: str, sample: int):
        self.name = name
        self.lock_name = lock_name
        self.sample = max(1, int(sample))
        self._n = 0

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        try:
            return obj.__dict__[self.name]
        except KeyError:
            raise AttributeError(self.name) from None

    def __set__(self, obj, value):
        self._n += 1
        if _state is not None and self._n % self.sample == 0:
            _state.on_attr_write(obj, self.name, self.lock_name)
        obj.__dict__[self.name] = value

    def __delete__(self, obj):
        obj.__dict__.pop(self.name, None)


_state: Optional[_State] = None


def _make_factory(real, reentrant: bool):
    def factory():
        if _state is None:
            return real()
        frame = sys._getframe(1)
        fn = frame.f_code.co_filename
        if not any(d in fn for d in _WRAP_DIRS):
            return real()
        site = _state.site_for(fn, frame.f_lineno)
        return _SanLock(real(), site, reentrant)

    factory.__name__ = real.__name__ if hasattr(real, "__name__") else "Lock"
    return factory


# -- public API -------------------------------------------------------------


def install() -> None:
    """Patch threading.Lock/RLock so serving/ call sites get tracked locks.

    Idempotent. Must run BEFORE the serving modules construct their locks
    (tests/conftest.py installs at collection time, which precedes every
    Engine/ServerState/BackendPool construction)."""
    global _state
    if _state is not None:
        return
    _state = _State()
    threading.Lock = _make_factory(_REAL_LOCK, reentrant=False)
    threading.RLock = _make_factory(_REAL_RLOCK, reentrant=True)


def uninstall() -> None:
    """Restore the real primitives (existing wrapped locks keep working —
    with _state gone their bookkeeping becomes a no-op)."""
    global _state
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    _state = None


def installed() -> bool:
    return _state is not None


def tracked_lock(reentrant: bool = False, site: Optional[str] = None):
    """A wrapped lock regardless of caller location — for tests that build
    synthetic acquisition orders (see tests/test_locksan.py)."""
    if _state is None:
        raise RuntimeError("locksan is not installed")
    if site is None:
        frame = sys._getframe(1)
        site = _state.site_for(frame.f_code.co_filename, frame.f_lineno)
    real = _REAL_RLOCK if reentrant else _REAL_LOCK
    return _SanLock(real(), site, reentrant)


def watch_attrs(cls, attrs=None, lock_name: str = "_lock", sample: int = 1):
    """Install write-checking descriptors on ``cls`` for ``attrs`` (default:
    the class's tpulint ``_R5_THREAD_OWNED`` declaration). Returns an undo
    callable. A write is fine when the instance's ``lock_name`` lock is held
    by the writing thread; otherwise unguarded writes from two distinct
    threads to the same attribute are flagged."""
    if attrs is None:
        attrs = getattr(cls, "_R5_THREAD_OWNED", ())
    installed_descs = []
    for name in attrs:
        if isinstance(cls.__dict__.get(name), _Guarded):
            continue
        desc = _Guarded(name, lock_name, sample)
        setattr(cls, name, desc)
        installed_descs.append(name)

    def undo():
        for name in installed_descs:
            if isinstance(cls.__dict__.get(name), _Guarded):
                delattr(cls, name)

    return undo


def violations() -> List[dict]:
    """Deterministic snapshot: sorted by (kind, sites)."""
    if _state is None:
        return []
    with _state._mu:
        return sorted((dict(v) for v in _state.violations),
                      key=lambda v: (v["kind"], v["sites"]))


def stats() -> dict:
    if _state is None:
        return {"installed": False}
    return {"installed": True, "acquires": _state.n_acquires,
            "attr_checks": _state.n_attr_checks,
            "sites": len(_state._site_seq),
            "violations": len(_state.violations)}


def reset() -> None:
    """Drop recorded violations and the order graph (keeps the install)."""
    if _state is None:
        return
    with _state._mu:
        _state.edges.clear()
        _state.violations.clear()
        _state._seen_keys.clear()
        _state._writers.clear()


def report() -> str:
    """Human-readable, deterministically ordered violation report."""
    vs = violations()
    if not vs:
        return "locksan: clean"
    lines = [f"locksan: {len(vs)} violation(s)"]
    for v in vs:
        lines.append(f"  [{v['kind']}] {v['detail']}")
    return "\n".join(lines)
