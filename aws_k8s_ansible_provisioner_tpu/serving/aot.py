"""AOT compiled-program registry: deviceless compilation + HBM fit ledger.

Replica respawn is the common case (drain, failover, rolling restarts,
reconciler repair), and every respawned engine pays 20-40 s of serial XLA
compile per program before ``/readyz`` flips. This module compiles the FULL
program set a serving config can dispatch — the same enumeration
``EnginePrograms.warmup`` walks (``serving/programs.py``) — **ahead of time
and deviceless**, then writes a committed manifest recording per-program
compile seconds and ``memory_analysis()`` bytes, summed into an HBM ledger
(params + KV pages + max temp) with an explicit fit/no-fit verdict against
per-chip capacity. An over-budget config fails fast at deploy time (non-zero
exit) instead of OOMing on the first burst.

Compilation target, best available first:

1. ``jax.experimental.topologies`` — an abstract TPU topology (default
   ``v5e:2x4`` = v5e-8) when libtpu is importable: real Mosaic/XLA-TPU
   lowering, no chips needed. The GCE metadata probe is skipped explicitly
   (``TPU_SKIP_MDS_QUERY``) — without it the topology lookup hangs on
   non-GCE hosts.
2. An 8-device host-platform mesh of identical axis shapes otherwise
   (``--xla_force_host_platform_device_count``): identical program
   *structure* and exact params/KV ledger bytes; temp bytes become a
   host-backend proxy (recorded as such in the manifest).

Programs compile through ``jax.jit(...).lower(abstract args).compile()`` —
operands are ``ShapeDtypeStruct``s built by ``jax.eval_shape`` over the same
init/quantize functions the engine calls, so nothing model-sized is ever
materialized (Qwen3-8B AOT runs in megabytes of host RAM).

Usage::

    python -m aws_k8s_ansible_provisioner_tpu.serving.aot \
        --model Qwen/Qwen3-8B --tp 8 --out AOT_QWEN3_8B_v5e8.json

At serve time the engine consumes the manifest (``--aot-manifest`` on the
server CLI → ``EnginePrograms.load_aot_manifest``): the config fingerprint
is re-checked, the ledger lands on ``tpu_serve_hbm_compiled_bytes``, and
warmup compiles through the persistent compilation cache the AOT run
populated (``--cache-dir`` / ``JAX_COMPILATION_CACHE_DIR``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional

MANIFEST_SCHEMA = "tpu-serve-aot/v1"
V5E_HBM_GIB_PER_CHIP = 16.0
# Fields every program entry must carry (schema check + tests).
PROGRAM_FIELDS = ("name", "compile_seconds", "argument_bytes",
                  "output_bytes", "temp_bytes", "generated_code_bytes")
LEDGER_FIELDS = ("capacity_bytes_per_chip", "params_bytes_per_chip",
                 "kv_bytes_per_chip", "max_temp_bytes", "total_bytes",
                 "headroom_bytes", "fit")


# ---------------------------------------------------------------------------
# Sizing plan (mirrors Engine.__init__ / EnginePrograms._init_params_and_cache
# arithmetic; tests/test_aot.py pins the two against each other)
# ---------------------------------------------------------------------------


class ProgramPlan:
    """The derived sizes every program's operand shapes hang off."""

    def __init__(self, cfg, serving, dp: int = 1, tp: int = 1):
        self.cfg, self.serving = cfg, serving
        self.dp, self.tp = dp, tp
        self.num_slots = serving.max_decode_slots
        if self.num_slots % dp:
            raise ValueError(f"max_decode_slots={self.num_slots} must be "
                             f"divisible by dp={dp}")
        max_len = -(-serving.max_cache_len // 256) * 256 \
            if serving.max_cache_len > 256 else serving.max_cache_len
        self.max_len = min(max_len, cfg.max_seq_len)
        self.buckets = tuple(b for b in serving.prefill_buckets
                             if b <= self.max_len)
        if not self.buckets:
            raise ValueError("no prefill bucket fits the cache window")
        self.kv_quant = serving.kv_dtype == "int8"
        self.weights_quant = serving.weights_dtype == "int8"
        self.paged = bool(serving.paged)
        ps = serving.page_size
        self.pages_per_slot = -(-self.max_len // ps) if self.paged else 0
        if self.paged:
            pool_pages = serving.kv_pool_pages \
                or self.num_slots * self.pages_per_slot
            if serving.kv_pool_pages and pool_pages % dp:
                raise ValueError(f"kv_pool_pages={pool_pages} must be "
                                 f"divisible by dp={dp}")
            # +1 scratch page per dp group (engine layout)
            self.total_pages = dp * (pool_pages // dp + 1)
        else:
            self.total_pages = 0
        # batched-prefill row bucket: the engine rounds the live batch up to
        # a power of two, warmup fills min(max_prefill_batch, num_slots)
        nb = max(1, min(serving.max_prefill_batch, self.num_slots))
        self.batch_rows = 1 << (nb - 1).bit_length()
        # chunk program width: configured chunk, else the largest bucket
        # (the prefix-cache suffix path dispatches it even when plain
        # chunked prefill is off) — Engine._chunk_size
        self.chunk = serving.prefill_chunk if serving.prefill_chunk > 0 \
            else self.buckets[-1]
        self.horizon = max(1, serving.decode_horizon)
        self.spec_rows = serving.spec_k + 1 if serving.spec_decode else 0

    def fingerprint(self) -> dict:
        """The config facts a consuming engine must match."""
        return {
            "model": self.cfg.name,
            "num_slots": self.num_slots,
            "max_len": self.max_len,
            "page_size": self.serving.page_size if self.paged else 0,
            "buckets": list(self.buckets),
            "weights_dtype": self.serving.weights_dtype,
            "kv_dtype": self.serving.kv_dtype,
            "paged": self.paged,
            "dp": self.dp, "tp": self.tp,
        }


# ---------------------------------------------------------------------------
# Abstract operands
# ---------------------------------------------------------------------------


def _mesh_for(devices, dp: int, tp: int):
    from aws_k8s_ansible_provisioner_tpu.config import MeshConfig
    from aws_k8s_ansible_provisioner_tpu.parallel.mesh import make_mesh

    need = dp * tp
    if len(devices) < need:
        raise RuntimeError(f"need {need} devices for dp={dp} tp={tp}, "
                           f"have {len(devices)}")
    return make_mesh(MeshConfig(dp=dp, tp=tp), devices=list(devices)[:need])


def _with_sharding(sds_tree, pspec_tree, mesh):
    """Attach NamedShardings to a ShapeDtypeStruct pytree (no-op without a
    mesh — single-device AOT lowers unsharded, like the engine)."""
    import jax
    from jax.sharding import NamedSharding

    if mesh is None:
        return sds_tree
    return jax.tree.map(
        lambda s, spec: jax.ShapeDtypeStruct(
            s.shape, s.dtype, sharding=NamedSharding(mesh, spec)),
        sds_tree, pspec_tree)


def _abstract_state(plan, mesh):
    """(params, cache) as ShapeDtypeStruct pytrees with the engine's
    shardings — via eval_shape over the engine's own init/quantize fns, so
    shapes can never drift from what the engine dispatches."""
    import jax
    import jax.numpy as jnp

    from aws_k8s_ansible_provisioner_tpu.models.layers import init_params
    from aws_k8s_ansible_provisioner_tpu.models.quant import quantize_params
    from aws_k8s_ansible_provisioner_tpu.parallel.sharding import (
        cache_pspecs, param_pspecs, pool_pspecs)
    from aws_k8s_ansible_provisioner_tpu.serving import kv_cache as kvc
    from aws_k8s_ansible_provisioner_tpu.serving import paged_kv as pkv

    cfg, serving = plan.cfg, plan.serving
    dtype = jnp.bfloat16 if serving.dtype == "bfloat16" else jnp.float32
    params = jax.eval_shape(
        lambda: init_params(cfg, jax.random.PRNGKey(0), dtype))
    if plan.weights_quant:
        params = jax.eval_shape(lambda p: quantize_params(p, cfg), params)
    params = _with_sharding(
        params, param_pspecs(cfg, quant_weights=plan.weights_quant), mesh)
    if plan.paged:
        cache = jax.eval_shape(
            lambda: pkv.init_pool(cfg, plan.total_pages, serving.page_size,
                                  dtype, quant=plan.kv_quant))
        cache = _with_sharding(cache, pool_pspecs(plan.kv_quant), mesh)
    else:
        cache = jax.eval_shape(
            lambda: kvc.init_cache(cfg, plan.num_slots, plan.max_len, dtype,
                                   quant=plan.kv_quant))
        cache = _with_sharding(cache, cache_pspecs(plan.kv_quant), mesh)
    return params, cache


def _sharded_bytes(sds_tree, pspec_tree, mesh) -> int:
    """Exact per-chip bytes of a sharded pytree: each leaf's bytes divided
    by the product of the mesh-axis sizes its PartitionSpec names
    (replicated leaves count whole — every chip holds them)."""
    import jax

    total = 0
    leaves = zip(jax.tree.leaves(sds_tree),
                 jax.tree.leaves(pspec_tree, is_leaf=lambda x: x is None
                                 or isinstance(x, tuple)))
    for leaf, spec in leaves:
        shards = 1
        if mesh is not None and spec is not None:
            for axes in spec:
                for ax in ((axes,) if isinstance(axes, str)
                           else (axes or ())):
                    shards *= mesh.shape.get(ax, 1)
        size = 1
        for d in leaf.shape:
            size *= d
        total += (size * leaf.dtype.itemsize) // max(1, shards)
    return total


# ---------------------------------------------------------------------------
# Program enumeration (mirrors EnginePrograms.warmup scope="full")
# ---------------------------------------------------------------------------


def enumerate_programs(plan, mesh, params, cache, bblock: int = 1):
    """Full program set for the config: one (name, jit_fn, args, kwargs)
    per distinct compiled executable the engine can dispatch. Mirrors
    ``warmup(scope="full")``: every prefill bucket, batched prefill, the
    chunk program, fused + horizon-1 decode, the penalties and logprobs
    variants, and the spec-verify program when speculation is on."""
    import jax
    import jax.numpy as jnp

    from aws_k8s_ansible_provisioner_tpu.serving.programs import (
        BAN_K, BIAS_K, decode_steps, mixed_step, prefill_batch_step,
        prefill_chunk_step, prefill_step, spec_decode_step)

    cfg, serving = plan.cfg, plan.serving
    B, pps = plan.num_slots, plan.pages_per_slot

    def sds(shape, dt):
        return jax.ShapeDtypeStruct(shape, dt)

    i32, f32, u32 = jnp.int32, jnp.float32, jnp.uint32
    rng = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    scalar = sds((), i32)

    def prefill_kwargs(n: Optional[int] = None):
        """Per-request operand rows; ``n`` rows for the batch program, the
        single-prompt scalar layout otherwise."""
        if n is None:
            return dict(
                pages=sds((pps,), i32) if plan.paged else None,
                seed=sds((), u32), ban_ids=sds((BAN_K,), i32),
                ban_until=scalar, bias_ids=sds((BIAS_K,), i32),
                bias_vals=sds((BIAS_K,), f32), rep=sds((), f32))
        return dict(
            tables=sds((n, pps), i32) if plan.paged else None,
            seeds=sds((n,), u32), ban_ids=sds((n, BAN_K), i32),
            ban_until=sds((n,), i32), bias_ids=sds((n, BIAS_K), i32),
            bias_vals=sds((n, BIAS_K), f32), reps=sds((n,), f32))

    programs = []
    for b in plan.buckets:
        programs.append((
            f"prefill_b{b}", prefill_step,
            (cfg, params, cache, sds((1, b), i32), scalar, scalar, rng,
             sds((), f32), scalar, sds((), f32)),
            prefill_kwargs()))
    # logprobs variants compile against the smallest bucket (any bucket
    # proves the variant; warmup uses an isolated small request too)
    b0 = plan.buckets[0]
    programs.append((
        f"prefill_b{b0}_logprobs", prefill_step,
        (cfg, params, cache, sds((1, b0), i32), scalar, scalar, rng,
         sds((), f32), scalar, sds((), f32)),
        dict(prefill_kwargs(), logprobs=True, prompt_logprobs=True)))
    n = plan.batch_rows
    programs.append((
        f"prefill_batch_n{n}_b{b0}", prefill_batch_step,
        (cfg, params, cache, sds((n, b0), i32), sds((n,), i32),
         sds((n,), i32), rng, sds((n,), f32), sds((n,), i32),
         sds((n,), f32)),
        prefill_kwargs(n)))
    programs.append((
        f"prefill_chunk_c{plan.chunk}", prefill_chunk_step,
        (cfg, params, cache, sds((1, plan.chunk), i32), scalar, scalar,
         scalar, rng, sds((), f32), scalar, sds((), f32)),
        dict(pages=sds((pps,), i32) if plan.paged else None,
             seed=sds((), u32), ban_ids=sds((BAN_K,), i32),
             ban_until=scalar, bias_ids=sds((BIAS_K,), i32),
             bias_vals=sds((BIAS_K,), f32), rep=sds((), f32),
             rep_seen=sds((cfg.vocab_size,), jnp.bool_))))

    def decode_kwargs(penalties=False, logprobs=False):
        kw = dict(
            mesh=mesh, impl=serving.attention_impl, logprobs=logprobs,
            penalties=penalties,
            table=sds((B, pps), i32) if plan.paged else None,
            seeds=sds((B,), u32), ban_ids=sds((B, BAN_K), i32),
            ban_until=sds((B,), i32), bias_ids=sds((B, BIAS_K), i32),
            bias_vals=sds((B, BIAS_K), f32), bblock=bblock)
        if penalties:
            kw.update(counts=sds((B, cfg.vocab_size), i32),
                      presence=sds((B,), f32), frequency=sds((B,), f32),
                      repetition=sds((B,), f32),
                      prompt_mask=sds((B, cfg.vocab_size), jnp.bool_))
        return kw

    decode_args = (cfg, plan.horizon, params, cache, sds((B,), i32),
                   sds((B,), i32), rng, sds((B,), f32), sds((B,), i32),
                   sds((B,), f32))
    programs.append((f"decode_fused_h{plan.horizon}", decode_steps,
                     decode_args, decode_kwargs()))
    if plan.horizon > 1:
        programs.append((
            "decode_h1", decode_steps,
            (cfg, 1) + decode_args[2:], decode_kwargs()))
    programs.append((f"decode_fused_h{plan.horizon}_penalties", decode_steps,
                     decode_args, decode_kwargs(penalties=True)))
    programs.append((f"decode_fused_h{plan.horizon}_logprobs", decode_steps,
                     decode_args, decode_kwargs(logprobs=True)))
    if (plan.paged and serving.ragged_attention > 0
            and serving.decode_pipeline > 0
            and (serving.ragged_features > 0 or not serving.spec_decode)):
        # Ragged mixed-batch program (ISSUE 14): one dispatch serves a
        # prefill chunk packed alongside every decode row. Operand layout
        # mirrors EnginePrograms._mixed_dispatch exactly. With
        # ragged_features (ISSUE 16) the spec-decode clause relaxes —
        # verify now hands the carry off instead of forcing a pre-spec
        # drain, so a spec-enabled engine still runs the mixed program.
        mixed_args = (cfg, params, cache, sds((B,), i32), sds((B,), i32),
                      sds((1, plan.chunk), i32), scalar, scalar, scalar,
                      sds((), f32), sds((cfg.vocab_size,), jnp.bool_),
                      sds((), u32), sds((), f32), scalar, sds((), f32), rng,
                      sds((B,), f32), sds((B,), i32), sds((B,), f32))
        mixed_kwargs = dict(
            mesh=mesh, impl=serving.attention_impl,
            table=sds((B, pps), i32), seeds=sds((B,), u32),
            ban_ids=sds((B, BAN_K), i32), ban_until=sds((B,), i32),
            bias_ids=sds((B, BIAS_K), i32),
            bias_vals=sds((B, BIAS_K), f32), bblock=bblock)
        programs.append((f"mixed_c{plan.chunk}", mixed_step,
                         mixed_args, mixed_kwargs))
        if serving.ragged_features > 0:
            # Guided variant (ISSUE 16): decode-row allow bitset + the
            # chunking request's own grammar row — the per-row mask
            # operands _mixed_dispatch passes when any guided slot is
            # active. Proven once here so the first guided admission on a
            # manifest-adopted replica never compiles.
            W = (cfg.vocab_size + 31) // 32
            programs.append((
                f"mixed_c{plan.chunk}_guided", mixed_step, mixed_args,
                dict(mixed_kwargs, allow=sds((B, W), u32),
                     pallow=sds((1, W), u32))))
    if plan.spec_rows:
        R = plan.spec_rows
        programs.append((
            f"spec_verify_r{R}", spec_decode_step,
            (cfg, R, params, cache, sds((B, R), i32), sds((B,), i32), rng,
             sds((B,), f32), sds((B,), i32), sds((B,), f32)),
            dict(impl=serving.attention_impl, mesh=mesh,
                 table=sds((B, pps), i32) if plan.paged else None,
                 seeds=sds((B,), u32), bblock=bblock)))
    return programs


# ---------------------------------------------------------------------------
# Deviceless compile + ledger
# ---------------------------------------------------------------------------


def _memory_entry(compiled) -> dict:
    """memory_analysis() bytes, zero-filled where the backend reports none
    (the host platform's analysis is partial — flagged via ``platform``)."""
    ma = None
    try:
        ma = compiled.memory_analysis()
    except Exception:            # tpulint: disable=R3 backend-optional API — CPU executables may not implement memory stats; zeros are the documented degraded value
        ma = None
    get = (lambda k: int(getattr(ma, k, 0) or 0)) if ma is not None \
        else (lambda k: 0)
    return {
        "argument_bytes": get("argument_size_in_bytes"),
        "output_bytes": get("output_size_in_bytes"),
        "temp_bytes": get("temp_size_in_bytes"),
        "generated_code_bytes": get("generated_code_size_in_bytes"),
    }


def compile_programs(programs, progress=None) -> list:
    entries = []
    for name, fn, args, kwargs in programs:
        t0 = time.perf_counter
        start = t0()
        compiled = fn.lower(*args, **kwargs).compile()
        dt = t0() - start
        entry = {"name": name, "compile_seconds": round(dt, 3)}
        entry.update(_memory_entry(compiled))
        entries.append(entry)
        if progress:
            progress(f"  {name}: {dt:.2f}s compile, "
                     f"temp {entry['temp_bytes'] / 2**20:.1f} MiB")
    return entries


def build_ledger(plan, mesh, params, cache, entries,
                 hbm_gib: float = V5E_HBM_GIB_PER_CHIP) -> dict:
    from aws_k8s_ansible_provisioner_tpu.parallel.sharding import (
        cache_pspecs, param_pspecs, pool_pspecs)

    capacity = int(hbm_gib * 2**30)
    pspecs = param_pspecs(plan.cfg, quant_weights=plan.weights_quant)
    params_bytes = _sharded_bytes(params, pspecs, mesh)
    kv_specs = pool_pspecs(plan.kv_quant) if plan.paged \
        else cache_pspecs(plan.kv_quant)
    kv_bytes = _sharded_bytes(cache, kv_specs, mesh)
    max_temp = max((e["temp_bytes"] for e in entries), default=0)
    total = params_bytes + kv_bytes + max_temp
    return {
        "capacity_bytes_per_chip": capacity,
        "params_bytes_per_chip": params_bytes,
        "kv_bytes_per_chip": kv_bytes,
        "max_temp_bytes": max_temp,
        "total_bytes": total,
        "headroom_bytes": capacity - total,
        "fit": total <= capacity,
        # Tier-2 KV (ISSUE 20): the host-RAM prefix-page budget the engine
        # will pin. Informational — host DRAM, NOT counted against the HBM
        # capacity above — but part of the fit story: a pod spec must
        # reserve it on top of the process's baseline RSS. Absent from
        # LEDGER_FIELDS so pre-tier manifests still verify.
        "host_tier_bytes": int(getattr(plan.serving,
                                       "kv_host_tier_bytes", 0))
        if plan.paged else 0,
    }


def verify_manifest(m: dict) -> None:
    """Schema check shared by tests, ``make aot-smoke``, and the engine's
    load path. Raises ValueError on any structural problem."""
    if m.get("schema") != MANIFEST_SCHEMA:
        raise ValueError(f"manifest schema {m.get('schema')!r} != "
                         f"{MANIFEST_SCHEMA!r}")
    for key in ("platform", "config", "programs", "hbm_ledger",
                "total_compile_seconds"):
        if key not in m:
            raise ValueError(f"manifest missing {key!r}")
    if not m["programs"]:
        raise ValueError("manifest has no programs")
    for p in m["programs"]:
        for f in PROGRAM_FIELDS:
            if f not in p:
                raise ValueError(f"program entry missing {f!r}: {p}")
    for f in LEDGER_FIELDS:
        if f not in m["hbm_ledger"]:
            raise ValueError(f"hbm_ledger missing {f!r}")


def build_manifest(cfg, serving, dp: int = 1, tp: int = 1,
                   devices=None, platform: str = "host",
                   topology: str = "", bblock: int = 1,
                   hbm_gib: float = V5E_HBM_GIB_PER_CHIP,
                   progress=None) -> dict:
    """Compile the full program set for (cfg, serving) over ``devices`` and
    return the manifest dict. ``devices`` defaults to the current backend's
    (the host-platform path)."""
    import jax

    if devices is None:
        devices = jax.devices()
    plan = ProgramPlan(cfg, serving, dp=dp, tp=tp)
    mesh = _mesh_for(devices, dp, tp) if dp * tp > 1 else None
    if mesh is not None and cfg.num_experts > 0 and cfg.moe_impl != "gshard":
        plan.cfg = cfg = cfg.scaled(moe_impl="gshard")  # engine mesh path
    params, cache = _abstract_state(plan, mesh)
    programs = enumerate_programs(plan, mesh, params, cache, bblock=bblock)
    if progress:
        progress(f"compiling {len(programs)} programs for "
                 f"{cfg.name} dp={dp} tp={tp} on {platform}...")
    entries = compile_programs(programs, progress=progress)
    ledger = build_ledger(plan, mesh, params, cache, entries,
                          hbm_gib=hbm_gib)
    manifest = {
        "schema": MANIFEST_SCHEMA,
        "platform": platform,
        "topology": topology,
        "jax_version": jax.__version__,
        "bblock": bblock,
        "config": plan.fingerprint(),
        "programs": entries,
        "hbm_ledger": ledger,
        "total_compile_seconds": round(
            sum(e["compile_seconds"] for e in entries), 3),
    }
    verify_manifest(manifest)
    return manifest


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _acquire_devices(args):
    """(devices, platform, topology): abstract TPU topology devices when
    libtpu imports (and --platform allows), else host-platform devices."""
    if args.platform in ("auto", "tpu"):
        try:
            import libtpu  # noqa: F401
            have_libtpu = True
        except ImportError:
            have_libtpu = False
        if have_libtpu:
            # Without the skip flag the topology lookup queries the GCE
            # metadata server and hangs (effectively) forever off-GCE.
            os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
            os.environ.setdefault("TPU_ACCELERATOR_TYPE", "v5litepod-8")
            os.environ.setdefault("TPU_WORKER_HOSTNAMES", "localhost")
            from jax.experimental import topologies

            topo = topologies.get_topology_desc(args.topology, "tpu")
            return list(topo.devices), "tpu", args.topology
        if args.platform == "tpu":
            raise RuntimeError("--platform tpu requires libtpu")
    import jax

    # Exactly dp*tp host devices: the persistent-cache key covers the
    # compile options (device count included), so an 8-device AOT run would
    # never produce cache hits for a single-device consumer engine.
    need = max(1, args.dp * args.tp)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={need}").strip()
    devices = jax.devices("cpu")
    return devices, "host", f"host:{len(devices)}"


def _resolve_model(name: str, serving):
    from aws_k8s_ansible_provisioner_tpu.config import (
        MODEL_REGISTRY, tiny_qwen3)

    if name in MODEL_REGISTRY:
        return MODEL_REGISTRY[name]
    if name == "tiny-qwen3":
        return tiny_qwen3()
    raise SystemExit(f"aot: unknown model {name!r}; registered: "
                     f"{sorted(MODEL_REGISTRY)} or tiny-qwen3")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m aws_k8s_ansible_provisioner_tpu.serving.aot",
        description="AOT-compile the full serving program set deviceless "
                    "and write the compile/HBM manifest.")
    ap.add_argument("--model", default="Qwen/Qwen3-8B")
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--tp", type=int, default=8)
    ap.add_argument("--topology", default="v5e:2x4",
                    help="jax.experimental.topologies descriptor")
    ap.add_argument("--platform", choices=("auto", "tpu", "host"),
                    default="auto")
    ap.add_argument("--out", default="",
                    help="manifest path (default: stdout)")
    ap.add_argument("--cache-dir", default="",
                    help="populate this persistent compilation cache "
                         "(what serve-time warmup then hits)")
    ap.add_argument("--hbm-gib", type=float, default=V5E_HBM_GIB_PER_CHIP,
                    help="per-chip HBM capacity for the fit verdict")
    ap.add_argument("--bblock", type=int, default=0,
                    help="decode batch block to compile (0: the config's "
                         "pin, else 1 — runtime autotune may still pick "
                         "another and warm-compile it)")
    ap.add_argument("--max-cache-len", type=int, default=0,
                    help="override ServingConfig.max_cache_len")
    ap.add_argument("--slots", type=int, default=0,
                    help="override ServingConfig.max_decode_slots")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    devices, platform, topology = _acquire_devices(args)

    import dataclasses

    import jax

    from aws_k8s_ansible_provisioner_tpu.config import ServingConfig

    if args.cache_dir:
        os.makedirs(args.cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", args.cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    serving = ServingConfig(model=args.model)
    overrides = {}
    if args.max_cache_len:
        overrides["max_cache_len"] = args.max_cache_len
    if args.slots:
        overrides["max_decode_slots"] = args.slots
    if overrides:
        serving = dataclasses.replace(serving, **overrides)
    cfg = _resolve_model(args.model, serving)
    bblock = args.bblock or (serving.decode_bblock
                             if serving.decode_bblock > 0 else 1)
    progress = None if args.quiet else \
        (lambda msg: print(msg, file=sys.stderr))
    manifest = build_manifest(cfg, serving, dp=args.dp, tp=args.tp,
                              devices=devices, platform=platform,
                              topology=topology, bblock=bblock,
                              hbm_gib=args.hbm_gib, progress=progress)
    text = json.dumps(manifest, indent=1)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text + "\n")
    else:
        print(text)
    ledger = manifest["hbm_ledger"]
    verdict = "FIT" if ledger["fit"] else "NO-FIT"
    print(f"aot: {len(manifest['programs'])} programs, "
          f"{manifest['total_compile_seconds']:.1f}s total compile "
          f"[{platform}/{topology}]; HBM {ledger['total_bytes'] / 2**30:.2f}"
          f" / {ledger['capacity_bytes_per_chip'] / 2**30:.0f} GiB per chip"
          f" -> {verdict}", file=sys.stderr)
    return 0 if ledger["fit"] else 2


if __name__ == "__main__":
    sys.exit(main())
