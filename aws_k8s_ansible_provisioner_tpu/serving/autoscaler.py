"""Fleet actuation: the self-scaling replica controller (ROADMAP item 4).

serving/capacity.py computes the complete scaling signal — offered load,
per-replica ceiling, a seconds-to-saturation forecast, and a replica
recommendation sized with headroom equal to the measured 5.5 s AOT
ready-time — but until this module nothing consumed it: under a ramp the
fleet shed at the knee instead of growing, and an idle fleet burned chips
instead of draining to zero (DeepServe, PAPERS.md: serverless LLM fleets
live or die on exactly this actuation loop). The controller closes it:

1. **Reconcile, don't command.** ``step()`` compares the committed target
   against the fleet recommendation (the router's ``/debug/capacity``
   aggregation by default; injectable for tests) and moves actual replica
   count toward it one deliberate action at a time. The clock is
   injectable (capacity/slo discipline) so every window below is
   exact-arithmetic testable.

2. **Scale-up admits only ready replicas.** New replicas come from a
   pluggable :class:`ReplicaLauncher` — in-process callables for tests and
   rehearse-local, a command template for kind/TPU — and enter rotation
   only after answering ``/readyz`` 200. A prewarmed STANDBY pool (size
   derived from the AOT manifest ready-time) is promoted first: promotion
   is instant, so the ready-time disappears from the scale-up latency.

3. **Scale-down is the PR 3 drain, never a kill.** The least-loaded
   replica leaves rotation, gets ``POST /admin/drain {"exit": false}``,
   and is reaped only at inflight==0 — zero non-2xx on surviving streams.
   A drain that never reaches zero is *stuck*: it is flagged, journaled,
   and finally escalated (force-reaped) by the reconcile path instead of
   wedging the controller behind one wedged replica.

4. **Scale-to-zero parks the fleet behind the router.** When
   ``min_replicas == 0`` and the fleet has been idle for
   ``idle_timeout_s``, the target drops to zero; the router answers the
   next ``/v1/*`` request by calling :meth:`Autoscaler.request_cold_start`
   and holding the request until a replica serves — AOT-backed, so the
   cold start costs the manifest ready-time, and a standby hides even
   that.

5. **Flap-proof by construction.** A target change must (a) persist for
   ``stable_s`` (hysteresis — one noisy forecast bucket proposes, it
   never commits) and (b) not reverse direction within ``cooldown_s`` of
   the previous commit (suppressed reversals are counted and journaled).
   Launch failures are classified transient/fatal with
   ``deploy.miniansible.classify_failure`` and retried on its
   deterministic capped backoff schedule — a quota blip retries, a bad
   manifest does not.

Every decision lands in the flight-recorder spool
(``autoscale_decision`` events) and the ``tpu_autoscale_*`` family
renders on BOTH /metrics routes, written only by
:meth:`Autoscaler.export` (tpulint R12 — the R11 contract extended to
this family).
"""

from __future__ import annotations

import http.client
import json
import logging
import math
import shlex
import subprocess
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from aws_k8s_ansible_provisioner_tpu.serving import chaos as _chaos
from aws_k8s_ansible_provisioner_tpu.serving import flightrec
from aws_k8s_ansible_provisioner_tpu.serving.metrics import (
    Counter, Gauge, Registry)

log = logging.getLogger("tpu_serve.autoscaler")

try:
    from deploy.miniansible import backoff_schedule, classify_failure
except ImportError:     # pragma: no cover - deploy/ not shipped beside the
    # serving package (minimal container): keep the controller importable
    # with the same *shape* of policy — no retry without the classifier
    # (an unrecognized error must stay fatal, same as miniansible's rule).
    def classify_failure(res: dict) -> Tuple[str, str]:
        return "fatal", str(res.get("msg") or "")[:300]

    def backoff_schedule(base: float, attempts: int, seed: str = "",
                         cap: Optional[float] = None) -> List[float]:
        cap = 60.0 if cap is None else cap
        return [min(base * (2.0 ** i), cap) for i in range(max(0, attempts))]


# Replica lifecycle states (ReplicaHandle.state).
LAUNCHING = "launching"   # spawned, waiting for /readyz
STANDBY = "standby"       # ready, parked OUT of rotation (prewarmed)
SERVING = "serving"       # ready and in the router pool
DRAINING = "draining"     # out of rotation, finishing in-flight work
STOPPED = "stopped"       # reaped (terminal; handle is dropped)

# Defaults. ready_s is the AOT manifest's measured ready-time
# (BENCH_coldstart_r01: 13.4 s cold -> 5.5 s AOT) — the quantity both the
# launch admission deadline and the auto standby size derive from.
DEFAULT_READY_S = 5.5
DEFAULT_INTERVAL_S = 1.0
DEFAULT_STABLE_S = 5.0
DEFAULT_COOLDOWN_S = 30.0
DEFAULT_IDLE_TIMEOUT_S = 120.0
DEFAULT_READY_TIMEOUT_S = 60.0
DEFAULT_DRAIN_STUCK_S = 45.0
DEFAULT_DRAIN_ESCALATE_S = 90.0
DEFAULT_LAUNCH_RETRIES = 3
DEFAULT_BACKOFF_BASE_S = 2.0
PROBE_TIMEOUT_S = 2.0


class AutoscaleMetrics:
    """The tpu_autoscale_* family. Registered here, rendered by BOTH
    /metrics routes, written only by Autoscaler.export() (tpulint R12).
    Monotone counts are exported as gauges set from the controller's
    internal counters — the single-writer discipline forbids inc() at the
    decision sites."""

    def __init__(self):
        r = Registry()
        self.registry = r
        self.desired_replicas = r.register(Gauge(
            "tpu_autoscale_desired_replicas",
            "Committed replica target (clamped recommendation after "
            "hysteresis + cooldown; 0 = parked / scale-to-zero)"))
        self.actual_replicas = r.register(Gauge(
            "tpu_autoscale_actual_replicas",
            "Replicas currently serving (ready AND in the router pool)"))
        self.standby_replicas = r.register(Gauge(
            "tpu_autoscale_standby_replicas",
            "Prewarmed ready replicas parked out of rotation (promoted "
            "before any launch on scale-up)"))
        self.launching_replicas = r.register(Gauge(
            "tpu_autoscale_launching_replicas",
            "Replicas spawned but not yet past /readyz (launch retries "
            "waiting out their backoff are counted separately)"))
        self.draining_replicas = r.register(Gauge(
            "tpu_autoscale_draining_replicas",
            "Replicas out of rotation finishing in-flight work before "
            "reap (inflight==0)"))
        self.stuck_replicas = r.register(Gauge(
            "tpu_autoscale_stuck_replicas",
            "Draining replicas past drain_stuck_s with inflight still "
            "nonzero — flagged and finally escalated, never wedging the "
            "controller"))
        self.scale_ups = r.register(Gauge(
            "tpu_autoscale_scale_ups",
            "Committed upward target changes since start (monotone count "
            "exported as a gauge: tpulint R12 single-writer discipline)"))
        self.scale_downs = r.register(Gauge(
            "tpu_autoscale_scale_downs",
            "Committed downward target changes since start (monotone "
            "count exported as a gauge)"))
        self.launch_failures = r.register(Gauge(
            "tpu_autoscale_launch_failures",
            "Replica launch failures by miniansible classification "
            "(transient = retried on the deterministic backoff schedule; "
            "fatal = abandoned)", ("class",)))
        self.cold_starts = r.register(Gauge(
            "tpu_autoscale_cold_starts",
            "Requests that found a parked fleet and triggered the "
            "AOT-backed cold-start path (monotone count)"))
        self.flaps_suppressed = r.register(Gauge(
            "tpu_autoscale_flaps_suppressed",
            "Direction reversals blocked by the cooldown window "
            "(monotone count; a noisy forecast proposes, it never flaps)"))
        self.last_decision_age_s = r.register(Gauge(
            "tpu_autoscale_last_decision_age_s",
            "Seconds since the controller last journaled a decision "
            "(-1 = no decision yet)"))
        self.autoscale_export_drops = r.register(Counter(
            "tpu_autoscale_export_drops_total",
            "Gauge refreshes dropped because status() raised "
            "(drop-not-fail: the /metrics render proceeds with stale "
            "values)"))


metrics = AutoscaleMetrics()


# ---------------------------------------------------------------------------
# Launchers: how a replica process comes to exist / stops existing.
# ---------------------------------------------------------------------------


class ReplicaLauncher:
    """Pluggable replica factory. ``launch()`` returns ``(addr, opaque)``
    — the ``host:port`` the replica will answer on plus whatever handle
    ``terminate`` needs to reap it. ``launch`` may raise: the controller
    classifies the failure transient/fatal and applies the deterministic
    backoff policy. ``terminate`` must be idempotent and never raise into
    the controller (best-effort reaping)."""

    def launch(self) -> Tuple[str, object]:
        raise NotImplementedError

    def terminate(self, addr: str, opaque: object) -> None:
        raise NotImplementedError


class CallableLauncher(ReplicaLauncher):
    """In-process launcher for tests and rehearse-local: ``spawn()``
    returns ``(addr, opaque)`` (e.g. a server thread + stop event),
    ``stop(addr, opaque)`` tears it down."""

    def __init__(self, spawn: Callable[[], Tuple[str, object]],
                 stop: Optional[Callable[[str, object], None]] = None):
        self._spawn = spawn
        self._stop = stop

    def launch(self) -> Tuple[str, object]:
        return self._spawn()

    def terminate(self, addr: str, opaque: object) -> None:
        if self._stop is not None:
            self._stop(addr, opaque)


class CommandLauncher(ReplicaLauncher):
    """Subprocess launcher for kind/TPU: formats ``template`` with a
    freshly-allocated ``{port}`` (and ``{host}``), Popens it, and reaps
    with SIGTERM -> SIGKILL. The template comes from the deploy manifest
    (serving.yaml.j2's router ``--autoscale-launch-cmd``), so the replica
    command line is single-sourced with the Deployment's own."""

    def __init__(self, template: str, host: str = "127.0.0.1"):
        if "{port}" not in template:
            raise ValueError("launch command template must contain {port}")
        self.template = template
        self.host = host

    @staticmethod
    def _free_port() -> int:
        import socket
        s = socket.socket()
        try:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]
        finally:
            s.close()

    def launch(self) -> Tuple[str, object]:
        port = self._free_port()
        cmd = self.template.format(port=port, host=self.host)
        proc = subprocess.Popen(shlex.split(cmd),
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
        return f"{self.host}:{port}", proc

    def terminate(self, addr: str, opaque: object) -> None:
        if opaque is None:
            return
        try:
            opaque.terminate()
            try:
                opaque.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                opaque.kill()
                opaque.wait(timeout=5.0)
        except Exception:   # tpulint: disable=R3 best-effort reap — a zombie child must not wedge the reconcile tick; the next tick retries nothing (the handle is gone) and the OS owns the orphan
            log.warning("terminate of %s failed", addr, exc_info=True)


class ReplicaHandle:
    """One replica the controller knows about. ``opaque`` is the
    launcher's reap handle (None for adopted replicas the controller did
    not launch — those are drained but never terminated)."""

    __slots__ = ("addr", "state", "purpose", "opaque", "t_launched",
                 "t_ready", "t_drain", "stuck", "seed", "attempts")

    def __init__(self, addr: str, state: str, purpose: str = "serving",
                 opaque: object = None, t_launched: float = 0.0,
                 seed: str = "", attempts: int = 0):
        self.addr = addr
        self.state = state
        self.purpose = purpose      # "serving" | "standby"
        self.opaque = opaque
        self.t_launched = t_launched
        self.t_ready = 0.0
        self.t_drain = 0.0
        self.stuck = False
        self.seed = seed
        self.attempts = attempts


# -- default HTTP probes (overridable for FakeClock unit tests) -------------


def _get_json(addr: str, path: str) -> Tuple[int, dict]:
    host, _, port = addr.rpartition(":")
    conn = http.client.HTTPConnection(host, int(port),
                                      timeout=PROBE_TIMEOUT_S)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        body = resp.read()
        try:
            d = json.loads(body)
        except ValueError:
            d = {}
        return resp.status, d if isinstance(d, dict) else {}
    finally:
        conn.close()


def default_ready(addr: str) -> bool:
    """/readyz 200 = admittable. Anything else (503 warming/draining,
    connect refused while the process boots) = not yet."""
    try:
        status, _ = _get_json(addr, "/readyz")
        return status == 200
    except OSError:
        return False


def default_inflight(addr: str) -> int:
    """/healthz ``inflight`` (the JSON rides 503 answers too). A replica
    that stopped answering holds nothing — 0, so the reap proceeds."""
    try:
        _, d = _get_json(addr, "/healthz")
        return max(0, int(d.get("inflight") or 0))
    except (OSError, ValueError, TypeError):
        return 0


def default_drain(addr: str) -> bool:
    """POST /admin/drain {"exit": false} — the PR 3 rotation-removal
    drain: the replica sheds new admissions (router re-routes) and
    finishes in-flight work; the controller reaps it at inflight==0."""
    host, _, port = addr.rpartition(":")
    body = json.dumps({"exit": False}).encode()
    conn = http.client.HTTPConnection(host, int(port),
                                      timeout=PROBE_TIMEOUT_S)
    try:
        conn.request("POST", "/admin/drain", body=body,
                     headers={"Content-Type": "application/json"})
        return conn.getresponse().status == 200
    except OSError:
        return False
    finally:
        conn.close()


class Autoscaler:
    """Reconciliation controller: fleet recommendation -> replica count.

    All shared state is guarded by ``self._lock``; probe/launcher/pool
    I/O happens strictly outside it (locksan: no autoscaler lock is ever
    held across a network call or a pool lock acquisition). One ``step``
    runs at a time (``_step_lock``) whether driven by the background
    runner or a test calling it directly."""

    def __init__(self, enabled: bool = False,
                 min_replicas: int = 1, max_replicas: int = 8,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 stable_s: float = DEFAULT_STABLE_S,
                 cooldown_s: float = DEFAULT_COOLDOWN_S,
                 idle_timeout_s: float = DEFAULT_IDLE_TIMEOUT_S,
                 ready_timeout_s: float = DEFAULT_READY_TIMEOUT_S,
                 drain_stuck_s: float = DEFAULT_DRAIN_STUCK_S,
                 drain_escalate_s: float = DEFAULT_DRAIN_ESCALATE_S,
                 launch_retries: int = DEFAULT_LAUNCH_RETRIES,
                 backoff_base_s: float = DEFAULT_BACKOFF_BASE_S,
                 standby: int = -1,
                 ready_s: float = DEFAULT_READY_S,
                 clock: Callable[[], float] = time.monotonic):
        self.enabled = bool(enabled)
        self.min_replicas = max(0, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas), 1)
        self.interval_s = max(0.05, float(interval_s))
        self.stable_s = max(0.0, float(stable_s))
        self.cooldown_s = max(0.0, float(cooldown_s))
        self.idle_timeout_s = max(0.0, float(idle_timeout_s))
        self.ready_timeout_s = max(0.1, float(ready_timeout_s))
        self.drain_stuck_s = max(0.1, float(drain_stuck_s))
        self.drain_escalate_s = max(self.drain_stuck_s,
                                    float(drain_escalate_s))
        self.launch_retries = max(0, int(launch_retries))
        self.backoff_base_s = max(0.0, float(backoff_base_s))
        self.standby = int(standby)     # -1 = auto from ready_s
        self.ready_s = max(0.0, float(ready_s))
        self.clock = clock
        self._lock = threading.Lock()
        self._step_lock = threading.Lock()
        # wiring (install()/configure() carry these across reconfigures)
        self.pool = None                            # router.BackendPool
        self.launcher: Optional[ReplicaLauncher] = None
        self._ready_fn: Callable[[str], bool] = default_ready
        self._inflight_fn: Callable[[str], int] = default_inflight
        self._drain_fn: Callable[[str], bool] = default_drain
        self._recommend_fn: Optional[Callable[[], dict]] = None
        # fleet state
        self._replicas: Dict[str, ReplicaHandle] = {}
        self._pending: List[dict] = []      # launches waiting out backoff
        self._seq = 0
        # decision state
        self._target: Optional[int] = None
        self._proposal: Optional[int] = None
        self._proposal_dir = 0
        self._proposal_since = 0.0
        self._last_dir = 0
        self._last_scale_t = 0.0
        self._flap_counted = False
        self._idle_since: Optional[float] = None
        self._cold_pending = False
        # monotone counts (exported as gauges by export() — R12)
        self._n_scale_ups = 0
        self._n_scale_downs = 0
        self._n_launch_failures = {"transient": 0, "fatal": 0}
        self._n_cold_starts = 0
        self._n_flaps_suppressed = 0
        self._last_decision = ""
        self._last_decision_t: Optional[float] = None
        # runner
        self._thread: Optional[threading.Thread] = None
        self._stop_ev = threading.Event()
        self._wake = threading.Event()
        self._serving_ev = threading.Event()

    # -- wiring --------------------------------------------------------------

    def install(self, pool=None, launcher: Optional[ReplicaLauncher] = None,
                ready_fn: Optional[Callable[[str], bool]] = None,
                inflight_fn: Optional[Callable[[str], int]] = None,
                drain_fn: Optional[Callable[[str], bool]] = None,
                recommend_fn: Optional[Callable[[], dict]] = None):
        """Attach the router pool, the launcher, and (tests) probe
        overrides. Call before start()."""
        with self._lock:
            if pool is not None:
                self.pool = pool
            if launcher is not None:
                self.launcher = launcher
            if ready_fn is not None:
                self._ready_fn = ready_fn
            if inflight_fn is not None:
                self._inflight_fn = inflight_fn
            if drain_fn is not None:
                self._drain_fn = drain_fn
            if recommend_fn is not None:
                self._recommend_fn = recommend_fn
        return self

    def adopt(self, addr: str):
        """Register a replica that already exists (the pool's initial
        static backends): it counts toward actual, can be drained on
        scale-down, but is never terminated (opaque=None — the controller
        did not launch it, so it only ever drains it)."""
        with self._lock:
            if addr not in self._replicas:
                self._replicas[addr] = ReplicaHandle(addr, SERVING)
                self._serving_ev.set()

    # -- runner --------------------------------------------------------------

    def start(self):
        """Spawn the background reconcile loop (idempotent)."""
        if not self.enabled:
            return self
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            t = threading.Thread(target=self._run, daemon=True,
                                 name="tpu-autoscaler")
            self._thread = t
        t.start()
        return self

    def stop(self, timeout_s: float = 2.0):
        self._stop_ev.set()
        self._wake.set()
        with self._lock:
            t = self._thread
            self._thread = None
        if t is not None and t.is_alive():
            t.join(timeout=timeout_s)

    def _run(self):
        while not self._stop_ev.is_set():
            try:
                self.step()
            except Exception:   # tpulint: disable=R3 controller survival — one broken tick (probe typo, launcher bug) must not kill the reconcile loop; the decision journal carries the evidence
                log.warning("autoscaler step failed", exc_info=True)
            if self._wake.wait(self.interval_s):
                self._wake.clear()

    # -- cold start (router request path) ------------------------------------

    def request_cold_start(self, timeout_s: float = 30.0) -> bool:
        """A request arrived and the pool is empty: unpark the fleet and
        wait (bounded) for a replica to serve. Returns True when one is
        serving. Counted once per triggering request."""
        if not self.enabled:
            return False
        with self._lock:
            if any(h.state == SERVING for h in self._replicas.values()):
                return True
            self._cold_pending = True
            self._n_cold_starts += 1
        self._serving_ev.clear()
        self._wake.set()
        ok = self._serving_ev.wait(timeout_s)
        with self._lock:
            self._cold_pending = False
        return ok

    # -- the reconcile tick --------------------------------------------------

    def step(self, now: Optional[float] = None):
        """One reconcile pass. Deliberately non-blocking-ish: every probe
        is one bounded HTTP call, launches are spawned (not awaited), and
        drains are polled — a stuck anything surfaces as state, never as
        a wedged controller."""
        if not self.enabled:
            return
        with self._step_lock:
            now = self.clock() if now is None else now
            self._progress_launches(now)
            self._progress_drains(now)
            self._retry_pending(now)
            self._reconcile(now)
            self._maintain_standby(now)

    # launch admission ------------------------------------------------------

    def _progress_launches(self, now: float):
        with self._lock:
            launching = [h for h in self._replicas.values()
                         if h.state == LAUNCHING]
        for h in launching:
            try:
                ready = bool(self._ready_fn(h.addr))
            except Exception:   # tpulint: disable=R3 probe-error = not-ready — a flaky /readyz poll just defers admission to the next tick; the ready_timeout_s deadline owns the give-up
                ready = False
            if ready:
                self._admit(h, now)
            elif now - h.t_launched >= self.ready_timeout_s:
                self._terminate(h)
                with self._lock:
                    self._replicas.pop(h.addr, None)
                self._launch_failed(
                    h.purpose, h.seed, h.attempts,
                    f"replica {h.addr} timed out waiting for /readyz "
                    f"({self.ready_timeout_s:.0f}s)", now)

    def _admit(self, h: ReplicaHandle, now: float):
        with self._lock:
            h.t_ready = now
            h.state = STANDBY if h.purpose == "standby" else SERVING
            state = h.state
        if state == SERVING:
            self._pool_add(h.addr)
            self._serving_ev.set()
        self._journal(now, "replica_ready", addr=h.addr, state=state,
                      ready_wait_s=round(now - h.t_launched, 3))

    # drain lifecycle -------------------------------------------------------

    def _progress_drains(self, now: float):
        with self._lock:
            draining = [h for h in self._replicas.values()
                        if h.state == DRAINING]
        ch = _chaos.get()
        for h in draining:
            if ch.on_autoscale_drain(h.addr):
                inflight = 1    # injected wedge: streams never finish
            else:
                try:
                    inflight = int(self._inflight_fn(h.addr))
                except Exception:   # tpulint: disable=R3 a dead replica holds no streams — probe failure reads 0 and the reap proceeds
                    inflight = 0
            if inflight <= 0:
                self._reap(h, now, "drained")
            elif not h.stuck and now - h.t_drain >= self.drain_stuck_s:
                with self._lock:
                    h.stuck = True
                self._journal(now, "drain_stuck", addr=h.addr,
                              inflight=inflight,
                              draining_s=round(now - h.t_drain, 3))
            elif h.stuck and now - h.t_drain >= self.drain_escalate_s:
                self._journal(now, "drain_escalated", addr=h.addr,
                              inflight=inflight,
                              draining_s=round(now - h.t_drain, 3))
                self._reap(h, now, "drain_escalated")

    def _reap(self, h: ReplicaHandle, now: float, reason: str):
        self._terminate(h)
        with self._lock:
            h.state = STOPPED
            self._replicas.pop(h.addr, None)
            if not any(x.state == SERVING for x in self._replicas.values()):
                self._serving_ev.clear()
        if reason == "drained":
            self._journal(now, "drained", addr=h.addr,
                          drain_s=round(now - h.t_drain, 3))

    def _terminate(self, h: ReplicaHandle):
        if h.opaque is None or self.launcher is None:
            return      # adopted replica: drained, never killed
        try:
            self.launcher.terminate(h.addr, h.opaque)
        except Exception:   # tpulint: disable=R3 best-effort reap — launcher bugs must not wedge the tick; the handle is dropped either way
            log.warning("launcher.terminate(%s) failed", h.addr,
                        exc_info=True)

    # launch + failure policy -----------------------------------------------

    def _retry_pending(self, now: float):
        with self._lock:
            due = [p for p in self._pending if now >= p["next_t"]]
            self._pending = [p for p in self._pending if now < p["next_t"]]
        for p in due:
            self._do_launch(p["purpose"], now, seed=p["seed"],
                            attempts=p["attempts"])

    def _do_launch(self, purpose: str, now: float, seed: str = "",
                   attempts: int = 0):
        if self.launcher is None:
            return
        if not seed:
            with self._lock:
                self._seq += 1
                seed = f"{purpose}-{self._seq}"
        try:
            _chaos.get().on_autoscale_launch()
            addr, opaque = self.launcher.launch()
        except Exception as e:  # tpulint: disable=R3 classified, not swallowed — miniansible.classify_failure decides transient (deterministic backoff retry) vs fatal (journaled give-up)
            self._launch_failed(purpose, seed, attempts, str(e), now)
            return
        h = ReplicaHandle(addr, LAUNCHING, purpose=purpose, opaque=opaque,
                          t_launched=now, seed=seed, attempts=attempts)
        with self._lock:
            self._replicas[addr] = h
        self._journal(now, "launch", addr=addr, purpose=purpose,
                      attempt=attempts + 1)

    def _launch_failed(self, purpose: str, seed: str, attempts: int,
                       msg: str, now: float):
        cls, reason = classify_failure({"msg": msg})
        with self._lock:
            self._n_launch_failures[cls] = \
                self._n_launch_failures.get(cls, 0) + 1
        attempts += 1
        if cls == "transient" and attempts <= self.launch_retries:
            delay = backoff_schedule(self.backoff_base_s, attempts,
                                     seed=seed)[attempts - 1]
            with self._lock:
                self._pending.append({"purpose": purpose, "seed": seed,
                                      "attempts": attempts,
                                      "next_t": now + delay})
            self._journal(now, "launch_retry", purpose=purpose,
                          attempt=attempts, delay_s=delay, reason=reason)
        else:
            self._journal(now, "launch_failed", purpose=purpose,
                          attempts=attempts, classification=cls,
                          reason=reason)

    # the decision ----------------------------------------------------------

    def _recommend(self) -> dict:
        """Fleet recommendation + offered load. Default source is the
        router's /debug/capacity aggregation over the pool's poller
        samples; tests inject a forecast directly."""
        if self._recommend_fn is not None:
            return dict(self._recommend_fn() or {})
        if self.pool is None:
            return {}
        from aws_k8s_ansible_provisioner_tpu.serving import router
        return dict(router._fleet_capacity(self.pool.fleet())["fleet"])

    def _reconcile(self, now: float):
        try:
            rec = self._recommend()
        except Exception:   # tpulint: disable=R3 no-signal = no-change — a broken recommendation source holds the current target rather than scaling on garbage
            rec = {}
        with self._lock:
            serving = sum(1 for h in self._replicas.values()
                          if h.state == SERVING)
            launching = sum(1 for h in self._replicas.values()
                            if h.state == LAUNCHING
                            and h.purpose == "serving")
            pending = sum(1 for p in self._pending
                          if p["purpose"] == "serving")
            cold = self._cold_pending
        current = serving + launching + pending
        reporting = int(rec.get("reporting_replicas") or 0)
        offered = float(rec.get("offered_tps") or 0.0)
        raw = rec.get("recommended_replicas")

        # idle tracking (scale-to-zero): offered load is the busy signal;
        # a fleet with no reporting replicas (parked) stays idle.
        with self._lock:
            if offered > 1e-9:
                self._idle_since = None
            elif self._idle_since is None:
                self._idle_since = now
            idle_for = now - self._idle_since \
                if self._idle_since is not None else 0.0
            if self._target is None:
                # bootstrap: adopt what exists, floored at min_replicas
                self._target = min(self.max_replicas,
                                   max(current, self.min_replicas))
            target = self._target

        if raw is None or (reporting == 0 and current == 0):
            # no signal (parked or poller not warm): hold the target
            desired = target
        else:
            desired = min(self.max_replicas,
                          max(self.min_replicas, int(raw)))
        if self.min_replicas == 0 and not cold:
            if current == 0:
                desired = 0     # parked stays parked until a request
            elif idle_for >= self.idle_timeout_s:
                desired = 0     # scale-to-zero: idle past the window
        if cold:
            desired = max(desired, 1, self.min_replicas)

        self._decide(now, desired, cold)
        self._actuate(now)

    def _decide(self, now: float, desired: int, cold: bool):
        events = []
        with self._lock:
            target = self._target
            if cold and target < 1:
                self._target = max(1, self.min_replicas)
                self._last_dir, self._last_scale_t = 1, now
                self._n_scale_ups += 1
                self._proposal = None
                events.append(("cold_start",
                               {"from": target, "to": self._target}))
            elif desired == target:
                self._proposal = None
                self._proposal_dir = 0
                self._flap_counted = False
            else:
                d = 1 if desired > target else -1
                if self._proposal is None or self._proposal_dir != d:
                    # new proposal (or direction flip): hysteresis window
                    # restarts — one noisy bucket never commits
                    self._proposal_dir = d
                    self._proposal_since = now
                    self._flap_counted = False
                self._proposal = desired
                if now - self._proposal_since + 1e-9 >= self.stable_s:
                    blocked = (self._last_dir != 0 and d != self._last_dir
                               and now - self._last_scale_t
                               < self.cooldown_s)
                    if blocked:
                        if not self._flap_counted:
                            self._n_flaps_suppressed += 1
                            self._flap_counted = True
                            events.append(("flap_suppressed", {
                                "from": target, "to": desired,
                                "cooldown_left_s": round(
                                    self.cooldown_s
                                    - (now - self._last_scale_t), 3)}))
                    else:
                        self._target = desired
                        self._last_dir = d
                        self._last_scale_t = now
                        self._proposal = None
                        self._proposal_dir = 0
                        if d > 0:
                            self._n_scale_ups += 1
                        else:
                            self._n_scale_downs += 1
                        events.append(("scale_up" if d > 0 else "scale_down",
                                       {"from": target, "to": desired}))
        for decision, data in events:
            self._journal(now, decision, **data)

    # actuation -------------------------------------------------------------

    def _actuate(self, now: float):
        with self._lock:
            target = self._target or 0
            serving = [h for h in self._replicas.values()
                       if h.state == SERVING]
            standby = [h for h in self._replicas.values()
                       if h.state == STANDBY]
            launching = sum(1 for h in self._replicas.values()
                            if h.state == LAUNCHING
                            and h.purpose == "serving")
            pending = sum(1 for p in self._pending
                          if p["purpose"] == "serving")
        current = len(serving) + launching + pending
        if current < target:
            need = target - current
            # standby promotion first: the ready-time has already been
            # paid, so the scale-up is one pool insert
            for h in standby[:need]:
                with self._lock:
                    h.state = SERVING
                    h.purpose = "serving"
                self._pool_add(h.addr)
                self._serving_ev.set()
                self._journal(now, "promote_standby", addr=h.addr)
                need -= 1
            for _ in range(need):
                self._do_launch("serving", now)
        elif current > target and serving:
            # one drain per tick: gradual, and each drain immediately
            # lowers ``current`` so the next tick re-evaluates
            victim = self._least_loaded(serving)
            with self._lock:
                victim.state = DRAINING
                victim.t_drain = now
            self._pool_remove(victim.addr)
            try:
                drained = bool(self._drain_fn(victim.addr))
            except Exception:   # tpulint: disable=R3 drain-POST failure = replica already gone — the inflight probe (reads 0) reaps it on the next tick
                drained = False
            self._journal(now, "drain", addr=victim.addr,
                          accepted=drained, target=target)

    def _least_loaded(self, serving: List[ReplicaHandle]) -> ReplicaHandle:
        """Scale-down victim: fewest in-flight streams (pool /load sample
        when fresh, else a direct /healthz probe). Ties break on address
        for determinism."""
        loads = {}
        if self.pool is not None:
            try:
                fl = self.pool.fleet()
                loads = {a: e.get("load") for a, e in fl.items()
                         if isinstance(e, dict) and e.get("load") is not None}
            except Exception:   # tpulint: disable=R3 a broken pool view falls back to direct probes below
                loads = {}

        def score(h: ReplicaHandle):
            s = loads.get(h.addr)
            if s is None:
                try:
                    s = int(self._inflight_fn(h.addr))
                except Exception:   # tpulint: disable=R3 unprobeable = idle — an unreachable replica is the cheapest one to drain
                    s = 0
            return (s, h.addr)

        return min(serving, key=score)

    # standby pool ----------------------------------------------------------

    def standby_target(self) -> int:
        """Prewarmed pool size. Auto (-1) derives from the AOT manifest
        ready-time: enough standbys that one promotion covers one
        ready-time of launch latency — ceil(ready_s / ready_s) = 1 for
        any nonzero ready-time (0 when cold start is free)."""
        if self.standby >= 0:
            return self.standby
        return int(math.ceil(self.ready_s / max(self.ready_s, 1e-9))) \
            if self.ready_s > 0 else 0

    def _maintain_standby(self, now: float):
        want = self.standby_target()
        with self._lock:
            standby = [h for h in self._replicas.values()
                       if h.state == STANDBY]
            warming = sum(1 for h in self._replicas.values()
                          if h.state == LAUNCHING
                          and h.purpose == "standby")
            pending = sum(1 for p in self._pending
                          if p["purpose"] == "standby")
            total = len(self._replicas) + len(self._pending)
        have = len(standby) + warming + pending
        if have < want and total < self.max_replicas + want:
            self._do_launch("standby", now)
        elif len(standby) > want:
            # shrink: standbys hold no streams — reap directly
            extra = sorted(standby, key=lambda h: h.addr)[want:]
            for h in extra:
                self._reap(h, now, "standby_shrunk")
                self._journal(now, "standby_shrunk", addr=h.addr)

    # pool plumbing ---------------------------------------------------------

    def _pool_add(self, addr: str):
        if self.pool is None:
            return
        try:
            self.pool.add_backend(addr)
        except Exception:   # tpulint: disable=R3 pool insert failure is journaled via the missing replica_ready effect; the next tick re-admits
            log.warning("pool.add_backend(%s) failed", addr, exc_info=True)

    def _pool_remove(self, addr: str):
        if self.pool is None:
            return
        try:
            self.pool.remove_backend(addr)
        except Exception:   # tpulint: disable=R3 pool removal failure still drains the replica; the poller's draining recognition removes it from rotation anyway
            log.warning("pool.remove_backend(%s) failed", addr,
                        exc_info=True)

    # journal / status / export ----------------------------------------------

    def _journal(self, now: float, decision: str, **data):
        with self._lock:
            self._last_decision = decision
            self._last_decision_t = now
        try:
            flightrec.record("autoscale_decision", None,
                             decision=decision, **data)
        except Exception:   # tpulint: disable=R3 the recorder drops-not-fails internally already; a broken recorder must not fail a scaling action either
            pass
        log.info("autoscale %s %s", decision, data)

    def status(self, now: Optional[float] = None) -> dict:
        """The /debug/autoscale document (tputop + probes render this)."""
        now = self.clock() if now is None else now
        with self._lock:
            by_state: Dict[str, int] = {}
            for h in self._replicas.values():
                by_state[h.state] = by_state.get(h.state, 0) + 1
            stuck = sum(1 for h in self._replicas.values() if h.stuck)
            target = self._target
            age = (now - self._last_decision_t) \
                if self._last_decision_t is not None else -1.0
            return {
                "enabled": self.enabled,
                "desired": target if target is not None
                else self.min_replicas,
                "actual": by_state.get(SERVING, 0),
                "launching": by_state.get(LAUNCHING, 0),
                "standby": by_state.get(STANDBY, 0),
                "draining": by_state.get(DRAINING, 0),
                "stuck": stuck,
                "pending_launches": len(self._pending),
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "standby_target": self.standby_target(),
                "parked": (target == 0
                           and by_state.get(SERVING, 0) == 0),
                "cold_start_pending": self._cold_pending,
                "scale_ups": self._n_scale_ups,
                "scale_downs": self._n_scale_downs,
                "launch_failures": dict(self._n_launch_failures),
                "cold_starts": self._n_cold_starts,
                "flaps_suppressed": self._n_flaps_suppressed,
                "last_decision": self._last_decision,
                "last_decision_age_s": round(age, 3),
            }

    def export(self) -> Optional[dict]:
        """Refresh every tpu_autoscale_* gauge — the single writer site
        for the family (tpulint R12). Both /metrics routes call this
        right before rendering; a raise is swallowed and counted
        (drop-not-fail)."""
        try:
            st = self.status()
            metrics.desired_replicas.set(float(st["desired"]))
            metrics.actual_replicas.set(float(st["actual"]))
            metrics.standby_replicas.set(float(st["standby"]))
            metrics.launching_replicas.set(float(st["launching"]))
            metrics.draining_replicas.set(float(st["draining"]))
            metrics.stuck_replicas.set(float(st["stuck"]))
            metrics.scale_ups.set(float(st["scale_ups"]))
            metrics.scale_downs.set(float(st["scale_downs"]))
            lf = st["launch_failures"]
            metrics.launch_failures.set(float(lf.get("transient", 0)),
                                        **{"class": "transient"})
            metrics.launch_failures.set(float(lf.get("fatal", 0)),
                                        **{"class": "fatal"})
            metrics.cold_starts.set(float(st["cold_starts"]))
            metrics.flaps_suppressed.set(float(st["flaps_suppressed"]))
            metrics.last_decision_age_s.set(st["last_decision_age_s"])
            return st
        except Exception:   # tpulint: disable=R3 drop-by-design — the controller can never fail a /metrics render; the drop is itself counted
            metrics.autoscale_export_drops.inc()
            return None


# ---------------------------------------------------------------------------
# Module-level wiring: one controller per process (the capacity pattern).
# ---------------------------------------------------------------------------

_controller: Optional[Autoscaler] = None
_controller_lock = threading.Lock()


def get() -> Autoscaler:
    global _controller
    with _controller_lock:
        if _controller is None:
            _controller = Autoscaler()
        return _controller


def configure(**kw) -> Autoscaler:
    """Swap in a freshly-configured controller, carrying over the wiring
    (pool, launcher, probe overrides) the previous instance held, and
    stopping its runner thread."""
    global _controller
    with _controller_lock:
        old = _controller
        _controller = Autoscaler(**kw)
        if old is not None:
            old.stop()
            _controller.pool = old.pool
            _controller.launcher = old.launcher
            _controller._ready_fn = old._ready_fn
            _controller._inflight_fn = old._inflight_fn
            _controller._drain_fn = old._drain_fn
            _controller._recommend_fn = old._recommend_fn
        return _controller


def reset() -> Autoscaler:
    global _controller
    with _controller_lock:
        old = _controller
        _controller = Autoscaler()
    if old is not None:
        old.stop()
    return _controller
