"""Declarative SLOs with Google-SRE multi-window burn rates (5m / 1h).

ROADMAP item 4 (serverless autoscaling, per DeepServe) needs a scaling signal
built from queue-depth/shed-rate/p95 — this module makes that signal a proper
SLO computation instead of ad-hoc threshold checks scattered through an
autoscaler loop. Four objectives over sliding windows:

==============  ==========================================================
objective       bad event (counts against the error budget)
==============  ==========================================================
``ttft_p95``    a first token slower than the TTFT target (budget 5%)
``e2e_p95``     an end-to-end latency above the e2e target (budget 5%)
``error_rate``  a request finishing "error"/"timeout" (budget = config)
``shed_rate``   a submission shed at admission (budget = config)
==============  ==========================================================

The burn rate is the SRE-book definition: (observed bad fraction in the
window) / (budget fraction). 1.0 = burning exactly the budget; 14.4 on the
5m window is the classic page-now threshold. Two windows (5m, 1h) give the
fast-burn/slow-burn pair; both export as
``tpu_serve_slo_burn_rate{objective,window}`` gauges and surface on
``/healthz`` for the router's fleet view and the L3 reconcile probe.

Everything is computed from ``time.monotonic()`` through an injectable clock,
so seeded tests assert exact burn values with a fake clock — no sleeps, no
flakes. Observation is O(1) append under a short lock; the burn computation
walks at most the window's samples at query time (observability reads pay,
request paths don't).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Deque, Dict, Optional, Tuple

from aws_k8s_ansible_provisioner_tpu.serving.metrics import Gauge, Registry

# (label, seconds) — the SRE fast/slow burn pair.
WINDOWS: Tuple[Tuple[str, float], ...] = (("5m", 300.0), ("1h", 3600.0))


def trim_window(dq, now: float, window_s: float) -> None:
    """Drop samples older than ``now - window_s`` off a time-ordered deque
    of ``(t, ...)`` tuples. The one trimming discipline every windowed
    accumulator in serving/ shares (this engine's burn windows, devmon's
    attribution window) — samples age out on WRITE and READ, so an idle
    window drains to empty instead of freezing its last value."""
    horizon = now - window_s
    while dq and dq[0][0] < horizon:
        dq.popleft()

# Terminal statuses that burn the error budget ("cancelled" is the client
# hanging up — their choice, not our failure).
BAD_STATUSES = ("error", "timeout")


class SLOMetrics:
    """The SLO engine's gauge set, rendered by BOTH the engine's and the
    router's /metrics routes (the burn rate is the fleet-level signal; the
    router aggregates it without scraping every replica twice)."""

    def __init__(self):
        self.registry = Registry()
        self.burn_rate = self.registry.register(Gauge(
            "tpu_serve_slo_burn_rate",
            "SLO error-budget burn rate per objective and window "
            "(1.0 = burning exactly the budget; >1 = on track to exhaust it)",
            ("objective", "window")))


# Process-wide: the engine(s) and both /metrics routes share these.
metrics = SLOMetrics()


class Objective:
    """One declarative objective: a latency target or a bad-event ratio."""

    __slots__ = ("name", "target_s", "budget")

    def __init__(self, name: str, budget: float,
                 target_s: Optional[float] = None):
        self.name = name
        self.target_s = target_s        # None for pure ratio objectives
        self.budget = max(1e-9, float(budget))


class SLOEngine:
    """Sliding-window burn-rate computation over the four objectives.

    ``clock`` defaults to ``time.monotonic`` and is injectable so tests
    drive exact timelines. Samples are ``(t, bad)`` pairs in per-objective
    deques, trimmed past the longest window on append; burn rates are
    computed at query time, so two calls at the same (fake) clock reading
    return identical values — the determinism contract the seeded tests
    assert.
    """

    MAX_SAMPLES = 100_000   # hard memory bound per objective (drop-oldest)

    def __init__(self, ttft_p95_ms: float = 0.0, e2e_p95_ms: float = 0.0,
                 error_rate: float = 0.01, shed_rate: float = 0.05,
                 enabled: bool = True,
                 clock: Callable[[], float] = time.monotonic):
        self.enabled = bool(enabled)
        self.clock = clock
        self.objectives: Dict[str, Objective] = {}
        if ttft_p95_ms and ttft_p95_ms > 0:
            self.objectives["ttft_p95"] = Objective(
                "ttft_p95", 0.05, target_s=ttft_p95_ms / 1000.0)
        if e2e_p95_ms and e2e_p95_ms > 0:
            self.objectives["e2e_p95"] = Objective(
                "e2e_p95", 0.05, target_s=e2e_p95_ms / 1000.0)
        if error_rate and error_rate > 0:
            self.objectives["error_rate"] = Objective("error_rate",
                                                      error_rate)
        if shed_rate and shed_rate > 0:
            self.objectives["shed_rate"] = Objective("shed_rate", shed_rate)
        self._lock = threading.Lock()
        self._samples: Dict[str, Deque[Tuple[float, int]]] = {
            name: collections.deque(maxlen=self.MAX_SAMPLES)
            for name in self.objectives}

    # -- observation side (engine thread + handler threads) ------------------

    def _observe(self, name: str, bad: bool):
        dq = self._samples.get(name)
        if dq is None:
            return
        now = self.clock()
        with self._lock:
            dq.append((now, 1 if bad else 0))
            trim_window(dq, now, WINDOWS[-1][1])

    def observe_ttft(self, ttft_s: float):
        if not self.enabled:
            return
        obj = self.objectives.get("ttft_p95")
        if obj is not None:
            self._observe("ttft_p95", ttft_s > obj.target_s)

    def observe_request(self, status: str, duration_s: float):
        """One terminal request: feeds error_rate and e2e_p95."""
        if not self.enabled:
            return
        self._observe("error_rate", status in BAD_STATUSES)
        obj = self.objectives.get("e2e_p95")
        if obj is not None and status not in BAD_STATUSES:
            self._observe("e2e_p95", duration_s > obj.target_s)

    def observe_admission(self, shed: bool):
        """One submit() outcome: feeds shed_rate (good = admitted)."""
        if not self.enabled:
            return
        self._observe("shed_rate", shed)

    # -- query side (deterministic at a fixed clock reading) -----------------

    def bad_fraction(self, objective: str, window_s: float,
                     now: Optional[float] = None) -> float:
        """Raw bad fraction in the window (burn rate BEFORE the budget
        division) — the shed/error pressure figure consumers that are not
        budget-relative (serving/capacity.py's saturation view) read
        directly. 0.0 with no samples or an unknown objective."""
        dq = self._samples.get(objective)
        if dq is None:
            return 0.0
        t0 = (self.clock() if now is None else now) - window_s
        with self._lock:
            n = bad = 0
            for t, b in reversed(dq):
                if t < t0:
                    break
                n += 1
                bad += b
        return (bad / n) if n else 0.0

    def burn_rate(self, objective: str, window_s: float,
                  now: Optional[float] = None) -> float:
        """(bad fraction in the window) / budget; 0.0 with no samples."""
        obj = self.objectives.get(objective)
        if obj is None:
            return 0.0
        return self.bad_fraction(objective, window_s, now=now) / obj.budget

    def snapshot(self, now: Optional[float] = None) -> dict:
        """Per-objective burn rates for /healthz and the fleet view."""
        now = self.clock() if now is None else now
        out = {}
        for name, obj in self.objectives.items():
            out[name] = {
                "budget": obj.budget,
                **({"target_s": obj.target_s}
                   if obj.target_s is not None else {}),
                **{label: round(self.burn_rate(name, secs, now=now), 6)
                   for label, secs in WINDOWS},
            }
        return out

    def export(self):
        """Refresh the tpu_serve_slo_burn_rate gauges (called by the
        /metrics and /healthz handlers just before rendering)."""
        now = self.clock()
        for name in self.objectives:
            for label, secs in WINDOWS:
                metrics.burn_rate.set(self.burn_rate(name, secs, now=now),
                                      objective=name, window=label)

    def burning(self, threshold: float = 1.0,
                window: str = "5m") -> Optional[str]:
        """The first objective whose ``window`` burn exceeds ``threshold``
        (the L3 probe's slo: ok|burning signal), else None."""
        secs = dict(WINDOWS).get(window, WINDOWS[0][1])
        for name in self.objectives:
            if self.burn_rate(name, secs) > threshold:
                return name
        return None


# ---------------------------------------------------------------------------
# Module-level wiring: one SLO engine per process.
# ---------------------------------------------------------------------------

_engine: Optional[SLOEngine] = None
_engine_lock = threading.Lock()


def get() -> SLOEngine:
    """The process-wide SLO engine (default objectives until configure)."""
    global _engine
    with _engine_lock:
        if _engine is None:
            _engine = SLOEngine()
        return _engine


def configure(**kw) -> SLOEngine:
    """Build and install the process SLO engine (build_state / tests)."""
    global _engine
    eng = SLOEngine(**kw)
    with _engine_lock:
        _engine = eng
    return eng


def reset() -> SLOEngine:
    """Fresh default engine (tests)."""
    return configure()
