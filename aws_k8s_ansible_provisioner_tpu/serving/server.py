"""OpenAI-compatible HTTP front end for the TPU serving engine.

This is the API surface the reference smoke-tests through its gateway
(`llm-d-test.yaml`): ``GET /v1/models`` must list the served model (the repo's one
hard assertion, ``llm-d-test.yaml:54-59``) and ``POST /v1/completions`` must
complete a prompt (``:61-78``). We implement the same OpenAI wire format vLLM
exposes, plus ``/v1/chat/completions`` with wired-in chat templates (an explicit
improvement — the reference ships templates it never applies, SURVEY.md §7 item 7)
and Prometheus ``/metrics`` on the same port (the scrape contract at
``otel-observability-setup.yaml:359-368``).

stdlib-only (ThreadingHTTPServer): the serving pod needs no web framework, and
request threads only tokenize/detokenize + block on queues — all compute batches
inside the engine thread.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import queue
import tempfile
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from aws_k8s_ansible_provisioner_tpu.serving import (autoscaler, capacity,
                                                     devmon, flightrec,
                                                     metrics, slo, tracing)
from aws_k8s_ansible_provisioner_tpu.serving.engine import (
    ContextLengthExceeded, EngineOverloaded)

log = logging.getLogger("tpu_serve")

# Wire names for the end-to-end deadline (relative milliseconds): the router
# forwards the header unchanged and bounds its own read timeout by it, the
# server parses either form into Request.deadline_s, the engine enforces it.
DEADLINE_HEADER = "X-Request-Deadline-Ms"
DEADLINE_FIELD = "deadline_ms"


def _now() -> int:
    # API `created` fields are true wall-clock stamps — the one sanctioned
    # wall-clock path (tpulint R1); everything deadline-shaped in this file
    # is time.monotonic().
    return int(tracing.wall_clock())


def _bubble_pct(eng) -> Optional[float]:
    """Host-bubble share of the decode timeline: bubble / (bubble + busy)."""
    bubble = eng.metrics.decode_bubble_seconds.total()
    busy = eng.metrics.device_busy_seconds.total()
    if bubble + busy <= 0:
        return None
    return round(100.0 * bubble / (bubble + busy), 2)


def _device_health() -> dict:
    """Compact device block for /healthz (the fleet poller relays it to
    /debug/fleet and tputop): HBM occupancy + drift verdict, duty cycle,
    and the decode program's MFU. Full table lives at /debug/roofline."""
    snap = devmon.get().snapshot()
    hbm = snap["hbm"]
    dec = snap["programs"].get("decode") or {}
    return {
        "hbm_drift": hbm["verdict"],
        "hbm_live_bytes": int(hbm["live_bytes"]),
        "hbm_compiled_bytes": int(hbm["compiled_bytes"]),
        "hbm_drift_bytes": int(hbm["drift_bytes"]),
        "duty_cycle": round(snap["duty_cycle"], 4),
        "mfu": round(dec.get("mfu", 0.0), 4),
        "membw_util": round(dec.get("membw_util", 0.0), 4),
        "dma_wait_fraction": round(snap["dma_wait_fraction"], 4),
    }


class _NotifyQueue(queue.Queue):
    """Request out_queue that signals a shared Event on every put.

    A multi-choice (n > 1) stream handler can't block on n stdlib queues at
    once; blocking on this one shared event replaces the ~100 Hz nonblocking
    poll-and-sleep sweep that burned CPU per concurrent stream (advisor r4).
    """

    def __init__(self, event: threading.Event):
        super().__init__()
        self.event = event

    def put(self, item, *a, **kw):
        super().put(item, *a, **kw)
        self.event.set()


class ServerState:
    """Everything the handler needs: engine, tokenizer, templater, identity."""

    def __init__(self, engine, tokenizer, templater, model_name: str):
        self.engine = engine
        self.tokenizer = tokenizer
        self.templater = templater
        self.model_name = model_name
        self.started = _now()
        # Request tracing (set by build_state from serving config; tests
        # inject seeded tracers). None = spans off entirely.
        self.tracer: Optional[tracing.Tracer] = None
        # Serializes /debug/profile captures (one JAX trace at a time).
        self.profile_lock = threading.Lock()
        # Graceful drain (r8): set by serve() so the SIGTERM handler /
        # /admin/drain can stop the process after the drain quiesces.
        self.stop_event: Optional[threading.Event] = None
        self._drain_lock = threading.Lock()
        self._drain_watcher: Optional[threading.Thread] = None
        # /v1/* requests currently inside a handler thread: the drain
        # watcher exits only when the ENGINE is idle AND every handler has
        # finished writing its response — zero dropped in-flight requests.
        self._inflight = 0
        self._inflight_lock = threading.Lock()

    def inflight_inc(self):
        with self._inflight_lock:
            self._inflight += 1

    def inflight_dec(self):
        with self._inflight_lock:
            self._inflight -= 1

    @property
    def inflight(self) -> int:
        with self._inflight_lock:
            return self._inflight

    def begin_drain(self, timeout_s: Optional[float] = None,
                    exit_when_idle: bool = True) -> float:
        """Flip the engine to draining and (by default) arm the watcher that
        stops the server once in-flight work finishes — the SIGTERM /
        preStop path. ``exit_when_idle=False`` drains WITHOUT scheduling an
        exit (operator takes a replica out of rotation but keeps the
        process; /admin/undrain reverses it). Idempotent."""
        t = self.engine.begin_drain(timeout_s)
        if not exit_when_idle:
            return t
        with self._drain_lock:
            if self._drain_watcher is None:
                self._drain_watcher = threading.Thread(
                    target=self._drain_watch, daemon=True,
                    name="drain-watcher")
                self._drain_watcher.start()
        return t

    def end_drain(self):
        self.engine.end_drain()

    def _drain_watch(self):
        """Stop the server once the drain quiesces: engine idle (no active
        slots, no queue, no chunk walk) and no /v1 handler still writing.
        Past the drain deadline (+grace for the deadline reaper to finish
        the stragglers it cancelled) the stop is forced — the reaper
        guarantees slots/pages were released exactly once either way."""
        eng = self.engine
        while True:
            if not eng.draining:        # drain cancelled via /admin/undrain
                with self._drain_lock:
                    self._drain_watcher = None
                return
            idle = (not eng._active_slots() and not eng.pending
                    and eng._chunk is None and self.inflight == 0)
            if idle or time.monotonic() > eng._drain_deadline + 5.0:
                break
            time.sleep(0.05)
        log.info("drain complete (inflight=%d active=%d queued=%d); "
                 "stopping server", self.inflight,
                 len(eng._active_slots()), len(eng.pending))
        if self.stop_event is not None:
            self.stop_event.set()


def _format_logprobs(tokenizer, ids, lp_data, k: int, chat: bool,
                     text_len: int = -1, base_offset: int = 0):
    """OpenAI logprobs payloads. Completions: {tokens, token_logprobs,
    top_logprobs, text_offset}; chat: {content: [{token, logprob,
    top_logprobs}]}. Token strings decode per-id (lossy for multi-byte
    merges — the same behavior as vLLM's per-token decode). ``text_len``
    truncates the payload to the tokens whose text survived a stop-string
    cut, so logprobs and choices[].text stay aligned; ``base_offset``
    shifts text_offset past an echoed prompt."""
    toks = [tokenizer.decode([t]) for t in ids]
    offsets, pos = [], base_offset
    for t in toks:
        offsets.append(pos)
        pos += len(t)
    n = len(toks)
    if text_len >= 0:
        # text_len counts GENERATED text only; offsets start at base_offset
        n = sum(1 for o in offsets if o - base_offset < text_len) \
            if text_len else 0
        n = max(n, 0)
    toks, offsets = toks[:n], offsets[:n]
    lp_data = lp_data[:n]
    own = [None if d is None else d[0] for d in lp_data]

    def top_list(d):
        if d is None:
            return []
        return [(tokenizer.decode([tid]), v) for tid, v in d[1][:k]]

    if chat:
        return {"content": [
            {"token": toks[i], "logprob": own[i],
             "top_logprobs": [{"token": t, "logprob": v}
                              for t, v in top_list(lp_data[i])]}
            for i in range(min(len(toks), len(lp_data)))]}
    return {"tokens": toks,
            "token_logprobs": own,
            "top_logprobs": [dict(top_list(d)) for d in lp_data],
            "text_offset": offsets}


def _wait_budget_s(engine, req) -> Optional[float]:
    """Server-side cap for a blocking collect: the request's own deadline
    plus grace — the ENGINE owns deadline enforcement (cancel + slot/page
    release + "timeout" finish); this budget is only the backstop that
    keeps a handler thread from hanging on a wedged engine loop. Without a
    deadline the configured default (request_timeout_s) applies; a config
    of 0 means genuinely unbounded (None), not some other magic constant."""
    if req.t_deadline:
        return max(1.0, req.t_deadline - time.monotonic()) + 30.0
    cap = float(engine.serving.request_timeout_s or 0)
    return cap + 30.0 if cap > 0 else None


def _apply_stop_strings(text: str, stops: List[str]) -> Optional[str]:
    """Return text truncated at the earliest stop string, or None if no match."""
    cut = None
    for s in stops:
        if s:
            i = text.find(s)
            if i >= 0 and (cut is None or i < cut):
                cut = i
    return text[:cut] if cut is not None else None


class Handler(BaseHTTPRequestHandler):
    state: ServerState  # set by serve()
    protocol_version = "HTTP/1.1"
    # Per-request trace context (class default so keep-alive connections
    # never leak a previous request's ids into an untraced one).
    _trace_ctx: Optional[tracing.SpanContext] = None

    # -- plumbing -----------------------------------------------------------

    def log_message(self, fmt, *args):
        log.debug("%s - %s", self.address_string(), fmt % args)

    def _json(self, code: int, obj: dict, headers: Optional[dict] = None):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, message: str,
               err_type: str = "invalid_request_error",
               err_code: Optional[str] = None,
               headers: Optional[dict] = None):
        err = {"message": message, "type": err_type,
               "code": err_code if err_code else code}
        if self._trace_ctx is not None:
            # log correlation: the ids to paste into Tempo / grep from the
            # collector when a request fails
            err["trace_id"] = self._trace_ctx.trace_id
            err["span_id"] = self._trace_ctx.span_id
        # ring-only black-box breadcrumb: 5xx edges land in /debug/events
        # beside the engine's own events (4xx are client errors — noise)
        if code >= 500:
            flightrec.record("http_error", None, code=code, type=err_type)
        self._json(code, {"error": err}, headers=headers)

    def _overloaded(self, e: EngineOverloaded):
        """429 + Retry-After: the structured load-shed answer. The router
        treats this as a routable signal (another replica may have room);
        clients back off by the hint. A DRAINING shed is 503 instead (the
        replica is leaving, not full) with the X-TPU-Draining marker the
        router keys on to re-route without dead-marking — shed at
        admission, so re-routing is always safe."""
        if e.reason == "draining":
            return self._error(503, str(e), "unavailable_error",
                               err_code="draining",
                               headers={"Retry-After":
                                        str(int(e.retry_after_s + 0.5)),
                                        "X-TPU-Draining": "1"})
        self._error(429, str(e), "overloaded_error",
                    err_code=f"engine_overloaded:{e.reason}",
                    headers={"Retry-After": str(int(e.retry_after_s + 0.5))})

    def _read_body(self) -> Optional[dict]:
        try:
            n = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(n) if n else b"{}"
            return json.loads(raw or b"{}")
        except (ValueError, json.JSONDecodeError):
            self._error(400, "request body is not valid JSON")
            return None

    # -- GET ----------------------------------------------------------------

    def do_GET(self):
        path = self.path.split("?")[0]
        if path == "/v1/models":
            base = {
                "id": self.state.model_name,
                "object": "model",
                "created": self.state.started,
                "owned_by": "tpu-serve",
                "max_model_len": self.state.engine.max_len,
            }
            # LoRA adapters are served as model ids (the vLLM --enable-lora
            # contract): request model == adapter name routes to it
            adapters = [{**base, "id": name, "parent": self.state.model_name}
                        for name in self.state.engine.lora_names]
            self._json(200, {"object": "list", "data": [base] + adapters})
        elif path == "/metrics":
            # Engine metrics + per-chip HBM gauges from THIS process's
            # runtime (the engine owns the chips; the node exporter derives
            # tpu_duty_cycle_percent from our busy-seconds counter).
            from aws_k8s_ansible_provisioner_tpu.k8s.metrics_exporter import (
                render_engine_chips)

            slo.get().export()       # refresh the burn-rate gauges
            devmon.get().export()    # refresh the tpu_device_* family
            capacity.get().export()  # refresh tpu_capacity_* (drop-not-fail)
            autoscaler.get().export()  # refresh tpu_autoscale_* (R12: the
            # replica process has no controller, so these render at their
            # defaults — same both-routes contract as the gateway families)
            # Content negotiation: OpenMetrics (exemplars + # EOF) when the
            # scraper asks for it, classic Prometheus text otherwise.
            om = "application/openmetrics-text" in \
                (self.headers.get("Accept") or "")
            text = (self.state.engine.metrics.registry.render(om)
                    + tracing.metrics.registry.render(om)
                    + flightrec.metrics.registry.render(om)
                    + slo.metrics.registry.render(om)
                    + devmon.metrics.registry.render(om)
                    + capacity.metrics.registry.render(om)
                    + autoscaler.metrics.registry.render(om)
                    + metrics.pipeline.registry.render(om)
                    + render_engine_chips())
            if om:
                text += "# EOF\n"
                ctype = ("application/openmetrics-text; version=1.0.0; "
                         "charset=utf-8")
            else:
                ctype = "text/plain; version=0.0.4"
            body = text.encode()
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path in ("/health", "/healthz", "/ping"):
            eng = self.state.engine
            stalled = eng.stalled_for_s
            status = "ok"
            if eng.last_error:
                status = "degraded"
            if eng.draining:
                # deliberate lifecycle state, not a failure: /healthz stays
                # 200 so the K8s LIVENESS probe never kills a pod
                # mid-drain; readiness (/readyz) is what flips to 503
                status = "draining"
            if stalled:
                # a wedged device dispatch hangs inside step(); K8s liveness
                # keys off this to restart the pod (the engine thread cannot
                # recover a hung XLA call itself)
                status = "stalled"
            dev = _device_health()
            self._json(503 if stalled else 200, {
                "status": status,
                "draining": bool(eng.draining),
                "model": self.state.model_name,
                "uptime_s": _now() - self.state.started,
                "active_requests": len(eng._active_slots()),
                "queue_depth": len(eng.pending),
                # /v1 requests inside a handler thread (parse/tokenize/
                # stream-out) are invisible to the two engine counters
                # above; external drain orchestration (deploy/probes.py
                # rolling_restart) needs the same inflight==0 signal the
                # in-process drain watcher uses before it may kill the
                # process
                "inflight": self.state.inflight,
                "stalled_for_s": round(stalled, 1) or None,
                "last_error": eng.last_error or None,
                # the autotuned decode batch-block (ISSUE r6): operators can
                # confirm the served kernel config without scraping metrics
                "decode_bblock": getattr(eng, "decode_bblock", None),
                # decode pipeline (r9): knob state plus the host-bubble share
                # of device wall time — sync mode shows the real gap the
                # pipeline would hide; pipelined steady state trends to 0.
                "decode_pipeline": eng.serving.decode_pipeline,
                "decode_bubble_pct": _bubble_pct(eng),
                # Ragged mixed-batch attention (ISSUE 14): knob state plus
                # the pipeline drain ledger — drains by reason and the
                # drain rate (drains per dispatch). Mixed traffic on the
                # ragged path should hold drain_rate ~0 where the legacy
                # path pays one drain per admission.
                "ragged_attention": eng.serving.ragged_attention,
                # Feature paths riding the ragged pipeline (ISSUE 16):
                # 0 means spec/LoRA/guided still de-pipeline to the sync
                # floor (the PR-14 fallback arm).
                "ragged_features": eng.serving.ragged_features,
                "pipeline": metrics.pipeline.snapshot(),
                "weights_dtype": eng.serving.weights_dtype,
                "kv_dtype": eng.serving.kv_dtype,
                "paged": bool(getattr(eng, "paged", False)),
                # AOT manifest adoption summary (serving/aot.py): operators
                # confirm the replica serves a pre-verified program set (and
                # its HBM ledger headroom) straight off the probe; null means
                # no manifest was loaded (plain lazy/warmup compilation).
                "aot": getattr(eng, "aot", None),
                # Robustness counters (r7): operators (and the chaos suite)
                # read shed/deadline/stall/preemption totals here without a
                # /metrics scrape+parse.
                "shed_total": int(eng.metrics.requests_shed.total()),
                "deadline_expired_total":
                    int(eng.metrics.deadline_expired.total()),
                "watchdog_stalls_total":
                    int(eng.metrics.watchdog_stalls.total()),
                "preemptions_total": int(eng.metrics.preemptions.total()),
                "max_queue_depth": eng.serving.max_queue_depth or None,
                "request_timeout_s": eng.serving.request_timeout_s or None,
                # Fleet-view block (this PR): the router's /debug/fleet and
                # tools/tputop.py read throughput, pool pressure, SLO burn
                # rates, and the flight recorder's last anomaly from the
                # SAME probe the reconcile loop already polls — no extra
                # scrape+parse round trip per replica.
                "tokens_per_second":
                    round(eng.metrics.tokens_per_second.value(), 2),
                "kv_pages_total": int(eng.metrics.kv_pages_total.value()),
                "kv_pages_in_use": int(eng.metrics.kv_pages_in_use.value()),
                # Free/evictable split + tier-2 ledger (ISSUE 20): "pool
                # full" vs "pool full of reusable prefixes" are different
                # capacity situations, and the tier split says where prefix
                # hits are actually being served from (hbm share / host
                # restore / miss) without a /metrics scrape+parse.
                "kv_pages_free": int(eng.metrics.kv_pages_free.value()),
                "kv_pages_evictable":
                    int(eng.metrics.kv_pages_evictable.value()),
                "prefix_tier_hits": {
                    t: int(eng.metrics.prefix_tier_hits.value(tier=t))
                    for t in ("hbm", "host", "miss")},
                "kv_host_tier": (
                    eng.host_tier.stats()
                    if getattr(eng, "host_tier", None) is not None else None),
                "slo": slo.get().snapshot(),
                "slo_burning": slo.get().burning(),
                "flight": flightrec.get().summary(),
                # Device panel (serving/devmon.py): HBM occupancy + drift
                # verdict and the roofline headline numbers, for the
                # router's fleet poller / tputop / probes.py L3. The drift
                # verdict WARNS, never kills: a ledger miss is a diagnosis,
                # not a liveness failure.
                "device": dev,
                "hbm_drift": dev["hbm_drift"],
                # Capacity block (serving/capacity.py): offered load vs the
                # ceiling, saturation, and the seconds-to-saturation
                # forecast — relayed by the router's poller into its
                # /debug/capacity fleet aggregation. Recommendation-only:
                # nothing in-process actuates on it.
                "capacity": capacity.get().snapshot(),
            })
        elif path == "/readyz":
            # Readiness, distinct from liveness (r8): a DRAINING replica is
            # alive (finishing streams; liveness must not kill it) but not
            # ready (K8s stops routing Service traffic to it; the preStop +
            # SIGTERM path relies on this ordering). Stalled is both.
            eng = self.state.engine
            if eng.draining:
                self._json(503, {"status": "draining"},
                           headers={"X-TPU-Draining": "1"})
            elif eng.stalled_for_s:
                self._json(503, {"status": "stalled"})
            else:
                self._json(200, {"status": "ready"})
        elif path == "/load":
            # Tiny load snapshot for the gateway's ~1 Hz poller (router.py
            # load-aware routing — VERDICT r3 next #5): kept separate from
            # /health (which runs stall diagnostics) and /metrics (whose
            # render cost scales with series count). ``draining`` removes
            # the replica from the router's rotation without dead-marking
            # it (it re-enters within one poll of draining going false).
            eng = self.state.engine
            self._json(200, {"active": len(eng._active_slots()),
                             "queued": len(eng.pending),
                             "slots": eng.num_slots,
                             "draining": bool(eng.draining)})
        elif path == "/admin/drain":
            # K8s lifecycle httpGet handlers can only GET; same semantics
            # as the POST (default timeout, exit when idle)
            self._admin_drain({})
        elif path == "/debug/profile":
            self._profile()
        elif path == "/debug/roofline":
            # Per-program roofline attribution table (serving/devmon.py):
            # measured s/step vs the analytical floor, MFU, bandwidth
            # utilization, dma-wait share, plus the live HBM ledger — the
            # PERF.md model rendered against production traffic.
            self._json(200, devmon.get().snapshot())
        elif path == "/debug/capacity":
            # This replica's capacity/saturation/forecast view
            # (serving/capacity.py) — the per-replica drill-down under the
            # router's fleet-level /debug/capacity aggregation.
            self._json(200, capacity.get().snapshot())
        elif path == "/debug/events":
            # the flight recorder's live ring, oldest first (?last=N caps it)
            import urllib.parse

            n, q = 100, self.path.split("?", 1)
            if len(q) == 2:
                vals = urllib.parse.parse_qs(q[1]).get("last")
                if vals and vals[0].isdigit():
                    n = min(int(vals[0]), 4096)
            self._json(200, {"events": flightrec.get().tail(n)})
        elif path.startswith("/debug/flight/"):
            # anomaly snapshot (or live timeline) for one request id
            rid = path[len("/debug/flight/"):]
            dump = flightrec.get().dump_for(rid)
            if dump is None and rid.isdigit():
                # engine request ids are ints; the URL hands us a string
                dump = flightrec.get().dump_for(int(rid))
            if dump is None:
                return self._error(404, f"no flight timeline for {rid!r} "
                                        "(snapshots keep the last anomalies "
                                        "only; see /debug/events)")
            self._json(200, dump)
        else:
            self._error(404, f"no route for GET {path}")

    def _profile(self):
        """Capture a JAX/XLA device trace while the engine serves.

        The reference's trace pipeline accepts and drops traces (its only
        exporter is `debug`, otel-observability-setup.yaml:633-636 — SURVEY.md
        §5 tracing gap); here profiling is real: a perfetto/TensorBoard-
        compatible trace is written server-side and its path returned.
        `?ms=N` controls the capture window (default 1000, max 30000).
        """
        import urllib.parse

        import jax as _jax

        q = self.path.split("?", 1)
        ms = 1000
        if len(q) == 2:
            vals = urllib.parse.parse_qs(q[1]).get("ms")
            if vals and vals[0].isdigit():
                ms = min(int(vals[0]), 30000)
        out_dir = os.path.join(
            tempfile.gettempdir(), "tpu-serve-profile",
            f"{time.strftime('%Y%m%d-%H%M%S')}-{uuid.uuid4().hex[:8]}")
        with self.state.profile_lock:
            try:
                _jax.profiler.start_trace(out_dir)
                time.sleep(ms / 1000.0)
            finally:
                try:
                    _jax.profiler.stop_trace()
                # tpulint: disable=R3 admin endpoint — a failed profiler stop is reported to the caller as a 500, not propagated into the handler thread
                except Exception as e:
                    self._error(500, f"profiler stop failed: {e}",
                                "internal_error")
                    return
        self._json(200, {"trace_dir": out_dir, "window_ms": ms,
                         "view": "tensorboard --logdir <trace_dir> "
                                 "(Profile tab) or perfetto"})

    # -- POST ---------------------------------------------------------------

    def do_POST(self):
        self._trace_ctx = None      # keep-alive: clear the previous
        path = self.path.split("?")[0]          # request's trace identity
        body = self._read_body()
        if body is None:
            return
        track = path.startswith("/v1/")
        if track:
            # the drain watcher waits for this to hit zero: a response still
            # being written is in-flight work a graceful shutdown must not
            # cut (admin/probe traffic deliberately doesn't count)
            self.state.inflight_inc()
        try:
            if path == "/v1/completions":
                self._completions(body, chat=False)
            elif path == "/v1/chat/completions":
                self._completions(body, chat=True)
            elif path == "/admin/drain":
                self._admin_drain(body)
            elif path == "/admin/undrain":
                self.state.end_drain()
                self._json(200, {"status": "ok", "draining": False})
            else:
                self._error(404, f"no route for POST {path}")
        except BrokenPipeError:
            pass
        # tpulint: disable=R3 request boundary — engine errors surface as 500s; the handler thread must outlive any single request
        except Exception as e:
            log.exception("request failed")
            try:
                self._error(500, f"{type(e).__name__}: {e}", "internal_error")
            # tpulint: disable=R3 best-effort error write — the client may already have hung up; nothing left to report to
            except Exception:
                pass
        finally:
            if track:
                self.state.inflight_dec()

    def _admin_drain(self, body: dict):
        """Begin a graceful drain (the preStop hook's target; SIGTERM takes
        the same path): stop admitting, finish in-flight work up to
        ``timeout_s`` (default drain_timeout_s), then stop the server —
        unless ``exit: false`` (drain for rotation-removal only;
        /admin/undrain reverses it)."""
        eng = self.state.engine
        try:
            timeout_s = body.get("timeout_s")
            if timeout_s is not None:
                timeout_s = float(timeout_s)
        except (TypeError, ValueError):
            return self._error(400, "'timeout_s' must be a number")
        exit_when_idle = bool(body.get("exit", True))
        t = self.state.begin_drain(timeout_s, exit_when_idle=exit_when_idle)
        log.info("drain requested (timeout %.1fs, exit=%s): %d active, "
                 "%d queued", t, exit_when_idle,
                 len(eng._active_slots()), len(eng.pending))
        self._json(200, {"status": "draining", "drain_timeout_s": t,
                         "exit_when_idle": exit_when_idle,
                         "active_requests": len(eng._active_slots()),
                         "queue_depth": len(eng.pending)})

    def _completions(self, body: dict, chat: bool):
        """Span-lifecycle wrapper around the real handler: continues the
        router's propagated ``traceparent`` into a ``server.request`` span,
        then reconstructs the five phase children (admission, queue_wait,
        prefill, decode, stream_out) retroactively from the engine Request's
        monotonic timestamps once the response is written — the engine's hot
        loop carries timestamps, never tracer calls."""
        st = self.state
        tracer = st.tracer
        if tracer is None:
            return self._completions_impl(body, chat)
        t0_mono = time.monotonic()
        parent = tracing.parse_traceparent(
            self.headers.get(tracing.TRACEPARENT_HEADER))
        span = tracer.start_span(
            "server.request", parent=parent, kind=tracing.KIND_SERVER,
            start_ns=tracing.mono_ns(t0_mono),
            attributes={"http.route": ("/v1/chat/completions" if chat
                                       else "/v1/completions"),
                        "request.stream": bool(body.get("stream", False))})
        raw_ddl = body.get(DEADLINE_FIELD, self.headers.get(DEADLINE_HEADER))
        if raw_ddl is not None:
            try:
                span.set_attribute("deadline.remaining_ms",
                                   int(float(raw_ddl)))
            except (TypeError, ValueError):
                pass
        self._trace_ctx = span.context
        self._trace_reqs = None
        try:
            return self._completions_impl(body, chat)
        except Exception as e:
            span.error(f"{type(e).__name__}: {e}")
            raise
        finally:
            self._emit_phase_spans(tracer, span, t0_mono)

    def _emit_phase_spans(self, tracer, span, t0_mono: float):
        """Phase children + request-span finish. Boundaries are the engine
        Request's own transition timestamps, clamped to a monotonic chain
        (an unset 0.0 collapses that phase to zero width at the previous
        boundary — e.g. a non-streamed request ends stream_out ≈ t_done),
        so consumers can rely on non-overlapping phases."""
        end_mono = time.monotonic()
        reqs = getattr(self, "_trace_reqs", None)
        if reqs:
            r = reqs[0]     # choice 0 == the n=1 request's timeline
            bounds = [t0_mono, r.t_submit, r.t_prefill_start,
                      r.t_first_token, r.t_done, end_mono]
            for i in range(1, len(bounds)):
                if bounds[i] <= 0.0 or bounds[i] < bounds[i - 1]:
                    bounds[i] = bounds[i - 1]
            names = ("admission", "queue_wait", "prefill", "decode",
                     "stream_out")
            for name, lo, hi in zip(names, bounds, bounds[1:]):
                tracer.emit_span(name, span.context, tracing.mono_ns(lo),
                                 tracing.mono_ns(hi),
                                 attributes={"phase.ms":
                                             round((hi - lo) * 1e3, 3)})
            span.set_attribute("request.n_choices", len(reqs))
            if r.finish_reason:
                span.set_attribute("request.finish_reason", r.finish_reason)
        tracer.finish(span, end_ns=tracing.mono_ns(end_mono))

    def _completions_impl(self, body: dict, chat: bool):
        st = self.state
        model = body.get("model") or st.model_name
        lora_name = model if model in st.engine.lora_names else None
        if model != st.model_name and lora_name is None:
            return self._error(404, f"model {model!r} not found; serving "
                                    f"{st.model_name!r} (adapters: "
                                    f"{st.engine.lora_names})",
                               "model_not_found")

        if chat:
            messages = body.get("messages")
            if not isinstance(messages, list) or not messages:
                return self._error(400, "'messages' must be a non-empty list")
            prompt_text = st.templater.render(messages, add_generation_prompt=True)
        else:
            prompt = body.get("prompt", "")
            if isinstance(prompt, list):
                prompt = prompt[0] if prompt else ""
            if not isinstance(prompt, str):
                return self._error(400, "'prompt' must be a string")
            prompt_text = prompt

        try:
            max_tokens = int(body.get("max_tokens",
                                      st.engine.serving.max_tokens_default))
            temperature = float(body.get("temperature", 1.0 if chat else 0.0))
            top_p = float(body.get("top_p", 1.0))
            top_k = int(body.get("top_k", 0))
            presence_penalty = float(body.get("presence_penalty", 0.0))
            frequency_penalty = float(body.get("frequency_penalty", 0.0))
            repetition_penalty = float(body.get("repetition_penalty", 1.0))
        except (TypeError, ValueError):
            return self._error(400, "sampling parameters must be numeric")
        if not (-2.0 <= presence_penalty <= 2.0
                and -2.0 <= frequency_penalty <= 2.0):
            return self._error(400, "penalties must be in [-2, 2]")
        if not (0.0 < repetition_penalty <= 10.0):
            return self._error(400, "'repetition_penalty' must be in "
                                    "(0, 10]")
        # a continuation's max_tokens means REMAINING budget (the router
        # decrements it by the already-relayed tokens), so 0 is legal there
        min_mt = 0 if body.get("resume_token_ids") is not None else 1
        if max_tokens < min_mt or max_tokens > st.engine.max_len:
            return self._error(400, f"max_tokens must be in [{min_mt}, "
                                    f"{st.engine.max_len}]")
        stops = body.get("stop") or []
        if isinstance(stops, str):
            stops = [stops]
        # vLLM extras: stop_token_ids (token-level stops beside the string
        # ones) and min_tokens (stop tokens masked from sampling until N
        # tokens generated)
        raw_stop_ids = body.get("stop_token_ids") or []
        if not isinstance(raw_stop_ids, list):
            # a string would silently iterate character-wise
            return self._error(400, "'stop_token_ids' must be a list of "
                                    "integers")
        try:
            stop_token_ids = tuple(int(t) for t in raw_stop_ids)
            min_tokens = int(body.get("min_tokens", 0))
        except (TypeError, ValueError):
            return self._error(400, "'stop_token_ids' must be integers and "
                                    "'min_tokens' an integer")
        if min_tokens < 0:
            return self._error(400, "'min_tokens' must be >= 0")
        stream = bool(body.get("stream", False))
        # End-to-end deadline (r7): relative milliseconds via the
        # X-Request-Deadline-Ms header (router-forwarded) or the deadline_ms
        # body field (body wins). The engine caps it at request_timeout_s,
        # enforces it across queue wait + decode, and expiry answers 408.
        raw_ddl = body.get(DEADLINE_FIELD, self.headers.get(DEADLINE_HEADER))
        deadline_s = None
        if raw_ddl is not None:
            try:
                deadline_s = float(raw_ddl) / 1000.0
            except (TypeError, ValueError):
                return self._error(400, f"'{DEADLINE_FIELD}' must be a "
                                        "number of milliseconds")
            if deadline_s <= 0:
                return self._error(400, f"'{DEADLINE_FIELD}' must be > 0")
        # vLLM ``ignore_eos``: generate to the max_tokens budget regardless
        # of eos (bench/load harnesses depend on it for deterministic sizes)
        ignore_eos = bool(body.get("ignore_eos", False))
        try:
            n_choices = int(body.get("n", 1))
        except (TypeError, ValueError):
            return self._error(400, "'n' must be an integer")
        if n_choices < 1 or n_choices > 8:
            return self._error(400, "'n' must be in [1, 8]")

        # OpenAI ``seed``: deterministic sampling (engine keys each draw by
        # (seed, position) — ops/sampling.per_slot_keys). Sibling choices get
        # seed + i so n > 1 still returns distinct samples, with choice 0
        # equal to the n=1 stream.
        seed = body.get("seed")
        if seed is not None:
            try:
                seed = int(seed)
            except (TypeError, ValueError):
                return self._error(400, "'seed' must be an integer")
        # OpenAI ``echo`` (completions only): prepend the prompt text to each
        # choice's text. Logprobs cover GENERATED tokens only (prompt
        # logprobs are not computed — vLLM subset); offsets account for the
        # echoed prompt.
        echo = bool(body.get("echo", False))
        if echo and chat:
            return self._error(400, "'echo' is not supported on chat "
                                    "completions")
        # OpenAI ``best_of`` (completions only): generate best_of candidates
        # server-side, return the n best by cumulative logprob. Candidates
        # ride the same continuous batch; ranking uses the engine's
        # chosen-token logprobs (requested internally when the client
        # didn't ask for logprobs).
        try:
            best_of = int(body.get("best_of", n_choices))
        except (TypeError, ValueError):
            return self._error(400, "'best_of' must be an integer")
        if chat:
            best_of = n_choices
        if best_of < n_choices or best_of > 8:
            return self._error(400, f"'best_of' must be in [n, 8], got "
                                    f"{best_of}")
        if stream and best_of > n_choices:
            return self._error(400, "best_of > n with stream=true is not "
                                    "supported (ranking needs complete "
                                    "candidates)")
        # vLLM ``prompt_logprobs``: per-prompt-position logprobs (position
        # 0 is null). OpenAI legacy echo+logprobs implies it (the prompt
        # part of the echoed logprobs payload).
        raw_plp = body.get("prompt_logprobs")
        try:
            plp = None if raw_plp is None else int(raw_plp)
        except (TypeError, ValueError):
            return self._error(400, "'prompt_logprobs' must be an integer")
        # OpenAI logprobs: completions take an int ``logprobs`` (0 = chosen-
        # token only — still enabled; absent/null = off); chat takes
        # ``logprobs: true`` + ``top_logprobs: N`` (explicit 0 respected).
        # Capped at the engine's static LOGPROB_K; streaming responses carry
        # per-token logprob chunks (vLLM's streamed-logprobs shape).
        from aws_k8s_ansible_provisioner_tpu.serving.engine import LOGPROB_K
        try:
            if chat:
                lp_n = int(body.get("top_logprobs", 0)) \
                    if bool(body.get("logprobs", False)) else None
            else:
                raw_lp = body.get("logprobs", None)
                if raw_lp is False:
                    raw_lp = None   # explicit false unambiguously means off
                elif isinstance(raw_lp, bool):
                    # bool is an int subclass: the chat-style {"logprobs":
                    # true} on /v1/completions is a client bug, not a 1
                    return self._error(400, "completions 'logprobs' is an "
                                            "integer, not a boolean")
                lp_n = None if raw_lp is None else int(raw_lp)
        except (TypeError, ValueError):
            return self._error(400, "'logprobs' must be numeric")
        if lp_n is not None and (lp_n < 0 or lp_n > LOGPROB_K):
            return self._error(400, f"logprobs must be in [0, {LOGPROB_K}]")
        if plp is not None:
            if not (0 <= plp <= LOGPROB_K):
                return self._error(400, f"prompt_logprobs must be in "
                                        f"[0, {LOGPROB_K}]")
            if stream:
                return self._error(400, "prompt_logprobs with stream=true "
                                        "is not supported")
        # OpenAI ``logit_bias``: {token_id: bias} map, additive on logits
        # before every sampling decision (±100 act as force/ban). vLLM
        # behind the reference's gateway accepts it; BIAS_K caps entries.
        from aws_k8s_ansible_provisioner_tpu.serving.engine import BIAS_K
        raw_bias = body.get("logit_bias") or {}
        if not isinstance(raw_bias, dict):
            return self._error(400, "'logit_bias' must be an object mapping "
                                    "token ids to bias values")
        try:
            logit_bias = tuple(sorted((int(k), float(v))
                                      for k, v in raw_bias.items()))
        except (TypeError, ValueError):
            return self._error(400, "'logit_bias' keys must be token ids "
                                    "and values numbers")
        if len(logit_bias) > BIAS_K:
            return self._error(400, f"'logit_bias' supports at most "
                                    f"{BIAS_K} entries")
        if any(t < 0 for t, _ in logit_bias):
            return self._error(400, "'logit_bias' token ids must be >= 0")
        if any(not (-100.0 <= v <= 100.0) for _, v in logit_bias):
            return self._error(400, "'logit_bias' values must be in "
                                    "[-100, 100]")
        # OpenAI ``stream_options``: include_usage adds a final usage-only
        # chunk to the SSE stream (and a null usage field on every chunk).
        so = body.get("stream_options") or {}
        if not isinstance(so, dict):
            return self._error(400, "'stream_options' must be an object")
        if so and not stream:
            return self._error(400, "'stream_options' requires stream=true")
        include_usage = bool(so.get("include_usage", False))
        # Mid-stream failover continuation (r8): the router re-issues a
        # dying stream carrying the token ids it already relayed
        # (resume_token_ids) and how much generated text the client already
        # received (resume_text_chars). The engine re-prefills
        # prompt + resume as a cache rebuild; the seeded draws continue at
        # the exact positions the dead replica would have used, and
        # _stream_response splices only NEW bytes to the client. max_tokens
        # in a continuation body is the REMAINING budget; the engine's is
        # total generated, so the resume length is added back (a body
        # without max_tokens keeps the default as the TOTAL budget —
        # exactly the original request's).
        raw_resume = body.get("resume_token_ids")
        resume_ids: tuple = ()
        resume_chars = 0
        if raw_resume is not None:
            if not isinstance(raw_resume, list):
                return self._error(400, "'resume_token_ids' must be a list "
                                        "of token ids")
            try:
                resume_ids = tuple(int(t) for t in raw_resume)
                resume_chars = int(body.get("resume_text_chars", 0))
            except (TypeError, ValueError):
                return self._error(400, "'resume_token_ids' must be integers"
                                        " and 'resume_text_chars' an "
                                        "integer")
            if resume_chars < 0:
                return self._error(400, "'resume_text_chars' must be >= 0")
            if not stream:
                return self._error(400, "'resume_token_ids' requires "
                                        "stream=true")
            if n_choices != 1 or best_of != 1:
                return self._error(400, "continuation supports a single "
                                        "choice (n=1, best_of=1)")
            if echo:
                return self._error(400, "continuation cannot combine with "
                                        "'echo' (the prompt was already "
                                        "streamed)")
            if plp is not None:
                return self._error(400, "continuation cannot carry "
                                        "prompt_logprobs")
            if "max_tokens" in body:
                max_tokens += len(resume_ids)
        # Constrained output via the grammar-mask sampler (serving/guided.py):
        # OpenAI ``response_format`` (json_object/json_schema) plus vLLM's
        # guided_json / guided_regex / guided_choice extensions. Compiled
        # grammars are cached per (tokenizer, spec); each sibling request
        # gets its own FSM cursor (engine.submit wraps the grammar).
        rf = body.get("response_format")
        if rf is not None and not isinstance(rf, dict):
            return self._error(400, "'response_format' must be an object")
        from aws_k8s_ansible_provisioner_tpu.serving.guided import (
            grammar_for_request)
        try:
            guided = grammar_for_request(st.tokenizer, body,
                                         sorted(st.engine._eos_set))
        except ValueError as e:
            return self._error(400, f"guided decoding: {e}")

        prompt_ids = st.tokenizer.encode(prompt_text)
        if not prompt_ids:
            prompt_ids = [st.engine.eos_token_id]
        if echo and lp_n is not None and plp is None and not stream \
                and len(prompt_ids) <= max(st.engine.buckets or (0,)):
            # OpenAI legacy echo+logprobs implies prompt logprobs — but only
            # when the request can honor them (non-stream, bucket-sized
            # prompt); otherwise keep the pre-r5 generated-only payload
            # instead of breaking previously-working requests (review r5)
            plp = lp_n
        if raw_resume is not None:
            # A relayed prefix that ALREADY satisfies a stop condition must
            # not decode further (the engine would generate past the point
            # the undisturbed stream stopped — only the finish chunk was
            # lost with the dead replica). Mirrors _emit's stop logic.
            fin = None
            if resume_ids:
                last = resume_ids[-1]
                if (((last in st.engine._eos_set and not ignore_eos)
                     or last in stop_token_ids)
                        and len(resume_ids) > min_tokens):
                    fin = "stop"
            if fin is None and len(resume_ids) >= max_tokens:
                fin = "length"
            if fin is not None:
                rid = ("chatcmpl-" if chat else "cmpl-") \
                    + uuid.uuid4().hex[:24]
                return self._finished_stream(
                    rid, chat, model, fin, n_prompt=len(prompt_ids),
                    n_gen=len(resume_ids), include_usage=include_usage)
        # best_of ranking needs each candidate's chosen-token logprobs; ask
        # the engine for them even when the client didn't (the response
        # strips them again — lp_requested below).
        rank = best_of > n_choices
        eng_lp = lp_n if lp_n is not None else (0 if rank else None)
        reqs = []
        try:
            # n/best_of: independent engine requests riding the same
            # continuous batch — the OpenAI semantics; identical for
            # temperature=0. Each sibling prefills the prompt itself (the
            # prefix cache only consults on ISOLATED arrivals, and the
            # siblings queue together), so n multiplies prefill cost.
            # Multi-choice streams share one wakeup event across the sibling
            # out_queues so the handler blocks instead of polling n queues.
            notify = threading.Event() if (stream and best_of > 1) else None
            for i in range(best_of):
                reqs.append(st.engine.generate(
                    prompt_ids, max_tokens=max_tokens,
                    temperature=temperature,
                    top_k=top_k, top_p=top_p, stream=stream, logprobs=eng_lp,
                    presence_penalty=presence_penalty,
                    frequency_penalty=frequency_penalty,
                    repetition_penalty=repetition_penalty,
                    stop_token_ids=stop_token_ids, min_tokens=min_tokens,
                    logit_bias=logit_bias, guided=guided,
                    ignore_eos=ignore_eos,
                    lora=lora_name, prompt_logprobs=plp,
                    deadline_s=deadline_s, resume_ids=resume_ids,
                    seed=None if seed is None else seed + i,
                    **({"out_queue": _NotifyQueue(notify)} if notify else {})))
        except EngineOverloaded as e:
            # a later sibling can shed as the queue fills — don't strand the
            # already-queued ones
            for r in reqs:
                st.engine.cancel(r)
            return self._overloaded(e)
        except ContextLengthExceeded as e:
            # Same wire shape the reference's vLLM returns for an oversized
            # prompt (VERDICT r1: silent tail-truncation answered a different
            # question than the client asked).
            return self._error(400, str(e),
                               err_code="context_length_exceeded")
        except ValueError as e:
            # engine-side request validation (e.g. min_tokens ban-list cap)
            return self._error(400, str(e))

        rid = ("chatcmpl-" if chat else "cmpl-") + uuid.uuid4().hex[:24]
        # hand the engine requests to the tracing wrapper: their monotonic
        # timestamps become the phase spans after the response is written
        self._trace_reqs = reqs
        if self._trace_ctx is not None:
            # bind the span identity into each engine request's flight
            # timeline: an anomaly dump hoists these to its top level, so
            # /debug/flight/<id> hands back the exact ids to paste into
            # Tempo beside the PR 5 phase spans
            for r in reqs:
                # also onto the request itself: the engine's histogram
                # observe points use it as the OpenMetrics exemplar
                r.trace_id = self._trace_ctx.trace_id
                flightrec.record("trace", r.id,
                                 trace_id=self._trace_ctx.trace_id,
                                 span_id=self._trace_ctx.span_id,
                                 api_id=rid)
        if stream:
            self._stream_response(reqs, rid, chat, stops, model=model,
                                  n_prompt=len(prompt_ids),
                                  include_usage=include_usage,
                                  echo_text=prompt_text if echo else None,
                                  lp_k=lp_n, resume_ids=resume_ids,
                                  resume_chars=resume_chars,
                                  is_resume=raw_resume is not None)
        else:
            self._full_response(reqs, rid, chat, stops, len(prompt_ids),
                                model=model,
                                n_choices=n_choices,
                                lp_requested=lp_n is not None,
                                echo_text=prompt_text if echo else None)

    def _full_response(self, reqs, rid: str, chat: bool, stops: List[str],
                       n_prompt: int = 0, model: Optional[str] = None,
                       n_choices: Optional[int] = None,
                       lp_requested: bool = True,
                       echo_text: Optional[str] = None):
        """Collect finished candidates into the response. When ``reqs``
        exceeds ``n_choices`` (best_of), rank candidates by cumulative
        chosen-token logprob and keep the best n. ``lp_requested=False``
        strips the internal ranking logprobs from the payload; ``echo_text``
        (completions ``echo``) prepends the prompt to each choice."""
        st = self.state
        n_choices = len(reqs) if n_choices is None else n_choices
        done = []
        completion_tokens = 0
        for req in reqs:
            try:
                ids = req.wait(timeout=_wait_budget_s(st.engine, req))
            except TimeoutError:
                # backstop only: the engine normally reaps the deadline
                # itself and this wait returns with finish_reason "timeout"
                for other in reqs:
                    st.engine.cancel(other)
                return self._error(408, "request timed out awaiting the "
                                        "engine", "timeout",
                                   err_code="deadline_exceeded")
            if req.finish_reason in ("error", "timeout"):
                for other in reqs:   # don't strand the sibling choices'
                    if other is not req:   # slots generating to max_tokens
                        st.engine.cancel(other)
                if req.finish_reason == "timeout":
                    return self._error(
                        408, "request deadline exceeded before completion "
                             "(slot and pages released)", "timeout",
                        err_code="deadline_exceeded")
                return self._error(500, "engine failure: "
                                   + (st.engine.last_error or "unknown"),
                                   "internal_error")
            completion_tokens += len(ids)
            done.append((req, ids))
        if len(done) > n_choices:
            # OpenAI best_of ranking: highest cumulative log probability of
            # the sampled tokens wins (the vLLM ordering)
            def score(pair):
                return sum(d[0] for d in pair[0].logprob_data
                           if d is not None)
            done.sort(key=score, reverse=True)
            done = done[:n_choices]
        choices = []
        for idx, (req, ids) in enumerate(done):
            text = st.tokenizer.decode(ids)
            finish = req.finish_reason
            cut = _apply_stop_strings(text, stops)
            if cut is not None:
                text, finish = cut, "stop"
            lp_obj = None
            if req.logprobs is not None and lp_requested:
                # align with a stop-string cut only when one happened: per-
                # token decode lengths can exceed the merged text's length
                # (multi-byte sequences), so unconditional truncation would
                # drop tail tokens
                lp_obj = _format_logprobs(
                    st.tokenizer, ids, req.logprob_data, req.logprobs, chat,
                    text_len=len(text) if cut is not None else -1,
                    base_offset=len(echo_text) if echo_text else 0)
            if echo_text is not None and req.prompt_logprob_data \
                    and lp_obj is not None and not chat:
                # OpenAI legacy echo+logprobs: the payload covers PROMPT +
                # generated; position 0 carries null (no context to score)
                ptoks = [st.tokenizer.decode([i]) for i in req.prompt_ids]
                poffs, p0 = [], 0
                for t in ptoks:
                    poffs.append(p0)
                    p0 += len(t)
                tail = req.prompt_logprob_data[1:]
                k = req.logprobs or 0
                pown = [None] + [d[0] for d in tail]
                ptop = [None] + [
                    {st.tokenizer.decode([tid]): v for tid, v in d[1][:k]}
                    for d in tail]
                lp_obj = {"tokens": ptoks + lp_obj["tokens"],
                          "token_logprobs": pown + lp_obj["token_logprobs"],
                          "top_logprobs": ptop + lp_obj["top_logprobs"],
                          "text_offset": poffs + lp_obj["text_offset"]}
            if echo_text is not None:
                text = echo_text + text
            if chat:
                choice = {"index": idx, "message": {"role": "assistant",
                                                    "content": text},
                          "finish_reason": finish}
                if lp_obj is not None:
                    choice["logprobs"] = lp_obj
            else:
                choice = {"index": idx, "text": text, "logprobs": lp_obj,
                          "finish_reason": finish}
            if req.prompt_logprob_data:
                # vLLM-style field: list over prompt positions; each entry
                # maps decoded token -> logprob (chosen + top-k)
                pl = [None]
                for t, d in enumerate(req.prompt_logprob_data[1:], start=1):
                    entry = {st.tokenizer.decode([req.prompt_ids[t]]): d[0]}
                    for tid, v in d[1][:req.prompt_logprobs or 0]:
                        entry.setdefault(st.tokenizer.decode([tid]), v)
                    pl.append(entry)
                choice["prompt_logprobs"] = pl
            choices.append(choice)
        usage = {"prompt_tokens": n_prompt,
                 "completion_tokens": completion_tokens,
                 "total_tokens": n_prompt + completion_tokens}
        if self._trace_ctx is not None:
            # log correlation without header plumbing: the ids a client
            # pastes into Tempo to find this request's span tree
            usage["trace_id"] = self._trace_ctx.trace_id
            usage["span_id"] = self._trace_ctx.span_id
        self._json(200, {"id": rid,
                         "object": "chat.completion" if chat
                         else "text_completion",
                         "created": _now(),
                         "model": model or st.model_name,
                         "choices": choices, "usage": usage})

    def _finished_stream(self, rid: str, chat: bool, model: Optional[str],
                         finish: str, n_prompt: int, n_gen: int,
                         include_usage: bool):
        """Degenerate continuation: the relayed prefix already satisfied a
        stop condition — only the finish chunk (+usage, [DONE]) was lost
        with the dead replica, so answer those directly without admitting
        anything to the engine."""
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        obj = "chat.completion.chunk" if chat else "text_completion"

        def raw_write(data: bytes):
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            self.wfile.flush()

        payload = {"index": 0, "finish_reason": finish}
        if chat:
            payload["delta"] = {}
        else:
            payload["text"] = ""
        body = {"id": rid, "object": obj, "created": _now(),
                "model": model or self.state.model_name,
                "choices": [payload]}
        if include_usage:
            body["usage"] = None
        raw_write(f"data: {json.dumps(body)}\n\n".encode())
        if include_usage:
            usage = {"prompt_tokens": n_prompt,
                     "completion_tokens": n_gen,
                     "total_tokens": n_prompt + n_gen}
            if self._trace_ctx is not None:
                usage["trace_id"] = self._trace_ctx.trace_id
                usage["span_id"] = self._trace_ctx.span_id
            raw_write(("data: " + json.dumps({
                "id": rid, "object": obj, "created": _now(),
                "model": model or self.state.model_name, "choices": [],
                "usage": usage,
                "failover": True}) + "\n\n").encode())
        raw_write(b"data: [DONE]\n\n")
        self.wfile.write(b"0\r\n\r\n")
        self.wfile.flush()

    def _stream_response(self, reqs, rid: str, chat: bool, stops: List[str],
                         model: Optional[str] = None,
                         n_prompt: int = 0, include_usage: bool = False,
                         echo_text: Optional[str] = None,
                         lp_k: Optional[int] = None,
                         resume_ids: tuple = (), resume_chars: int = 0,
                         is_resume: bool = False):
        """SSE streaming with incremental detokenization (n choices).

        Correctness over eagerness: text is held back while it could still be
        (a) the tail of an incomplete multi-byte character (detokenizer handles
        this) or (b) a prefix of a stop string (``hold`` chars withheld), so a
        client never sees bytes that a later token retroactively changes.
        A broken pipe cancels the engine request so the decode slot frees.

        Every content chunk carries the generated ``token_ids`` it covers —
        the router buffers them per stream so a replica death mid-stream can
        fail over as a deterministic continuation. A continuation
        (``is_resume``) pre-feeds the detokenizer with the already-relayed
        ``resume_ids`` and SKIPS the first ``resume_chars`` of generated
        text: the client receives only chunks it hasn't seen, and the
        concatenated stream is byte-identical to an undisturbed run.
        """
        from aws_k8s_ansible_provisioner_tpu.utils.tokenizer import (
            IncrementalDetokenizer)
        from aws_k8s_ansible_provisioner_tpu.serving import chaos as _chaos

        st = self.state
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def raw_write(data: bytes):
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            self.wfile.flush()

        obj = "chat.completion.chunk" if chat else "text_completion"
        _sent = {"chunks": 0}

        def chunk(idx: int, delta_text: Optional[str],
                  finish_reason: Optional[str], role: bool = False,
                  lp: Optional[dict] = None,
                  tok_ids: Optional[List[int]] = None):
            payload = {"index": idx, "finish_reason": finish_reason}
            if chat:
                d = {}
                if role:
                    d["role"] = "assistant"
                if delta_text:
                    d["content"] = delta_text
                payload["delta"] = d
            else:
                payload["text"] = delta_text or ""
            if lp is not None:
                payload["logprobs"] = lp
            if tok_ids:
                # failover bookkeeping (r8): the generated token ids this
                # chunk covers. OpenAI clients ignore the extra field; the
                # router accumulates them so a mid-stream replica death can
                # re-issue the request as a deterministic continuation.
                payload["token_ids"] = [int(t) for t in tok_ids]
            body = {"id": rid, "object": obj, "created": _now(),
                    "model": model or st.model_name,
                    "choices": [payload]}
            if include_usage:
                # OpenAI stream_options.include_usage: every content chunk
                # carries usage: null; the final stats ride a dedicated
                # choices-less chunk before [DONE]
                body["usage"] = None
            raw_write(f"data: {json.dumps(body)}\n\n".encode())
            if delta_text or tok_ids:
                _sent["chunks"] += 1
                ch = _chaos.get()
                if ch.enabled:
                    # kill_replica_after_chunks fault point: may RST the
                    # connection and raise (unwound like a broken pipe)
                    ch.on_stream_chunk(self, _sent["chunks"])

        def consume_skip(s, text: str) -> str:
            """Drop the leading chars a failed-over client already received
            (continuation streams only; no-op otherwise)."""
            if s["skip"] and text:
                k = min(s["skip"], len(text))
                s["skip"] -= k
                text = text[k:]
            return text

        # Per-choice state: the n > 1 sibling requests ride the same
        # continuous batch, so their tokens arrive interleaved — each choice
        # detokenizes, stop-string-holds, and finishes independently, tagged
        # by its chunk "index" (the OpenAI multi-choice stream shape).
        hold = max((len(s) for s in stops if s), default=1) - 1
        base_off = len(echo_text) if echo_text else 0
        states = [{"req": r, "detok": IncrementalDetokenizer(st.tokenizer),
                   "pending": "", "finish": None, "n_lp": 0, "skip": 0,
                   "carry": "", "tok_pending": [],
                   "acc": "", "offset": base_off} for r in reqs]
        multi = len(states) > 1
        if is_resume and states:
            # Continuation: rebuild the detokenizer over the already-relayed
            # tokens so the first NEW token's delta merges correctly, then
            # arm the skip that drops what the client already has. The
            # flushed prior text re-enters the normal pending/hold pipeline
            # (non-lp) or the first chunk's carry (lp) — whatever the dead
            # replica had flushed-but-held arrives with the first new chunk.
            s = states[0]
            prior = "".join(s["detok"].push(int(t)) for t in resume_ids)
            skip = min(int(resume_chars), len(prior))
            s["acc"] = prior
            s["offset"] = base_off + len(prior)
            if lp_k is not None:
                s["carry"] = prior
            else:
                s["pending"] = prior
            s["skip"] = skip

        def token_lp(s, token: int, delta: str):
            """Per-token logprob payload for a streamed chunk — the vLLM
            shape: completions carry parallel one-element arrays, chat a
            one-element content list. logprob_data[k] is guaranteed present
            before the k-th token reaches the queue (engine._emit order)."""
            d = s["req"].logprob_data[s["n_lp"]] \
                if s["n_lp"] < len(s["req"].logprob_data) else None
            s["n_lp"] += 1
            tok_str = st.tokenizer.decode([token])
            own = None if d is None else d[0]
            tops = [] if d is None else \
                [(st.tokenizer.decode([tid]), v) for tid, v in d[1][:lp_k]]
            if chat:
                return {"content": [{
                    "token": tok_str, "logprob": own,
                    "top_logprobs": [{"token": t, "logprob": v}
                                     for t, v in tops]}]}
            off = s["offset"]
            s["offset"] += len(delta)
            return {"tokens": [tok_str], "token_logprobs": [own],
                    "top_logprobs": [dict(tops)], "text_offset": [off]}

        def drain(i: int, block_s: float) -> bool:
            """Advance choice i by at most one queue item; emit any ready
            text. Returns whether an item arrived."""
            s = states[i]
            try:
                item = s["req"].out_queue.get(timeout=block_s)
            except queue.Empty:
                return False
            if lp_k is not None:
                # Per-TOKEN chunks so the logprob arrays align with their
                # token: each queue item emits one chunk carrying that
                # token's text delta (possibly "" while a multi-byte
                # sequence is incomplete) and its logprob record. Stop
                # strings cut the accumulated text without holdback (the
                # already-sent token entries stand — vLLM's streamed
                # behavior has the same artifact).
                if item is None:
                    tail = s["detok"].finish()
                    s["finish"] = s["req"].finish_reason or "stop"
                    tail = consume_skip(s, s["carry"] + tail)
                    s["carry"] = ""
                    if tail:
                        chunk(i, tail, None)
                    chunk(i, None, s["finish"])
                    return True
                delta = s["detok"].push(item)
                # windowed stop scan: only the region a NEW stop match could
                # end in (delta + the longest stop's tail) — scanning the
                # whole accumulated text would be O(len^2) per stream
                # (review r4). Matches wholly inside older text were caught
                # on earlier tokens.
                window = (s["acc"][-hold:] if hold else "") + delta
                s["acc"] += delta
                cut = _apply_stop_strings(window, stops)
                if cut is not None:
                    overshoot = len(window) - len(cut)
                    delta = delta[:len(delta) - overshoot] \
                        if overshoot <= len(delta) else ""
                    s["finish"] = "stop"
                    st.engine.cancel(s["req"])
                if s["carry"]:
                    # continuation: the rebuilt prior text (beyond what the
                    # client already has — consume_skip drops that part)
                    # rides the first new token's chunk
                    delta, s["carry"] = s["carry"] + delta, ""
                delta = consume_skip(s, delta)
                chunk(i, delta, None, lp=token_lp(s, item, delta),
                      tok_ids=[int(item)])
                if s["finish"]:
                    chunk(i, None, s["finish"])
                return True
            if item is None:
                s["pending"] += s["detok"].finish()
                s["finish"] = s["req"].finish_reason or "stop"
            else:
                s["pending"] += s["detok"].push(item)
                s["tok_pending"].append(int(item))
            cut_text = _apply_stop_strings(s["pending"], stops)
            if cut_text is not None:
                s["pending"], s["finish"] = cut_text, "stop"
                st.engine.cancel(s["req"])  # free the slot; rest discarded
            ready = s["pending"] if s["finish"] else (
                s["pending"][:len(s["pending"]) - hold] if hold
                else s["pending"])
            if ready:
                send = consume_skip(s, ready)
                if send or s["tok_pending"]:
                    chunk(i, send, None, tok_ids=s["tok_pending"])
                    s["tok_pending"] = []
                s["pending"] = s["pending"][len(ready):]
            if s["finish"]:
                chunk(i, None, s["finish"], tok_ids=s["tok_pending"])
                s["tok_pending"] = []
            return True

        # No-progress backstop (r7): the configured deadline default, not a
        # hardcoded 600 — the engine reaps per-request deadlines and sends
        # sentinels, so this only guards against a wedged engine loop.
        # Config 0 = unbounded (capped at threading's wait ceiling, ~49
        # days, because queue.get cannot take infinity).
        stall_s = float(st.engine.serving.request_timeout_s or 0)
        if stall_s <= 0:
            stall_s = threading.TIMEOUT_MAX
        try:
            for i in range(len(states)):
                if is_resume:
                    # the client got the role/echo chunk from the replica
                    # that died; a continuation re-sending it would splice
                    # duplicate bytes into the stream
                    break
                if chat:
                    chunk(i, "", None, role=True)
                elif echo_text:
                    # completions echo+stream: the prompt leads each
                    # choice's stream (vLLM's behavior)
                    chunk(i, echo_text, None)
            last_progress = time.monotonic()
            while any(s["finish"] is None for s in states):
                progressed = False
                for i, s in enumerate(states):
                    if s["finish"] is not None:
                        continue
                    if multi:
                        # drain every available item without blocking — a
                        # per-choice blocking slice would cap a fast
                        # choice's delta rate at one token per idle-sibling
                        # timeout (review r4); the single sleep below is
                        # the only wait when ALL queues are empty
                        while s["finish"] is None and drain(i, 0.0):
                            progressed = True
                    else:
                        progressed |= drain(i, stall_s)
                if progressed:
                    last_progress = time.monotonic()
                elif multi:
                    if time.monotonic() - last_progress > stall_s:
                        raise TimeoutError(
                            f"no stream progress in {stall_s:.0f}s")
                    ev = getattr(states[0]["req"].out_queue, "event", None)
                    if ev is not None:
                        # wait → clear → re-drain: a put racing the clear
                        # leaves its item in the queue for the drain sweep,
                        # and a put after the clear re-sets the event, so no
                        # wakeup is ever lost.
                        ev.wait(timeout=1.0)
                        ev.clear()
                    else:
                        # siblings submitted without the shared event (direct
                        # callers constructing their own reqs)
                        time.sleep(0.01)
                elif time.monotonic() - last_progress > stall_s:
                    raise TimeoutError(
                        f"no stream progress in {stall_s:.0f}s")
            if include_usage:
                # generated includes the resume prefix on a continuation, so
                # usage matches the undisturbed run; ``failover: true`` is
                # the client-visible marker that this stream was failed over
                n_gen = sum(len(s["req"].generated) for s in states)
                usage = {"prompt_tokens": n_prompt,
                         "completion_tokens": n_gen,
                         "total_tokens": n_prompt + n_gen}
                if self._trace_ctx is not None:
                    usage["trace_id"] = self._trace_ctx.trace_id
                    usage["span_id"] = self._trace_ctx.span_id
                final = {
                    "id": rid, "object": obj, "created": _now(),
                    "model": model or st.model_name, "choices": [],
                    "usage": usage,
                }
                if is_resume:
                    final["failover"] = True
                raw_write(("data: " + json.dumps(final) + "\n\n").encode())
            raw_write(b"data: [DONE]\n\n")
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            for s in states:
                st.engine.cancel(s["req"])
        except Exception:
            # headers already sent: can't switch to a JSON error response now;
            # free the slots and drop the connection.
            log.exception("stream failed mid-flight")
            for s in states:
                st.engine.cancel(s["req"])
            raise BrokenPipeError  # handled (ignored) by do_POST


# ---------------------------------------------------------------------------
# Assembly
# ---------------------------------------------------------------------------


def build_state(serving_cfg=None, model_cfg=None, params=None,
                tokenizer=None) -> ServerState:
    """Wire tokenizer + params + engine + templater into a ServerState.

    With a checkpoint dir: real weights + real tokenizer. Without: random weights
    + byte tokenizer (offline dry-run mode — BASELINE.json config #1's CPU-only
    path needs the full stack to run with zero downloads).
    """
    import jax
    import jax.numpy as jnp

    from aws_k8s_ansible_provisioner_tpu.config import (
        MODEL_REGISTRY, ServingConfig, tiny_qwen3)
    from aws_k8s_ansible_provisioner_tpu.models import (
        config_from_hf_dir, init_params)
    from aws_k8s_ansible_provisioner_tpu.serving.chat_template import ChatTemplater
    from aws_k8s_ansible_provisioner_tpu.serving.engine import Engine
    from aws_k8s_ansible_provisioner_tpu.utils.tokenizer import load_tokenizer

    serving = serving_cfg or ServingConfig()
    ckpt = serving.checkpoint_dir or None

    if tokenizer is None:
        tokenizer = load_tokenizer(ckpt)

    if model_cfg is None:
        if ckpt:
            model_cfg = config_from_hf_dir(ckpt)
        elif serving.model in MODEL_REGISTRY:
            model_cfg = MODEL_REGISTRY[serving.model]
        elif serving.model == "tiny-qwen3":
            # offline dry-run model sized to the byte tokenizer
            model_cfg = tiny_qwen3(vocab_size=tokenizer.vocab_size,
                                   eos_token_id=tokenizer.eos_token_id,
                                   num_layers=4, hidden_size=128,
                                   intermediate_size=256)
        elif serving.model == "tiny-qwen3-moe":
            from aws_k8s_ansible_provisioner_tpu.config import tiny_qwen3_moe

            model_cfg = tiny_qwen3_moe(vocab_size=tokenizer.vocab_size,
                                       eos_token_id=tokenizer.eos_token_id,
                                       num_layers=4, hidden_size=128)
        elif serving.model == "tiny-gemma":
            from aws_k8s_ansible_provisioner_tpu.config import tiny_gemma

            model_cfg = tiny_gemma(vocab_size=tokenizer.vocab_size,
                                   eos_token_id=tokenizer.eos_token_id,
                                   num_layers=4, hidden_size=128)
        elif serving.model == "tiny-mistral":
            from aws_k8s_ansible_provisioner_tpu.config import tiny_mistral

            model_cfg = tiny_mistral(vocab_size=tokenizer.vocab_size,
                                     eos_token_id=tokenizer.eos_token_id,
                                     num_layers=4, hidden_size=128,
                                     sliding_window=32)
        else:
            raise ValueError(f"unknown model {serving.model!r} and no checkpoint")

    dtype = jnp.bfloat16 if serving.dtype == "bfloat16" else jnp.float32
    # Build the serving mesh BEFORE loading weights so an 8B checkpoint can
    # load directly sharded (per-device transfer = the shard; no chip ever
    # holds the full model — the --tp 8 / v5e-8 path, SURVEY.md §7 #3).
    mesh = Engine._build_mesh(serving)
    if params is None:
        if ckpt:
            # Cached conversion: first start converts safetensors and writes an
            # orbax cache next to the checkpoint; restarts restore directly
            # (sharded restore when a mesh is configured).
            from aws_k8s_ansible_provisioner_tpu.models.checkpoint import (
                load_checkpoint_cached)

            params = load_checkpoint_cached(ckpt, model_cfg, dtype, mesh=mesh)
        else:
            log.warning("no checkpoint_dir: serving RANDOM weights (%s) — "
                        "dry-run/benchmark mode only", model_cfg.name)
            params = init_params(model_cfg, jax.random.PRNGKey(0), dtype)

    draft = None
    if serving.spec_decode and serving.spec_method == "draft":
        if not serving.draft_checkpoint_dir:
            raise ValueError("spec_method='draft' requires "
                             "--draft-checkpoint-dir")
        from aws_k8s_ansible_provisioner_tpu.models.checkpoint import (
            load_checkpoint_cached)

        draft_cfg = config_from_hf_dir(serving.draft_checkpoint_dir)
        # the draft is small by design: load unsharded (serving/draft.py
        # runs it replicated beside the sharded target)
        draft_params = load_checkpoint_cached(serving.draft_checkpoint_dir,
                                              draft_cfg, dtype, mesh=None)
        draft = (draft_cfg, draft_params)
        log.info("draft model: %s (%s)", draft_cfg.name,
                 serving.draft_checkpoint_dir)
    lora = None
    if serving.lora_adapters:
        lora = {}
        for spec in serving.lora_adapters:
            name, sep, path = spec.partition("=")
            if not sep or not name or not path:
                raise ValueError(f"--lora expects name=path, got {spec!r}")
            if name in lora:
                raise ValueError(f"duplicate LoRA adapter name {name!r}")
            if name == serving.model:
                raise ValueError(f"LoRA adapter name {name!r} would shadow "
                                 f"the served base model id")
            lora[name] = path
    engine = Engine(model_cfg, params, serving,
                    eos_token_id=tokenizer.eos_token_id, mesh=mesh,
                    draft=draft, lora=lora)
    templater = ChatTemplater(model_cfg.name, tokenizer,
                              template_path=serving.chat_template or None)
    state = ServerState(engine, tokenizer, templater, serving.model)
    # Tracing: config endpoint wins; empty falls back to the manifest's
    # $OTEL_EXPORTER_OTLP_ENDPOINT; neither set = spans created (ids still
    # echo into responses) but never exported.
    state.tracer = tracing.build_tracer(
        "tpu-serve-engine",
        endpoint=getattr(serving, "otlp_endpoint", "") or None,
        sample=getattr(serving, "trace_sample", 1.0))
    # Flight recorder + SLO engine: module singletons the engine's record/
    # finish shorthands already write through — configure() swaps in the
    # served settings (spool dir, objectives) atomically.
    flightrec.configure(
        spool_dir=getattr(serving, "flight_spool_dir", "") or "")
    slo.configure(
        ttft_p95_ms=getattr(serving, "slo_ttft_p95_ms", 0.0),
        error_rate=getattr(serving, "slo_error_rate", 0.01))
    # Device telemetry: configure() carries over the cost model + HBM
    # samplers the engine installed during construction above.
    devmon.configure(
        enabled=getattr(serving, "devmon_enabled", True),
        peak_tflops=getattr(serving, "devmon_peak_tflops", 197.0),
        hbm_gbps=getattr(serving, "devmon_peak_hbm_gbps", 819.0),
        hbm_tolerance_mb=getattr(serving, "devmon_hbm_tolerance_mb", 64.0))
    # Capacity estimator: configure() carries over the engine closures
    # (queue depth, throughput fallback) installed during construction.
    capacity.configure(
        enabled=getattr(serving, "capacity_enabled", True),
        headroom_s=getattr(serving, "capacity_headroom_s", 5.5),
        window_s=getattr(serving, "capacity_window_s", 60.0),
        trend_window_s=getattr(serving, "capacity_trend_window_s", 300.0))
    return state


def serve(state: ServerState, host: str, port: int,
          ready_event: Optional[threading.Event] = None,
          stop_event: Optional[threading.Event] = None):
    """Run engine thread + HTTP server until stop_event (or forever).

    The HTTP server always runs on its own thread and this function blocks
    on ``stop_event`` — the one shape that lets a SIGTERM handler or
    POST /admin/drain stop the process from any thread after a graceful
    drain (state.begin_drain sets the stop once in-flight work finishes)."""
    stop = stop_event or threading.Event()
    state.stop_event = stop
    engine_thread = threading.Thread(
        target=state.engine.run_forever, args=(stop,), daemon=True,
        name="engine-loop")
    engine_thread.start()

    class BoundHandler(Handler):
        pass

    BoundHandler.state = state
    httpd = ThreadingHTTPServer((host, port), BoundHandler)
    httpd.daemon_threads = True
    log.info("serving %s on %s:%d (%d slots, cache %d)", state.model_name,
             host, port, state.engine.num_slots, state.engine.max_len)
    server_thread = threading.Thread(target=httpd.serve_forever,
                                     daemon=True, name="http")
    server_thread.start()
    if ready_event is not None:
        ready_event.set()
    try:
        stop.wait()
    except KeyboardInterrupt:
        stop.set()
    httpd.shutdown()
    # Close the LISTENING socket too: shutdown() only stops the accept
    # loop, leaving connects to land in the kernel backlog and black-hole
    # — a stopped replica must refuse connections so a gateway's
    # connect-phase failover (router.py) sees it dead immediately.
    httpd.server_close()


def main(argv=None):
    from aws_k8s_ansible_provisioner_tpu.config import ServingConfig

    p = argparse.ArgumentParser(description="TPU-native OpenAI-compatible "
                                            "LLM server")
    p.add_argument("--model", default="Qwen/Qwen3-0.6B")
    p.add_argument("--checkpoint-dir", default="")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--max-decode-slots", type=int, default=32)
    p.add_argument("--max-cache-len", type=int, default=2048)
    p.add_argument("--dtype", default="bfloat16",
                   choices=["bfloat16", "float32"])
    p.add_argument("--kv-dtype", default="auto", choices=["auto", "int8"],
                   help="KV-cache storage dtype; int8 halves cache HBM "
                        "footprint/bandwidth (~2x the decode slots per chip)")
    p.add_argument("--weights-dtype", default="int8",
                   choices=["int8", "bf16", "auto"],
                   help="weight storage dtype; int8 (the shipped default) "
                        "halves the weight HBM stream — the dominant "
                        "bytes/token term at small batch (weights-only "
                        "per-channel quantization; compute stays bf16 on "
                        "the MXU). 'bf16' (alias 'auto') is the explicit "
                        "full-precision opt-out")
    p.add_argument("--decode-bblock", type=int, default=0,
                   help="decode kernel batch-block (slots per grid step); "
                        "0 = autotune over {1,4,8} at startup (TPU only)")
    p.add_argument("--decode-pipeline", type=int, default=1,
                   help="one-deep asynchronous decode pipeline: dispatch "
                        "N+1 is enqueued before N's tokens are fetched, "
                        "hiding host emit/SSE time behind device compute "
                        "(seeded streams stay byte-identical). 0 restores "
                        "the synchronous dispatch-fetch-emit loop")
    p.add_argument("--ragged-attention", type=int, default=1,
                   help="ragged mixed-batch attention: chunked prefill "
                        "packs into the SAME dispatch as the decode batch "
                        "(one program, paged pool), so admissions stop "
                        "draining the decode pipeline. 0 restores the "
                        "legacy serialized chunk walk (sync escape hatch; "
                        "seeded streams stay byte-identical)")
    p.add_argument("--ragged-features", type=int, default=1,
                   help="feature paths ride the ragged pipeline: guided "
                        "decoding's FSM mask becomes a device-resident "
                        "per-row operand, LoRA rows select adapters inside "
                        "the packed layout, and spec-decode verify hands "
                        "the carry off without draining. 0 restores the "
                        "per-feature sync fallback (byte-identity A/B arm)")
    p.add_argument("--kv-host-tier-bytes", type=int, default=256 * 2**20,
                   help="byte budget for the tier-2 host-RAM KV store: "
                        "evicted prefix pages spill here and restore via "
                        "one batched device_put instead of re-prefilling "
                        "(paged mode only). 0 disables the tier — the "
                        "byte-identity escape hatch")
    p.add_argument("--chat-template", default="",
                   help="path to a Jinja chat template file")
    p.add_argument("--platform", default="",
                   help="force a JAX platform (e.g. cpu for dry-run)")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel degree (shards heads/MLP over the "
                        "ICI mesh; needs tp devices)")
    p.add_argument("--dp", type=int, default=1,
                   help="data-parallel degree (shards decode slots)")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel degree (shards the KV cache's "
                        "sequence axis — the long-context axis; decode "
                        "merges per-shard flash partials over ICI)")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel degree (MoE models: shards experts "
                        "over the mesh; GSPMD emits the dispatch collectives)")
    p.add_argument("--prefill-chunk", type=int, default=0,
                   help="chunked prefill size; 0 disables (long prompts "
                        "then cap at the largest bucket)")
    p.add_argument("--no-prefix-cache", action="store_true",
                   help="disable automatic prompt-prefix K/V reuse")
    p.add_argument("--spec-decode", action="store_true",
                   help="prompt-lookup speculative decoding (greedy-lossless "
                        "multi-token steps; runs single-device and under "
                        "pure-tp meshes)")
    p.add_argument("--spec-k", type=int, default=4,
                   help="draft tokens per speculative step")
    p.add_argument("--spec-method", default="prompt_lookup",
                   choices=["prompt_lookup", "draft"],
                   help="proposal source: context n-gram matching, or a "
                        "small draft LM (--draft-checkpoint-dir)")
    p.add_argument("--draft-checkpoint-dir", default="",
                   help="HF checkpoint dir of the draft model "
                        "(spec_method=draft)")
    p.add_argument("--lora", action="append", default=[],
                   metavar="NAME=PATH",
                   help="register a peft LoRA adapter dir, served as model "
                        "id NAME (repeatable; vLLM --enable-lora parity)")
    p.add_argument("--request-timeout", type=float, default=600.0,
                   help="default/maximum end-to-end deadline in seconds "
                        "(per-request X-Request-Deadline-Ms / deadline_ms "
                        "is capped by it; 0 disables)")
    p.add_argument("--max-queue-depth", type=int, default=256,
                   help="bounded engine queue: admissions past this depth "
                        "are shed with 429 + Retry-After (0 = unbounded)")
    p.add_argument("--drain-timeout", type=float, default=30.0,
                   help="graceful-drain budget in seconds: on SIGTERM or "
                        "POST /admin/drain, stop admitting (503 draining, "
                        "/readyz 503) and let in-flight requests finish up "
                        "to this long before exiting 0; stragglers are "
                        "cancelled through the deadline path")
    p.add_argument("--admission-max-wait", type=float, default=0.0,
                   help="shed admissions whose estimated queue wait "
                        "(seconds) exceeds this (0 disables)")
    p.add_argument("--otlp-endpoint", default="",
                   help="OTLP/HTTP trace collector base URL (spans POST to "
                        "<endpoint>/v1/traces); empty falls back to "
                        "$OTEL_EXPORTER_OTLP_ENDPOINT, neither = tracing "
                        "stays local (ids still echo in responses)")
    p.add_argument("--trace-sample", type=float, default=1.0,
                   help="root-span sampling probability in [0, 1]; "
                        "propagated contexts keep the caller's decision")
    p.add_argument("--slo-ttft-p95-ms", type=float, default=0.0,
                   help="TTFT p95 objective in milliseconds: first tokens "
                        "slower than this burn the 5%% latency error budget "
                        "(tpu_serve_slo_burn_rate{objective=\"ttft_p95\"}); "
                        "0 disables the objective")
    p.add_argument("--slo-error-rate", type=float, default=0.01,
                   help="error-rate SLO budget: the allowed fraction of "
                        "requests finishing error/timeout; burn rate 1.0 "
                        "means failing exactly at budget (0 disables)")
    p.add_argument("--flight-spool-dir", default="",
                   help="directory for the flight recorder's anomaly dump "
                        "spool (capped JSONL; rolled at 16 MiB); empty "
                        "keeps dumps in memory only (/debug/flight/<id>)")
    p.add_argument("--devmon-peak-tflops", type=float, default=197.0,
                   help="per-chip peak TFLOP/s the tpu_device_mfu gauges "
                        "divide by (default: v5e bf16; set per TPU "
                        "generation)")
    p.add_argument("--devmon-peak-hbm-gbps", type=float, default=819.0,
                   help="per-chip peak HBM GB/s the tpu_device_membw_util "
                        "gauges divide by (default: v5e)")
    p.add_argument("--devmon-hbm-tolerance-mb", type=float, default=64.0,
                   help="live-vs-compiled HBM drift tolerance in MB before "
                        "the /healthz hbm_drift verdict flips to 'warn' "
                        "(warn-only; never fails probes)")
    p.add_argument("--no-devmon", action="store_true",
                   help="disable device telemetry recording (the "
                        "tpu_device_* gauges freeze at their defaults)")
    p.add_argument("--capacity-headroom-s", type=float, default=5.5,
                   help="forecast headroom the recommended_replicas figure "
                        "buys, in seconds — set to the AOT registry's "
                        "measured ready-time (BENCH_coldstart_r01: 5.5 s) "
                        "so a replica started on the signal is serving "
                        "before the projected demand lands")
    p.add_argument("--capacity-window-s", type=float, default=60.0,
                   help="sliding window for the offered-load and "
                        "utilization rates (tpu_capacity_offered_tps)")
    p.add_argument("--capacity-trend-window-s", type=float, default=300.0,
                   help="longer window the saturation forecast fits its "
                        "EWMA + linear trend over")
    p.add_argument("--no-capacity", action="store_true",
                   help="disable the capacity estimator (the "
                        "tpu_capacity_* gauges freeze at their defaults; "
                        "/healthz keeps an empty-ish capacity block)")
    p.add_argument("--no-warmup", action="store_true")
    p.add_argument("--aot-manifest", default="",
                   help="AOT compile manifest (serving/aot.py) to adopt: "
                        "fingerprint-checked against this engine, HBM fit "
                        "enforced, ledger surfaced on /healthz and the "
                        "tpu_serve_hbm_compiled_bytes gauge")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)

    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    # Persistent XLA compilation cache: warmup compiles ~13 programs (20-40s
    # each on TPU); a CONTAINER restart (the liveness probe's stall-recovery
    # kick) must not pay that again. The serving manifest backs the path
    # with an emptyDir and pins JAX_COMPILATION_CACHE_DIR to it — pod-level
    # restarts (rollout, node drain) start cold; back the path with a PVC if
    # rollout survival matters. Env JAX_COMPILATION_CACHE_DIR overrides.
    cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(tempfile.gettempdir(), "tpu-serve-xla-cache"))
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    # tpulint: disable=R3 startup nicety — a missing compile cache slows warmup but must never block serving; warning carries the traceback
    except Exception:
        log.warning("persistent compile cache unavailable", exc_info=True)

    from aws_k8s_ansible_provisioner_tpu.config import MeshConfig

    serving = ServingConfig(
        model=args.model, port=args.port, host=args.host,
        max_decode_slots=args.max_decode_slots,
        max_cache_len=args.max_cache_len, dtype=args.dtype,
        kv_dtype=args.kv_dtype, weights_dtype=args.weights_dtype,
        decode_bblock=args.decode_bblock,
        decode_pipeline=args.decode_pipeline,
        ragged_attention=args.ragged_attention,
        ragged_features=args.ragged_features,
        kv_host_tier_bytes=args.kv_host_tier_bytes,
        checkpoint_dir=args.checkpoint_dir, chat_template=args.chat_template,
        prefill_chunk=args.prefill_chunk,
        prefix_cache=not args.no_prefix_cache,
        spec_decode=args.spec_decode, spec_k=args.spec_k,
        spec_method=args.spec_method,
        draft_checkpoint_dir=args.draft_checkpoint_dir,
        lora_adapters=tuple(args.lora),
        request_timeout_s=args.request_timeout,
        max_queue_depth=args.max_queue_depth,
        admission_max_wait_s=args.admission_max_wait,
        drain_timeout_s=args.drain_timeout,
        otlp_endpoint=args.otlp_endpoint,
        trace_sample=args.trace_sample,
        slo_ttft_p95_ms=args.slo_ttft_p95_ms,
        slo_error_rate=args.slo_error_rate,
        flight_spool_dir=args.flight_spool_dir,
        devmon_enabled=not args.no_devmon,
        devmon_peak_tflops=args.devmon_peak_tflops,
        devmon_peak_hbm_gbps=args.devmon_peak_hbm_gbps,
        devmon_hbm_tolerance_mb=args.devmon_hbm_tolerance_mb,
        capacity_enabled=not args.no_capacity,
        capacity_headroom_s=args.capacity_headroom_s,
        capacity_window_s=args.capacity_window_s,
        capacity_trend_window_s=args.capacity_trend_window_s,
        mesh=MeshConfig(dp=args.dp, tp=args.tp, sp=args.sp, ep=args.ep))
    state = build_state(serving)
    if args.aot_manifest:
        # Fail fast BEFORE warmup: a mismatched or no-fit manifest means the
        # deploy pipeline compiled a different program set than this engine
        # would dispatch — compiling anyway just delays the error to OOM.
        aot = state.engine.load_aot_manifest(args.aot_manifest)
        log.info("AOT manifest adopted: %d programs, %.1fs compile on "
                 "%s, HBM %.2f GiB/chip (headroom %.2f GiB)",
                 aot["programs"], aot["total_compile_seconds"],
                 aot["platform"], aot["hbm_total_bytes"] / 2**30,
                 aot["hbm_headroom_bytes"] / 2**30)
    if not args.no_warmup:
        log.info("warmup: compiling %d prefill buckets + decode ...",
                 len(state.engine.buckets))
        t0 = time.monotonic()
        state.engine.warmup()
        log.info("warmup done in %.1fs", time.monotonic() - t0)
    # Graceful termination (r8): SIGTERM (k8s pod deletion, after the
    # preStop hook's explicit /admin/drain) flips the engine to draining —
    # new requests shed 503, /readyz 503 so the Service stops routing here,
    # in-flight requests finish up to drain_timeout_s — then serve()'s stop
    # fires and the process exits 0 with zero dropped in-flight requests.
    import signal

    def _on_sigterm(signum, frame):
        log.info("SIGTERM: graceful drain (timeout %.1fs)",
                 args.drain_timeout)
        state.begin_drain()

    signal.signal(signal.SIGTERM, _on_sigterm)
    serve(state, args.host, args.port)
    log.info("drained and stopped; exiting 0")


if __name__ == "__main__":
    main()
