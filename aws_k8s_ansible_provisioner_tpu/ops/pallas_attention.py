"""Pallas TPU kernel: ragged decode attention over the slot-contiguous KV cache.

This is the hot loop of the whole framework — the TPU-native equivalent of the
paged-attention CUDA kernels inside the reference's external vLLM engine
(SURVEY.md §3.3: "the true hot loop (token-by-token decode on the GPU) lives
entirely inside the external vLLM container"; §7 hard part #1). One program
instance handles one decode slot; the KV cache streams HBM→VMEM in chunks with
flash-style online softmax, so per-step cost is cache-bandwidth-bound with no
[B, S] float32 logits materialization in HBM.

Raggedness (every slot at a different length) is handled two ways:
- masking: key columns ≥ length contribute -inf logits;
- *DMA skipping*: the chunk index_map clamps dead chunks (beyond the slot's
  length) to the last live chunk — Pallas skips re-fetch when a block index
  repeats, so a slot at length 130 reads ~2 chunks of cache, not S/CHUNK.
  With the identity block table of the slot-contiguous head-major cache
  (serving/kv_cache.py pages_view), this IS paged attention: chunk c of
  (slot b, head h) is page ``(b*Hkv + h)*pages_per_stream + c``.

GQA grouping stays in-kernel: per KV head h, the G=Hq/Hkv query rows attend to
one [CHUNK, D] K/V stream — no repeat_kv copy ever exists (the same design as
the XLA fallback in ops/attention.py, here with explicit VMEM control).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _pick_chunk(S: int, chunk: int, interpret: bool, quant: bool) -> int:
    """Largest legal cache-chunk size dividing the window S.

    On real TPU the block shapes impose tiling rules Mosaic enforces at
    lowering: the K/V block's sublane dim is the chunk (multiple of 8 for
    bf16, 32 for int8), and the quantized path's scale block has the chunk on
    the LANE axis (multiple of 128, or the full dimension). The engine's
    windows are 256-aligned so the preferred chunk survives; this guard keeps
    odd windows (or odd sp shards) compiling instead of dying in Mosaic.
    Interpret mode (CPU tests) has no such constraints.
    """
    chunk = min(chunk, S)
    while S % chunk:
        chunk -= 1
    if interpret or chunk == S:
        return chunk
    # quant: scale-block lane rule (128) also covers the int8 sublane rule
    # (32); bf16/f32 caches only need the sublane rule (16 covers both).
    align = 128 if quant else 16
    if chunk % align == 0:
        return chunk
    best = next((c for c in range(chunk // align * align, align - 1, -align)
                 if S % c == 0), None)
    return best if best is not None else S


def decode_attend_pallas(q: jnp.ndarray, cache_k: jnp.ndarray,
                         cache_v: jnp.ndarray, lengths: jnp.ndarray,
                         chunk: int = 256, interpret: bool = False) -> jnp.ndarray:
    """Flash decode attention: q [B,1,Hq,D] over ONE layer's cache [B,Hkv,S,D]
    (head-major, see serving/kv_cache.py), ragged by ``lengths`` [B] (counting
    the just-written token). Returns [B,1,Hq,D].

    Thin wrapper over the layer-indexed production kernel (the serving engine
    always decodes against the full stacked cache; this single-layer form is
    the parity-test surface and the API for callers holding one layer).
    """
    return decode_attend_pallas_layer(q, cache_k[None], cache_v[None], lengths,
                                      jnp.int32(0), chunk=chunk,
                                      interpret=interpret)


def _decode_kernel_layer(lengths_ref,      # scalar prefetch [B] int32
                         layer_ref,        # scalar prefetch [1] int32
                         q_ref,            # [1, Hq, D]
                         k_ref,            # [1, 1, Hkv, CHUNK, D]
                         v_ref,            # [1, 1, Hkv, CHUNK, D]
                         o_ref,            # [1, Hq, D]
                         acc_ref, m_ref, l_ref,
                         *, chunk: int, groups: int, scale: float,
                         window: int = 0):
    """Same flash accumulation as ``_decode_kernel`` but over the FULL
    [L, B, Hkv, S, D] cache: the layer index arrives as a scalar-prefetch value
    and the index_map selects the layer block, so the carry-path decode
    (models/layers.model_forward_carry) never materializes a per-layer cache
    slice in HBM. ``window`` > 0 = sliding-window attention: only the last
    ``window`` columns are live; chunks entirely below it are skipped (their
    DMA was already clamped away by the index map)."""
    b = pl.program_id(0)
    c = pl.program_id(1)
    num_chunks = pl.num_programs(1)
    length = lengths_ref[b]
    hq, d = q_ref.shape[1], q_ref.shape[2]
    hkv = k_ref.shape[2]
    lo = jnp.maximum(length - window, 0) if window > 0 else 0

    @pl.when(c == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when((c * chunk < length) & ((c + 1) * chunk > lo))
    def _accumulate():
        q3 = (q_ref[0].astype(jnp.float32) * scale).reshape(hkv, groups, d)
        k3 = k_ref[0, 0].astype(jnp.float32)                      # [Hkv, C, D]
        s = jax.lax.dot_general(
            q3, k3, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)                   # [Hkv, G, C]
        s = s.reshape(hq, chunk)
        col = c * chunk + jax.lax.broadcasted_iota(jnp.int32, (hq, chunk), 1)
        s = jnp.where((col < length) & (col >= lo), s, NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)
        l_cur = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        v3 = v_ref[0, 0].astype(jnp.float32)                      # [Hkv, C, D]
        pv = jax.lax.dot_general(
            p.reshape(hkv, groups, chunk), v3,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)                   # [Hkv, G, D]
        acc_ref[:] = acc_ref[:] * corr + pv.reshape(hq, d)
        m_ref[:, :1] = m_cur
        l_ref[:, :1] = l_cur

    @pl.when(c == num_chunks - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-9)
        o_ref[0, :, :] = (acc_ref[:] / l).astype(o_ref.dtype)


def _decode_kernel_layer_q(lengths_ref,     # scalar prefetch [B] int32
                           layer_ref,       # scalar prefetch [1] int32
                           q_ref,           # [1, Hq, D]
                           k_ref,           # [1, 1, Hkv, CHUNK, D] int8
                           v_ref,           # [1, 1, Hkv, CHUNK, D] int8
                           ks_ref,          # [1, 1, Hkv, CHUNK] f32 scales
                           vs_ref,          # [1, 1, Hkv, CHUNK] f32 scales
                           o_ref, acc_ref, m_ref, l_ref,
                           *, chunk: int, groups: int, scale: float,
                           window: int = 0):
    """Int8-cache variant of ``_decode_kernel_layer``: K/V stream as int8 (half
    the HBM traffic of bf16 — the whole point; decode is cache-bandwidth-bound)
    and dequantization folds into the flash accumulation inside VMEM:
    ``q·(k_q*ks) == (q·k_q)*ks`` per key column, and ``p·(v_q*vs) ==
    (p*vs)·v_q`` per value row — the f32 cache never materializes anywhere.
    """
    b = pl.program_id(0)
    c = pl.program_id(1)
    num_chunks = pl.num_programs(1)
    length = lengths_ref[b]
    hq, d = q_ref.shape[1], q_ref.shape[2]
    hkv = k_ref.shape[2]
    lo = jnp.maximum(length - window, 0) if window > 0 else 0

    @pl.when(c == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when((c * chunk < length) & ((c + 1) * chunk > lo))
    def _accumulate():
        q3 = (q_ref[0].astype(jnp.float32) * scale).reshape(hkv, groups, d)
        k3 = k_ref[0, 0].astype(jnp.float32)                  # [Hkv, C, D]
        s = jax.lax.dot_general(
            q3, k3, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)               # [Hkv, G, C]
        s = s * ks_ref[0, 0][:, None, :]                      # fold k scales
        s = s.reshape(hq, chunk)
        col = c * chunk + jax.lax.broadcasted_iota(jnp.int32, (hq, chunk), 1)
        s = jnp.where((col < length) & (col >= lo), s, NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)
        l_cur = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        v3 = v_ref[0, 0].astype(jnp.float32)                  # [Hkv, C, D]
        p3 = p.reshape(hkv, groups, chunk) * vs_ref[0, 0][:, None, :]
        pv = jax.lax.dot_general(
            p3, v3, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)               # [Hkv, G, D]
        acc_ref[:] = acc_ref[:] * corr + pv.reshape(hq, d)
        m_ref[:, :1] = m_cur
        l_ref[:, :1] = l_cur

    @pl.when(c == num_chunks - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-9)
        o_ref[0, :, :] = (acc_ref[:] / l).astype(o_ref.dtype)


def _decode_kernel_layer_bb(lengths_ref,    # scalar prefetch [B] int32
                            layer_ref,      # scalar prefetch [1] int32
                            q_ref,          # [BB, Hq, D]
                            k_ref,          # [1, BB, Hkv, CHUNK, D]
                            v_ref,          # [1, BB, Hkv, CHUNK, D]
                            o_ref,          # [BB, Hq, D]
                            acc_ref, m_ref, l_ref,   # [BB, Hq, *]
                            *, chunk: int, groups: int, scale: float,
                            bb: int, window: int = 0, quant: bool = False,
                            ks_ref=None, vs_ref=None):
    """Batch-blocked flash decode: BB slots per grid step.

    The round-5 TPU decomposition (BENCH_session_r5.json) put the decode
    substep at ~3x its bandwidth bound; at grid (B=128, chunks=4) x 28
    layers each step streams only ~0.5 MB, so fixed per-grid-step cost
    (DMA issue + kernel overhead, ~1 us class) rivals the stream time
    itself. Blocking BB slots into one grid step multiplies the DMA size
    by BB and divides the step count by BB, pushing the kernel back toward
    the stream bound. Trade: the chunk-skip clamp must cover the LONGEST
    slot in the block (shorter slots' dead chunks ride along), so blocks
    of similar-length slots waste nothing and mixed blocks pay up to
    (max-min) extra rows — the engine's slot allocator is FCFS, which
    correlates neighbors' ages. Gated by PALLAS_DECODE_BBLOCK until
    measured on hardware (the recovery sweep carries it).
    """
    bbi = pl.program_id(0)
    c = pl.program_id(1)
    num_chunks = pl.num_programs(1)
    hq, d = q_ref.shape[1], q_ref.shape[2]
    hkv = k_ref.shape[2]
    lens = jnp.stack([lengths_ref[bbi * bb + i] for i in range(bb)])  # [BB]
    max_len = jnp.max(lens)
    lo = jnp.maximum(lens - window, 0) if window > 0 else \
        jnp.zeros_like(lens)
    lo_min = jnp.min(lo)

    @pl.when(c == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when((c * chunk < max_len) & ((c + 1) * chunk > lo_min))
    def _accumulate():
        q3 = (q_ref[:].astype(jnp.float32) * scale) \
            .reshape(bb * hkv, groups, d)
        k3 = k_ref[0].astype(jnp.float32).reshape(bb * hkv, chunk, d)
        s = jax.lax.dot_general(
            q3, k3, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)        # [BB*Hkv, G, C]
        if quant:
            s = s * ks_ref[0].reshape(bb * hkv, chunk)[:, None, :]
        s = s.reshape(bb, hq, chunk)
        col = c * chunk + jax.lax.broadcasted_iota(jnp.int32,
                                                   (bb, hq, chunk), 2)
        live = (col < lens[:, None, None]) & (col >= lo[:, None, None])
        s = jnp.where(live, s, NEG_INF)
        m_prev = m_ref[:, :, :1]
        l_prev = l_ref[:, :, :1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)
        l_cur = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        v3 = v_ref[0].astype(jnp.float32).reshape(bb * hkv, chunk, d)
        p3 = p.reshape(bb * hkv, groups, chunk)
        if quant:
            p3 = p3 * vs_ref[0].reshape(bb * hkv, chunk)[:, None, :]
        pv = jax.lax.dot_general(
            p3, v3, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)        # [BB*Hkv, G, D]
        acc_ref[:] = acc_ref[:] * corr + pv.reshape(bb, hq, d)
        m_ref[:, :, :1] = m_cur
        l_ref[:, :, :1] = l_cur

    @pl.when(c == num_chunks - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :, :1], 1e-9)
        o_ref[:] = (acc_ref[:] / l).astype(o_ref.dtype)


def _decode_kernel_layer_q_bb(lengths_ref, layer_ref, q_ref, k_ref, v_ref,
                              ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref,
                              *, chunk: int, groups: int, scale: float,
                              bb: int, window: int = 0):
    """Int8 batch-blocked variant: scale folding as in
    _decode_kernel_layer_q, DMA batching as in _decode_kernel_layer_bb."""
    _decode_kernel_layer_bb(lengths_ref, layer_ref, q_ref, k_ref, v_ref,
                            o_ref, acc_ref, m_ref, l_ref, chunk=chunk,
                            groups=groups, scale=scale, bb=bb,
                            window=window, quant=True, ks_ref=ks_ref,
                            vs_ref=vs_ref)


def _decode_kernel_layer_q_stats(lengths_ref, layer_ref, q_ref, k_ref, v_ref,
                                 ks_ref, vs_ref, o_ref, mo_ref, lo_ref,
                                 acc_ref, m_ref, l_ref,
                                 *, chunk: int, groups: int, scale: float,
                                 window: int = 0):
    """Stats-emitting int8 variant (sequence-parallel decode merge)."""
    _decode_kernel_layer_q(lengths_ref, layer_ref, q_ref, k_ref, v_ref,
                           ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref,
                           chunk=chunk, groups=groups, scale=scale,
                           window=window)
    c = pl.program_id(1)

    @pl.when(c == pl.num_programs(1) - 1)
    def _emit_stats():
        o_ref[0, :, :] = acc_ref[:].astype(o_ref.dtype)  # overwrite normalized
        mo_ref[0] = jnp.broadcast_to(m_ref[:, :1], mo_ref.shape[1:])
        lo_ref[0] = jnp.broadcast_to(l_ref[:, :1], lo_ref.shape[1:])


def _decode_kernel_layer_stats(lengths_ref, layer_ref, q_ref, k_ref, v_ref,
                               o_ref,       # [1, Hq, D] f32 UNNORMALIZED acc
                               mo_ref,      # [1, Hq, 128] f32 running max
                               lo_ref,      # [1, Hq, 128] f32 running denom
                               acc_ref, m_ref, l_ref,
                               *, chunk: int, groups: int, scale: float,
                               window: int = 0):
    """Stats-emitting variant for sequence-parallel decode: instead of the
    normalized context, outputs the raw flash triple (acc, m, l) so the
    caller can merge partials across sequence shards with a log-sum-exp
    combine (ops/attention.py sp path). A shard holding none of a slot's rows
    emits (0, -inf, 0), which contributes nothing to the merge."""
    _decode_kernel_layer(lengths_ref, layer_ref, q_ref, k_ref, v_ref,
                         o_ref, acc_ref, m_ref, l_ref,
                         chunk=chunk, groups=groups, scale=scale,
                         window=window)
    c = pl.program_id(1)

    @pl.when(c == pl.num_programs(1) - 1)
    def _emit_stats():
        o_ref[0, :, :] = acc_ref[:].astype(o_ref.dtype)  # overwrite normalized
        mo_ref[0] = jnp.broadcast_to(m_ref[:, :1], mo_ref.shape[1:])
        lo_ref[0] = jnp.broadcast_to(l_ref[:, :1], lo_ref.shape[1:])


@functools.partial(jax.jit,
                   static_argnames=("chunk", "interpret", "return_stats",
                                    "window", "bblock"))
def decode_attend_pallas_layer(q: jnp.ndarray, cache_k: jnp.ndarray,
                               cache_v: jnp.ndarray, lengths: jnp.ndarray,
                               layer: jnp.ndarray, chunk: int = 256,
                               interpret: bool = False,
                               return_stats: bool = False,
                               cache_ks: jnp.ndarray = None,
                               cache_vs: jnp.ndarray = None,
                               window: int = 0,
                               bblock: int = None):
    """Flash decode attention over ONE layer of the full stacked cache.

    q: [B, 1, Hq, D]; cache_k/v: [L, B, Hkv, S, D] (the whole cache buffer —
    no per-layer slice is ever cut); lengths: [B] (counting the just-written
    token); layer: scalar int32. Returns [B, 1, Hq, D].

    With ``cache_ks``/``cache_vs`` ([L, B, Hkv, S] f32) the cache is int8 and
    the kernel dequantizes in VMEM by folding the per-row scales into the
    flash accumulation (see _decode_kernel_layer_q) — half the HBM streaming
    of the bf16 cache.

    The hot path of the carry-based decode loop: only the live chunks of the
    selected layer stream HBM→VMEM (same DMA-skip clamping as
    ``decode_attend_pallas``); everything else in the 4-GB-scale cache is
    untouched.
    """
    B, _, Hq, D = q.shape
    Hkv, S = cache_k.shape[2], cache_k.shape[3]
    groups = Hq // Hkv
    quant = cache_ks is not None
    chunk = _pick_chunk(S, chunk, interpret, quant)
    num_chunks = S // chunk
    lengths = lengths.astype(jnp.int32)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)

    def q_map(b, c, lens, lay):
        return (b, 0, 0)

    def _clamped(b, c, lens):
        # live chunk range [lo, hi]: above the slot's length AND (with a
        # sliding window) below its window start, chunks clamp to the range
        # edge — Pallas skips the repeated fetch, so dead cache never moves
        hi = jnp.maximum(pl.cdiv(lens[b], chunk) - 1, 0)
        if window > 0:
            lo_chunk = jnp.maximum(lens[b] - window, 0) // chunk
            return jnp.clip(c, lo_chunk, hi)
        return jnp.minimum(c, hi)

    def kv_map(b, c, lens, lay):
        return (lay[0], b, 0, _clamped(b, c, lens), 0)

    def scale_map(b, c, lens, lay):
        return (lay[0], b, 0, _clamped(b, c, lens))

    scratch = [
        pltpu.VMEM((Hq, D), jnp.float32),
        pltpu.VMEM((Hq, 128), jnp.float32),
        pltpu.VMEM((Hq, 128), jnp.float32),
    ]
    in_specs = [
        pl.BlockSpec((1, Hq, D), q_map),
        pl.BlockSpec((1, 1, Hkv, chunk, D), kv_map),
        pl.BlockSpec((1, 1, Hkv, chunk, D), kv_map),
    ]
    operands = [q[:, 0], cache_k, cache_v]
    if quant:
        # chunk on the LANE axis: legal because _pick_chunk forces a
        # 128-multiple (or full-S) chunk on the compiled path
        in_specs += [pl.BlockSpec((1, 1, Hkv, chunk), scale_map)] * 2
        operands += [cache_ks, cache_vs]
    scale = 1.0 / (D ** 0.5)
    if return_stats:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, num_chunks),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, Hq, D), q_map),
                pl.BlockSpec((1, Hq, 128), q_map),
                pl.BlockSpec((1, Hq, 128), q_map),
            ],
            scratch_shapes=scratch,
        )
        kernel = functools.partial(
            _decode_kernel_layer_q_stats if quant
            else _decode_kernel_layer_stats,
            chunk=chunk, groups=groups, scale=scale, window=window)
        acc, m, l = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((B, Hq, D), jnp.float32),
                jax.ShapeDtypeStruct((B, Hq, 128), jnp.float32),
                jax.ShapeDtypeStruct((B, Hq, 128), jnp.float32),
            ],
            interpret=interpret,
        )(lengths, layer_arr, *operands)
        # stats are replicated along the 128-lane axis; take lane 0
        return acc, m[:, :, 0], l[:, :, 0]
    # Batch-blocking (PALLAS_DECODE_BBLOCK, default off): BB slots per grid
    # step — BBx bigger DMAs, BB-fewer grid steps; see
    # _decode_kernel_layer_bb for the measured rationale. Resolved to the
    # largest divisor of B not exceeding the requested block.
    bb = int(os.environ.get("PALLAS_DECODE_BBLOCK", "0") or 0) \
        if bblock is None else bblock
    bb = max(1, min(bb, B)) if bb else 1
    while B % bb:
        bb -= 1
    if bb > 1:
        def q_map_bb(g, c, lens, lay):
            return (g, 0, 0)

        def _clamped_bb(g, c, lens):
            # the block's live range covers its LONGEST slot (and, with a
            # window, its EARLIEST window start)
            hi = jnp.int32(0)
            lo_chunk = None
            for i in range(bb):
                ln = lens[g * bb + i]
                hi = jnp.maximum(hi, pl.cdiv(ln, chunk) - 1)
                if window > 0:
                    lc = jnp.maximum(ln - window, 0) // chunk
                    lo_chunk = lc if lo_chunk is None \
                        else jnp.minimum(lo_chunk, lc)
            hi = jnp.maximum(hi, 0)
            if window > 0:
                return jnp.clip(c, lo_chunk, hi)
            return jnp.minimum(c, hi)

        def kv_map_bb(g, c, lens, lay):
            return (lay[0], g, 0, _clamped_bb(g, c, lens), 0)

        def scale_map_bb(g, c, lens, lay):
            return (lay[0], g, 0, _clamped_bb(g, c, lens))

        in_specs_bb = [
            pl.BlockSpec((bb, Hq, D), q_map_bb),
            pl.BlockSpec((1, bb, Hkv, chunk, D), kv_map_bb),
            pl.BlockSpec((1, bb, Hkv, chunk, D), kv_map_bb),
        ]
        if quant:
            in_specs_bb += [pl.BlockSpec((1, bb, Hkv, chunk),
                                         scale_map_bb)] * 2
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B // bb, num_chunks),
            in_specs=in_specs_bb,
            out_specs=pl.BlockSpec((bb, Hq, D), q_map_bb),
            scratch_shapes=[
                pltpu.VMEM((bb, Hq, D), jnp.float32),
                pltpu.VMEM((bb, Hq, 128), jnp.float32),
                pltpu.VMEM((bb, Hq, 128), jnp.float32),
            ],
        )
        kernel = functools.partial(
            _decode_kernel_layer_q_bb if quant else _decode_kernel_layer_bb,
            chunk=chunk, groups=groups, scale=scale, bb=bb, window=window)
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
            interpret=interpret,
        )(lengths, layer_arr, *operands)
        return out[:, None]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, num_chunks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, Hq, D), q_map),
        scratch_shapes=scratch,
    )
    kernel = functools.partial(
        _decode_kernel_layer_q if quant else _decode_kernel_layer,
        chunk=chunk, groups=groups, scale=scale, window=window)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        interpret=interpret,
    )(lengths, layer_arr, *operands)
    return out[:, None]


def _spec_accumulate(lengths_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
                     o_ref, acc_ref, m_ref, l_ref,
                     *, chunk: int, groups: int, scale: float, R: int,
                     window: int = 0):
    """Shared body for the R-draft speculative decode kernels.

    q_ref: [1, R*Hq, D] — R query rows per slot (the last accepted token plus
    R-1 draft continuations), rows ordered (draft, head). Query row r may see
    cache columns < lengths[b] + 1 + r (its own just-written row included).
    The K/V chunk streams ONCE per grid step and is reused by all R queries —
    the whole point of verifying drafts in one pass: R tokens for one cache
    read. ks/vs fold int8 scales when present (None = bf16 cache).
    """
    b = pl.program_id(0)
    c = pl.program_id(1)
    num_chunks = pl.num_programs(1)
    length = lengths_ref[b]
    d = q_ref.shape[2]
    hkv = k_ref.shape[2]
    hq = q_ref.shape[1] // R
    # below-window compute skip (row 0's window start bounds all R rows)
    lo = jnp.maximum(length + 1 - window, 0) if window > 0 else 0

    @pl.when(c == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when((c * chunk < length + R) & ((c + 1) * chunk > lo))
    def _accumulate():
        k3 = k_ref[0, 0].astype(jnp.float32)                  # [Hkv, C, D]
        v3 = v_ref[0, 0].astype(jnp.float32)
        for r in range(R):                                    # static unroll
            sl = slice(r * hq, (r + 1) * hq)
            q3 = (q_ref[0, sl].astype(jnp.float32) * scale
                  ).reshape(hkv, groups, d)
            s = jax.lax.dot_general(
                q3, k3, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)           # [Hkv, G, C]
            if ks_ref is not None:
                s = s * ks_ref[0, 0][:, None, :]
            s = s.reshape(hq, chunk)
            col = c * chunk + jax.lax.broadcasted_iota(
                jnp.int32, (hq, chunk), 1)
            live = col < length + 1 + r
            if window > 0:   # sliding window: row r sees its last W keys
                live = live & (col >= length + 1 + r - window)
            s = jnp.where(live, s, NEG_INF)
            m_prev = m_ref[sl, :1]
            l_prev = l_ref[sl, :1]
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            corr = jnp.exp(m_prev - m_cur)
            p = jnp.exp(s - m_cur)
            l_cur = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
            p3 = p.reshape(hkv, groups, chunk)
            if vs_ref is not None:
                p3 = p3 * vs_ref[0, 0][:, None, :]
            pv = jax.lax.dot_general(
                p3, v3, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)           # [Hkv, G, D]
            acc_ref[sl] = acc_ref[sl] * corr + pv.reshape(hq, d)
            m_ref[sl, :1] = m_cur
            l_ref[sl, :1] = l_cur

    @pl.when(c == num_chunks - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-9)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _spec_kernel_plain(lengths_ref, layer_ref, q_ref, k_ref, v_ref, o_ref,
                       acc_ref, m_ref, l_ref, **kw):
    _spec_accumulate(lengths_ref, q_ref, k_ref, v_ref, None, None,
                     o_ref, acc_ref, m_ref, l_ref, **kw)


def _spec_kernel_quant(lengths_ref, layer_ref, q_ref, k_ref, v_ref, ks_ref,
                       vs_ref, o_ref, acc_ref, m_ref, l_ref, **kw):
    _spec_accumulate(lengths_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
                     o_ref, acc_ref, m_ref, l_ref, **kw)


@functools.partial(jax.jit, static_argnames=("chunk", "interpret", "window"))
def decode_attend_pallas_spec(q: jnp.ndarray, cache_k: jnp.ndarray,
                              cache_v: jnp.ndarray, lengths: jnp.ndarray,
                              layer: jnp.ndarray, chunk: int = 256,
                              interpret: bool = False,
                              cache_ks: jnp.ndarray = None,
                              cache_vs: jnp.ndarray = None,
                              window: int = 0) -> jnp.ndarray:
    """Speculative-verify flash attention: R query rows per slot in one pass.

    q: [B, R, Hq, D] — row r is the query at position lengths[b] + r (the
    caller has already written all R K/V rows); returns [B, R, Hq, D]. Each
    query row masks to its own causal frontier (lengths + 1 + r). One cache
    stream serves all R rows, so verifying R-1 drafts costs ~one decode
    step's HBM traffic — the bandwidth economics that make prompt-lookup
    speculation profitable on a bandwidth-bound chip.
    """
    B, R, Hq, D = q.shape
    Hkv, S = cache_k.shape[2], cache_k.shape[3]
    groups = Hq // Hkv
    quant = cache_ks is not None
    chunk = _pick_chunk(S, chunk, interpret, quant)
    num_chunks = S // chunk
    lengths = lengths.astype(jnp.int32)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)

    def q_map(b, c, lens, lay):
        return (b, 0, 0)

    def _clamped(b, c, lens):
        hi = jnp.maximum(pl.cdiv(lens[b] + R, chunk) - 1, 0)
        if window > 0:
            # lowest chunk any of the R rows can see (row 0's window start)
            lo_chunk = jnp.maximum(lens[b] + 1 - window, 0) // chunk
            return jnp.clip(c, lo_chunk, hi)
        return jnp.minimum(c, hi)

    def kv_map(b, c, lens, lay):
        return (lay[0], b, 0, _clamped(b, c, lens), 0)

    def scale_map(b, c, lens, lay):
        return (lay[0], b, 0, _clamped(b, c, lens))

    in_specs = [
        pl.BlockSpec((1, R * Hq, D), q_map),
        pl.BlockSpec((1, 1, Hkv, chunk, D), kv_map),
        pl.BlockSpec((1, 1, Hkv, chunk, D), kv_map),
    ]
    operands = [q.reshape(B, R * Hq, D), cache_k, cache_v]
    if quant:
        in_specs += [pl.BlockSpec((1, 1, Hkv, chunk), scale_map)] * 2
        operands += [cache_ks, cache_vs]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, num_chunks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, R * Hq, D), q_map),
        scratch_shapes=[
            pltpu.VMEM((R * Hq, D), jnp.float32),
            pltpu.VMEM((R * Hq, 128), jnp.float32),
            pltpu.VMEM((R * Hq, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _spec_kernel_quant if quant else _spec_kernel_plain,
        chunk=chunk, groups=groups, scale=1.0 / (D ** 0.5), R=R,
        window=window)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, R * Hq, D), q.dtype),
        interpret=interpret,
    )(lengths, layer_arr, *operands)
    return out.reshape(B, R, Hq, D)


@functools.partial(jax.jit, static_argnames=("interpret",))
def cache_write_row(cache: jnp.ndarray, new: jnp.ndarray,
                    lengths: jnp.ndarray, layer: jnp.ndarray,
                    interpret: bool = False) -> jnp.ndarray:
    """Write one new K (or V) row per slot into the full cache, IN PLACE.

    cache: [L, B, Hkv, S, D]; new: [B, Hkv, D]; lengths: [B] (row index per
    slot — rows outside [0, S) are DROPPED, which both makes surplus
    mid-horizon writes safe and lets sequence-parallel shards pass
    ``global_row - shard_offset`` and have exactly the owning shard write);
    layer: scalar int32. Returns the updated cache — same buffer.

    Why a kernel for a 2 KB-per-slot write: the functional alternatives all
    copy. ``.at[layer, rows, :, lengths].set(...)`` lowers to scatter, and
    XLA's copy-insertion around scatters in while-loop carries materializes
    full-cache copies (measured: 7 copies of the 3.6 GB cache per decode step,
    22.9 GB accessed — 330 ms/token). ``input_output_aliases`` lowers to a
    custom call with output-operand aliasing, which buffer assignment MUST
    honor — the 938M-element buffer is never copied; each grid step DMAs one
    [Hkv, D] row. This is the TPU equivalent of vLLM's in-place
    ``cache_kernel`` CUDA writes (reference SURVEY.md §2.2 row 1).
    """
    L, B, Hkv, S, D = cache.shape
    lengths = lengths.astype(jnp.int32)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)
    # Pallas TPU blocks need the sublane dim divisible by 8: touch the 8-row
    # block containing the target row and mask the single row in (8 rows
    # in + out per slot ≈ 32 KB — still ~10^5x less traffic than the
    # full-cache copies this kernel exists to avoid).
    ROWS = 8 if S % 8 == 0 else S

    def new_map(b, lens, lay):
        return (b, 0, 0)

    def blk_map(b, lens, lay):
        # S-axis block size ROWS -> block index = row // ROWS. Out-of-window
        # rows (negative under sequence sharding, or >= S) clamp to a valid
        # block here and are DROPPED by the kernel's row mask — the scatter
        # mode='drop' contract.
        return (lay[0], b, 0, jnp.clip(lens[b], 0, S - 1) // ROWS, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Hkv, D), new_map),
            pl.BlockSpec((1, 1, Hkv, ROWS, D), blk_map),
        ],
        out_specs=pl.BlockSpec((1, 1, Hkv, ROWS, D), blk_map),
    )

    def kernel(lengths_ref, layer_ref, new_ref, cin_ref, cout_ref):
        b = pl.program_id(0)
        tgt = lengths_ref[b]
        in_window = (tgt >= 0) & (tgt < S)
        r = jnp.where(in_window, jnp.clip(tgt, 0, S - 1) % ROWS, -1)
        row = jax.lax.broadcasted_iota(jnp.int32, (Hkv, ROWS, D), 1)
        cout_ref[0, 0] = jnp.where(row == r, new_ref[0][:, None, :],
                                   cin_ref[0, 0])

    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(cache.shape, cache.dtype),
        input_output_aliases={3: 0},   # cache operand (after 2 scalars + new)
        interpret=interpret,
    )(lengths, layer_arr, new, cache)


@functools.partial(jax.jit, static_argnames=("interpret",))
def cache_write_row_quant(cache: jnp.ndarray, scales: jnp.ndarray,
                          new: jnp.ndarray, lengths: jnp.ndarray,
                          layer: jnp.ndarray, interpret: bool = False):
    """Quantizing variant of :func:`cache_write_row` for the int8 cache.

    cache: [L, B, Hkv, S, D] int8; scales: [L, B, Hkv, S] f32; new: [B, Hkv, D]
    float. Quantizes each new row per-head in VMEM (round-half-even, matching
    kv_cache.quantize_rows bit-for-bit so XLA-prefilled and Pallas-decoded
    rows are interchangeable) and writes the int8 row + its scale IN PLACE
    (both buffers aliased). Out-of-window rows drop, as in the bf16 kernel.
    Returns (cache, scales) — same buffers.
    """
    L, B, Hkv, S, D = cache.shape
    lengths = lengths.astype(jnp.int32)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)
    # int8 arrays tile as (32, 128) on TPU: touch a 32-row block (vs 8 for
    # bf16), falling back to the FULL window when 32 doesn't divide it (an
    # 8-row fallback would violate the int8 sublane rule in Mosaic). Still
    # ~128 KB in+out per slot — noise next to the full-cache copies this
    # kernel avoids.
    ROWS = 32 if S % 32 == 0 else S

    def new_map(b, lens, lay):
        return (b, 0, 0)

    def blk_map(b, lens, lay):
        return (lay[0], b, 0, jnp.clip(lens[b], 0, S - 1) // ROWS, 0)

    def scale_map(b, lens, lay):
        # Full-S scale block: S is the scales array's minormost (lane) axis
        # and a lane-axis block must be a 128-multiple or the full dimension —
        # a ROWS-sized block would fail Mosaic lowering. Hkv*S*4 bytes
        # in+out per slot is still noise next to the copies this avoids.
        return (lay[0], b, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Hkv, D), new_map),
            pl.BlockSpec((1, 1, Hkv, ROWS, D), blk_map),
            pl.BlockSpec((1, 1, Hkv, S), scale_map),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, Hkv, ROWS, D), blk_map),
            pl.BlockSpec((1, 1, Hkv, S), scale_map),
        ],
    )

    def kernel(lengths_ref, layer_ref, new_ref, cin_ref, sin_ref,
               cout_ref, sout_ref):
        b = pl.program_id(0)
        tgt = lengths_ref[b]
        in_window = (tgt >= 0) & (tgt < S)
        r = jnp.where(in_window, jnp.clip(tgt, 0, S - 1) % ROWS, -1)
        # the one shared quantizer (plain jnp ops, valid inside Pallas):
        # XLA-prefilled and Pallas-decoded rows MUST quantize identically
        from aws_k8s_ansible_provisioner_tpu.serving.kv_cache import (
            quantize_rows)

        q8, sc = quantize_rows(new_ref[0])                    # [Hkv,D],[Hkv]
        row = jax.lax.broadcasted_iota(jnp.int32, (Hkv, ROWS, D), 1)
        cout_ref[0, 0] = jnp.where(row == r, q8[:, None, :], cin_ref[0, 0])
        # scale block spans the whole window: target column is tgt itself
        # (masked to -1 out of window, matching the row-block drop)
        rs = jax.lax.broadcasted_iota(jnp.int32, (Hkv, S), 1)
        tgt_col = jnp.where(in_window, tgt, -1)
        sout_ref[0, 0] = jnp.where(rs == tgt_col, sc[:, None], sin_ref[0, 0])

    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(cache.shape, cache.dtype),
            jax.ShapeDtypeStruct(scales.shape, scales.dtype),
        ],
        input_output_aliases={3: 0, 4: 1},  # cache, scales (after 2 scalars + new)
        interpret=interpret,
    )(lengths, layer_arr, new, cache, scales)


# ---------------------------------------------------------------------------
# Paged variants: physical page pool + per-slot block tables, DOUBLE-BUFFERED
# ---------------------------------------------------------------------------
#
# The dense kernels above address chunk c of slot b at cache[(lay, b, :,
# c*CHUNK:(c+1)*CHUNK)] — an IDENTITY block table (kv_cache.pages_view). The
# paged variants below keep the same flash math but OWN their data movement:
# the pools stay in HBM (memory_space=ANY) and the kernel streams pages
# through a two-slot VMEM buffer with explicit async copies — page c+1's
# DMAs are issued BEFORE page c's flash update runs, so the fetch of the
# next page overlaps the compute of the current one instead of serializing
# behind it at a grid-step boundary.
#
# Why not the implicit grid pipeline (the pre-r6 implementation): with grid
# (B, max_pages) every (slot, page) pair is its own grid step, and the r5
# decomposition (PERF.md) measured ~14k such steps per fused substep, each
# moving only ~0.5 MB — fixed per-step cost (DMA issue + kernel dispatch,
# ~1 µs class) rivaled the stream time itself and pinned decode at ~36% of
# the HBM roofline. Here the grid is (B/BB,): one step per BLOCK of BB
# slots, the page loop lives inside the kernel (statically unrolled over the
# table width), and each buffer fill issues BB page copies back-to-back —
# BBx larger transfers in flight, BBx fewer grid steps, and dead pages
# (beyond a block's longest slot, or below its sliding-window start) are
# skipped outright rather than clamp-refetched. This is the TPU analogue of
# vLLM's paged-attention block indirection (SURVEY.md §2.2 row 1) crossed
# with the Ragged Paged Attention amortization argument (PAPERS.md): the
# page gather is done by the DMA engine, overlapped, in block-sized batches.
#
# The body's contract is RAGGED: a grid row is an arbitrary (table row,
# live-column limit) pair, not intrinsically "slot i decoding". The
# per-slot decode/spec entry points below are the identity-indirection
# special case; ragged_attend_pallas_paged exposes the general form — a
# packed mix of decode rows and prefill-chunk rows served by ONE dispatch
# (serving/programs.mixed_step rides it to keep the decode pipeline open
# across prefill admissions).
#
# ``bblock`` (BB) is the knob the engine autotunes at startup
# (Engine._resolve_decode_bblock: one-shot microbench over {1, 4, 8} per
# (batch, page_size, kv_dtype)); 1 remains valid and still double-buffers.


def _paged_db_body(lengths_ref, layer_ref, table_ref, q_ref, k_hbm, v_hbm,
                   ks_hbm, vs_hbm, o_ref, k_buf, v_buf, ks_buf, vs_buf,
                   acc_ref, m_ref, l_ref, sem,
                   *, ps: int, groups: int, scale: float, R: int, bb: int,
                   num_pages: int, window: int, spec: bool):
    """Shared double-buffered paged flash body (decode R=1 / spec-verify R>1,
    bf16 / int8 pools, full / sliding-window attention).

    One grid step handles BB slots end to end: init flash state, then walk
    the block's live logical pages [lo, hi] with a two-slot VMEM buffer —
    issue page c+1's copies, wait page c's, accumulate page c. The table is
    scalar-prefetched (SMEM), so physical ids resolve in-kernel with no HBM
    round trip. Per-slot raggedness inside a block rides the column mask
    (shorter slots' dead columns contribute exp(-1e30 - m) == 0 exactly once
    any live column has been seen — bit-identical to the skip-based
    single-slot accumulation); the per-slot page index clamps into the
    slot's OWN live range so a mixed block never fetches a neighbor's
    garbage table entries.
    """
    g = pl.program_id(0)
    lay = layer_ref[0]
    quant = ks_hbm is not None
    hq = q_ref.shape[1] // R
    d = q_ref.shape[2]
    hkv = k_buf.shape[2]
    lens = jnp.stack([lengths_ref[g * bb + i] for i in range(bb)])   # [BB]
    extent = lens + (R if spec else 0)
    hi = jnp.maximum(pl.cdiv(extent, ps) - 1, 0)                     # [BB]
    hi_max = jnp.max(hi)
    if window > 0:
        wstart = jnp.maximum(lens + (1 if spec else 0) - window, 0)
        lo = wstart // ps                                            # [BB]
        lo_min = jnp.min(lo)
    else:
        lo = jnp.zeros_like(lens)
        lo_min = jnp.int32(0)

    def live(c: int):
        # block-level liveness of logical page c (c is a python int): some
        # slot in the block still has rows there
        return (c <= hi_max) & (c >= lo_min)

    def copies(c: int, slot: int):
        """The block's page-c DMAs into buffer ``slot`` (created identically
        at start and wait time — the documented make_async_copy pattern)."""
        out = []
        for i in range(bb):
            # clamp into slot i's own live range: table entries past it may
            # be anything valid (scratch, stale) — never fetch them
            pg = table_ref[g * bb + i, jnp.clip(c, lo[i], hi[i])]
            out.append(pltpu.make_async_copy(
                k_hbm.at[lay, pg], k_buf.at[slot, i], sem.at[slot, i, 0]))
            out.append(pltpu.make_async_copy(
                v_hbm.at[lay, pg], v_buf.at[slot, i], sem.at[slot, i, 1]))
            if quant:
                out.append(pltpu.make_async_copy(
                    ks_hbm.at[lay, pg], ks_buf.at[slot, i],
                    sem.at[slot, i, 2]))
                out.append(pltpu.make_async_copy(
                    vs_hbm.at[lay, pg], vs_buf.at[slot, i],
                    sem.at[slot, i, 3]))
        return out

    def start(c: int):
        @pl.when(live(c))
        def _():
            for dma in copies(c, c % 2):
                dma.start()

    def wait(c: int):
        @pl.when(live(c))
        def _():
            for dma in copies(c, c % 2):
                dma.wait()

    acc_ref[:] = jnp.zeros_like(acc_ref)
    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    start(0)                           # prologue: first page in flight
    for c in range(num_pages):         # static unroll over the table width
        if c + 1 < num_pages:
            start(c + 1)               # fetch page c+1 while computing c
        wait(c)

        @pl.when(live(c))
        def _accumulate(c=c):
            buf = c % 2
            k3 = k_buf[buf].astype(jnp.float32).reshape(bb * hkv, ps, d)
            v3 = v_buf[buf].astype(jnp.float32).reshape(bb * hkv, ps, d)
            if quant:
                kscale = ks_buf[buf].reshape(bb * hkv, ps)
                vscale = vs_buf[buf].reshape(bb * hkv, ps)
            for r in range(R):         # static unroll over draft rows
                sl = slice(r * hq, (r + 1) * hq)
                q3 = (q_ref[:, sl].astype(jnp.float32) * scale) \
                    .reshape(bb * hkv, groups, d)
                s = jax.lax.dot_general(
                    q3, k3, (((2,), (2,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)   # [BB*Hkv, G, ps]
                if quant:
                    s = s * kscale[:, None, :]
                s = s.reshape(bb, hq, ps)
                col = c * ps + jax.lax.broadcasted_iota(jnp.int32,
                                                        (bb, hq, ps), 2)
                limit = lens[:, None, None] + (1 + r if spec else 0)
                live_col = col < limit
                if window > 0:
                    live_col &= col >= limit - window
                s = jnp.where(live_col, s, NEG_INF)
                m_prev = m_ref[:, sl, :1]
                l_prev = l_ref[:, sl, :1]
                m_cur = jnp.maximum(m_prev,
                                    jnp.max(s, axis=-1, keepdims=True))
                corr = jnp.exp(m_prev - m_cur)
                p = jnp.exp(s - m_cur)
                l_cur = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
                p3 = p.reshape(bb * hkv, groups, ps)
                if quant:
                    p3 = p3 * vscale[:, None, :]
                pv = jax.lax.dot_general(
                    p3, v3, (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)   # [BB*Hkv, G, d]
                acc_ref[:, sl] = acc_ref[:, sl] * corr \
                    + pv.reshape(bb, hq, d)
                m_ref[:, sl, :1] = m_cur
                l_ref[:, sl, :1] = l_cur

    l_fin = jnp.maximum(l_ref[:, :, :1], 1e-9)
    o_ref[:] = (acc_ref[:] / l_fin).astype(o_ref.dtype)


def _paged_db_kernel(lengths_ref, layer_ref, table_ref, q_ref, k_hbm, v_hbm,
                     o_ref, k_buf, v_buf, acc_ref, m_ref, l_ref, sem, **kw):
    _paged_db_body(lengths_ref, layer_ref, table_ref, q_ref, k_hbm, v_hbm,
                   None, None, o_ref, k_buf, v_buf, None, None,
                   acc_ref, m_ref, l_ref, sem, **kw)


def _paged_db_kernel_quant(lengths_ref, layer_ref, table_ref, q_ref, k_hbm,
                           v_hbm, ks_hbm, vs_hbm, o_ref, k_buf, v_buf,
                           ks_buf, vs_buf, acc_ref, m_ref, l_ref, sem, **kw):
    _paged_db_body(lengths_ref, layer_ref, table_ref, q_ref, k_hbm, v_hbm,
                   ks_hbm, vs_hbm, o_ref, k_buf, v_buf, ks_buf, vs_buf,
                   acc_ref, m_ref, l_ref, sem, **kw)


def _resolve_bb(bblock, B: int) -> int:
    """Largest divisor of B not exceeding the requested block (>= 1)."""
    bb = max(1, min(int(bblock or 1), B))
    while B % bb:
        bb -= 1
    return bb


def _paged_flash_db(q2, pool_k, pool_v, lengths, layer_arr, table,
                    *, bb: int, R: int, spec: bool, window: int,
                    interpret: bool, pool_ks, pool_vs):
    """Build + dispatch the double-buffered paged flash call.

    q2: [B, R*Hq, D] (R=1 for plain decode). Grid is (B // bb,); the pools
    ride as ANY-memory-space operands (never blocked by Pallas — the kernel
    DMAs exactly the live pages), q/o are VMEM-blocked per slot block.
    """
    B, RHq, D = q2.shape
    Hkv, ps = pool_k.shape[2], pool_k.shape[3]
    groups = (RHq // R) // Hkv
    num_pages = table.shape[1]
    quant = pool_ks is not None

    def q_map(g, lens, lay, tab):
        return (g, 0, 0)

    in_specs = [
        pl.BlockSpec((bb, RHq, D), q_map),
        pl.BlockSpec(memory_space=pltpu.ANY),
        pl.BlockSpec(memory_space=pltpu.ANY),
    ]
    operands = [q2, pool_k, pool_v]
    if quant:
        in_specs += [pl.BlockSpec(memory_space=pltpu.ANY)] * 2
        operands += [pool_ks, pool_vs]
    scratch = [
        pltpu.VMEM((2, bb, Hkv, ps, D), pool_k.dtype),     # k page buffers
        pltpu.VMEM((2, bb, Hkv, ps, D), pool_v.dtype),     # v page buffers
    ]
    if quant:
        scratch += [pltpu.VMEM((2, bb, Hkv, ps), pool_ks.dtype)] * 2
    scratch += [
        pltpu.VMEM((bb, RHq, D), jnp.float32),             # acc
        pltpu.VMEM((bb, RHq, 128), jnp.float32),           # m
        pltpu.VMEM((bb, RHq, 128), jnp.float32),           # l
        pltpu.SemaphoreType.DMA((2, bb, 4 if quant else 2)),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B // bb,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bb, RHq, D), q_map),
        scratch_shapes=scratch,
    )
    kernel = functools.partial(
        _paged_db_kernel_quant if quant else _paged_db_kernel,
        ps=ps, groups=groups, scale=1.0 / (D ** 0.5), R=R, bb=bb,
        num_pages=num_pages, window=window, spec=spec)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, RHq, D), q2.dtype),
        interpret=interpret,
    )(lengths, layer_arr, table, *operands)


@functools.partial(jax.jit, static_argnames=("interpret", "window", "bblock"))
def decode_attend_pallas_paged(q: jnp.ndarray, pool_k: jnp.ndarray,
                               pool_v: jnp.ndarray, lengths: jnp.ndarray,
                               layer: jnp.ndarray, table: jnp.ndarray,
                               interpret: bool = False,
                               pool_ks: jnp.ndarray = None,
                               pool_vs: jnp.ndarray = None,
                               window: int = 0,
                               bblock: int = 1):
    """Double-buffered flash decode attention over one layer of the PAGED
    pool.

    q: [B, 1, Hq, D]; pool_k/v: [L, P, Hkv, page, D]; lengths: [B] (counting
    the just-written token); layer: scalar int32; table: [B, max_pages] int32
    physical page ids (row b maps slot b's logical pages; entries at or past
    the slot's live range may be any valid id — they are clamped away, never
    fetched). Returns [B, 1, Hq, D]. pool_ks/vs switch the int8 scale-folding
    body, as in the dense kernel. ``bblock`` slots share each grid step
    (resolved to the largest divisor of B); page i+1 prefetches while page i
    computes regardless of bblock — see _paged_db_body.
    """
    B = q.shape[0]
    lengths = lengths.astype(jnp.int32)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)
    out = _paged_flash_db(
        q[:, 0], pool_k, pool_v, lengths, layer_arr, table.astype(jnp.int32),
        bb=_resolve_bb(bblock, B), R=1, spec=False, window=window,
        interpret=interpret, pool_ks=pool_ks, pool_vs=pool_vs)
    return out[:, None]


@functools.partial(jax.jit, static_argnames=("interpret", "window", "bblock"))
def ragged_attend_pallas_paged(q: jnp.ndarray, pool_k: jnp.ndarray,
                               pool_v: jnp.ndarray, row_limits: jnp.ndarray,
                               layer: jnp.ndarray, row_tables: jnp.ndarray,
                               interpret: bool = False,
                               pool_ks: jnp.ndarray = None,
                               pool_vs: jnp.ndarray = None,
                               window: int = 0,
                               bblock: int = 1) -> jnp.ndarray:
    """RAGGED paged flash attention: N query-token-packed rows, each with its
    OWN (page table row, live-column count) — one program serves a mixed
    batch of single-token decode rows and prefill-chunk rows in a single
    dispatch (PAPERS.md "Ragged Paged Attention").

    The key move is that the double-buffered body (_paged_db_body) never
    cared that row i belonged to slot i — its math is entirely driven by the
    (table row, limit) pair it is handed per query row. Lifting the table to
    PER-ROW indirection (``row_tables`` [N, max_pages]: row i holds the page
    run of whatever slot row i queries) turns the per-slot decode kernel
    into a variable-length-rows kernel with zero changes to the flash
    accumulation, the page-clamp raggedness handling, or the two-slot DMA
    pipeline:

    - a DECODE row carries its slot's table row and limit = context + 1;
    - a PREFILL-CHUNK row at position p carries the chunking slot's table
      row and limit = p + 1 (plain causality), so C chunk rows of one slot
      pack alongside B decode rows of B other slots and every row masks to
      exactly its own live columns. Chunk rows of the same slot landing in
      one bblock-wide grid step fetch the same pages — the block's page
      stream amortizes over them exactly as it does over decode neighbors.

    q: [N, Hq, D] packed query rows; row_limits: [N] live columns per row;
    row_tables: [N, max_pages] int32 (entries at or past a row's live range
    may be any valid id — clamped away, never fetched); layer: scalar.
    Returns [N, Hq, D]. pool_ks/vs switch the int8 scale-folding body;
    ``window`` > 0 applies per-row sliding-window masking off each row's own
    limit. ``bblock`` packed rows share each grid step (resolved to the
    largest divisor of N).
    """
    N = q.shape[0]
    row_limits = row_limits.astype(jnp.int32)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)
    return _paged_flash_db(
        q, pool_k, pool_v, row_limits, layer_arr,
        row_tables.astype(jnp.int32),
        bb=_resolve_bb(bblock, N), R=1, spec=False, window=window,
        interpret=interpret, pool_ks=pool_ks, pool_vs=pool_vs)


@functools.partial(jax.jit, static_argnames=("interpret", "window", "bblock"))
def decode_attend_pallas_spec_paged(q: jnp.ndarray, pool_k: jnp.ndarray,
                                    pool_v: jnp.ndarray, lengths: jnp.ndarray,
                                    layer: jnp.ndarray, table: jnp.ndarray,
                                    interpret: bool = False,
                                    pool_ks: jnp.ndarray = None,
                                    pool_vs: jnp.ndarray = None,
                                    window: int = 0,
                                    bblock: int = 1) -> jnp.ndarray:
    """Paged speculative-verify attention: R query rows per slot, one pass,
    double-buffered page streaming (see _paged_db_body).

    q: [B, R, Hq, D]; row r masks to columns < lengths + 1 + r. The caller
    has already written all R rows (their pages allocated up front — the
    engine's ensure-pages step covers lengths + R). Same economics as the
    dense spec kernel: one page stream serves all R queries — and with
    ``bblock`` > 1, all BB slots of a block.
    """
    B, R, Hq, D = q.shape
    lengths = lengths.astype(jnp.int32)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)
    out = _paged_flash_db(
        q.reshape(B, R * Hq, D), pool_k, pool_v, lengths, layer_arr,
        table.astype(jnp.int32), bb=_resolve_bb(bblock, B), R=R, spec=True,
        window=window, interpret=interpret, pool_ks=pool_ks, pool_vs=pool_vs)
    return out.reshape(B, R, Hq, D)


@functools.partial(jax.jit, static_argnames=("interpret",))
def cache_write_row_paged(pool: jnp.ndarray, new: jnp.ndarray,
                          rows: jnp.ndarray, table: jnp.ndarray,
                          layer: jnp.ndarray,
                          interpret: bool = False) -> jnp.ndarray:
    """Write one new K (or V) row per slot into the PAGED pool, IN PLACE.

    pool: [L, P, Hkv, page, D]; new: [B, Hkv, D]; rows: [B] logical row per
    slot; table: [B, max_pages] int32; layer: scalar. Rows outside
    [0, max_pages*page) DROP (surplus-write invariant). Same aliased-output
    design as the dense cache_write_row (see its docstring for why a kernel
    and not a scatter).
    """
    L, P, Hkv, ps, D = pool.shape
    rows = rows.astype(jnp.int32)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)
    table = table.astype(jnp.int32)
    S_v = table.shape[1] * ps
    ROWS = 8 if ps % 8 == 0 else ps

    def new_map(b, lens, lay, tab):
        return (b, 0, 0)

    def blk_map(b, lens, lay, tab):
        r = jnp.clip(lens[b], 0, S_v - 1)
        return (lay[0], tab[b, r // ps], 0, (r % ps) // ROWS, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B := new.shape[0],),
        in_specs=[
            pl.BlockSpec((1, Hkv, D), new_map),
            pl.BlockSpec((1, 1, Hkv, ROWS, D), blk_map),
        ],
        out_specs=pl.BlockSpec((1, 1, Hkv, ROWS, D), blk_map),
    )

    def kernel(lengths_ref, layer_ref, table_ref, new_ref, cin_ref, cout_ref):
        b = pl.program_id(0)
        tgt = lengths_ref[b]
        in_window = (tgt >= 0) & (tgt < S_v)
        # ROWS divides page_size, so the in-block row is tgt % ROWS
        r = jnp.where(in_window, jnp.clip(tgt, 0, S_v - 1) % ROWS, -1)
        row = jax.lax.broadcasted_iota(jnp.int32, (Hkv, ROWS, D), 1)
        cout_ref[0, 0] = jnp.where(row == r, new_ref[0][:, None, :],
                                   cin_ref[0, 0])

    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(pool.shape, pool.dtype),
        input_output_aliases={4: 0},   # pool operand (after 3 scalars + new)
        interpret=interpret,
    )(rows, layer_arr, table, new, pool)


@functools.partial(jax.jit, static_argnames=("interpret",))
def cache_write_row_quant_paged(pool: jnp.ndarray, scales: jnp.ndarray,
                                new: jnp.ndarray, rows: jnp.ndarray,
                                table: jnp.ndarray, layer: jnp.ndarray,
                                interpret: bool = False):
    """Quantizing paged row write: int8 pool + per-row scales, both aliased.

    pool: [L, P, Hkv, page, D] int8; scales: [L, P, Hkv, page] f32; new:
    [B, Hkv, D] float. Same quantizer as the dense kernel
    (kv_cache.quantize_rows) so prefilled and decoded rows are
    interchangeable. Returns (pool, scales) — same buffers.
    """
    L, P, Hkv, ps, D = pool.shape
    rows = rows.astype(jnp.int32)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)
    table = table.astype(jnp.int32)
    S_v = table.shape[1] * ps
    ROWS = 32 if ps % 32 == 0 else ps

    def new_map(b, lens, lay, tab):
        return (b, 0, 0)

    def blk_map(b, lens, lay, tab):
        r = jnp.clip(lens[b], 0, S_v - 1)
        return (lay[0], tab[b, r // ps], 0, (r % ps) // ROWS, 0)

    def scale_map(b, lens, lay, tab):
        r = jnp.clip(lens[b], 0, S_v - 1)
        return (lay[0], tab[b, r // ps], 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(new.shape[0],),
        in_specs=[
            pl.BlockSpec((1, Hkv, D), new_map),
            pl.BlockSpec((1, 1, Hkv, ROWS, D), blk_map),
            pl.BlockSpec((1, 1, Hkv, ps), scale_map),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, Hkv, ROWS, D), blk_map),
            pl.BlockSpec((1, 1, Hkv, ps), scale_map),
        ],
    )

    def kernel(lengths_ref, layer_ref, table_ref, new_ref, cin_ref, sin_ref,
               cout_ref, sout_ref):
        b = pl.program_id(0)
        tgt = lengths_ref[b]
        in_window = (tgt >= 0) & (tgt < S_v)
        r = jnp.where(in_window, jnp.clip(tgt, 0, S_v - 1) % ROWS, -1)
        from aws_k8s_ansible_provisioner_tpu.serving.kv_cache import (
            quantize_rows)

        q8, sc = quantize_rows(new_ref[0])                    # [Hkv,D],[Hkv]
        row = jax.lax.broadcasted_iota(jnp.int32, (Hkv, ROWS, D), 1)
        cout_ref[0, 0] = jnp.where(row == r, q8[:, None, :], cin_ref[0, 0])
        # scale block spans one whole page: target column = tgt % page
        rs = jax.lax.broadcasted_iota(jnp.int32, (Hkv, ps), 1)
        tgt_col = jnp.where(in_window, jnp.clip(tgt, 0, S_v - 1) % ps, -1)
        sout_ref[0, 0] = jnp.where(rs == tgt_col, sc[:, None], sin_ref[0, 0])

    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(pool.shape, pool.dtype),
            jax.ShapeDtypeStruct(scales.shape, scales.dtype),
        ],
        input_output_aliases={4: 0, 5: 1},  # pool, scales (3 scalars + new)
        interpret=interpret,
    )(rows, layer_arr, table, new, pool, scales)


def supported(cfg=None) -> bool:
    """Pallas decode path is compiled only on TPU backends (interpret elsewhere)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False
