"""Pallas TPU kernel: ragged decode attention over the slot-contiguous KV cache.

This is the hot loop of the whole framework — the TPU-native equivalent of the
paged-attention CUDA kernels inside the reference's external vLLM engine
(SURVEY.md §3.3: "the true hot loop (token-by-token decode on the GPU) lives
entirely inside the external vLLM container"; §7 hard part #1). One program
instance handles one decode slot; the KV cache streams HBM→VMEM in chunks with
flash-style online softmax, so per-step cost is cache-bandwidth-bound with no
[B, S] float32 logits materialization in HBM.

Raggedness (every slot at a different length) is handled two ways:
- masking: key columns ≥ length contribute -inf logits;
- *DMA skipping*: the chunk index_map clamps dead chunks (beyond the slot's
  length) to the last live chunk — Pallas skips re-fetch when a block index
  repeats, so a slot at length 130 reads ~2 chunks of cache, not S/CHUNK.
  With the identity block table of the slot-contiguous head-major cache
  (serving/kv_cache.py pages_view), this IS paged attention: chunk c of
  (slot b, head h) is page ``(b*Hkv + h)*pages_per_stream + c``.

GQA grouping stays in-kernel: per KV head h, the G=Hq/Hkv query rows attend to
one [CHUNK, D] K/V stream — no repeat_kv copy ever exists (the same design as
the XLA fallback in ops/attention.py, here with explicit VMEM control).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(lengths_ref,            # scalar prefetch [B] int32
                   q_ref,                  # [1, Hq, D]
                   k_ref,                  # [1, Hkv, CHUNK, D]
                   v_ref,                  # [1, Hkv, CHUNK, D]
                   o_ref,                  # [1, Hq, D]
                   acc_ref,                # VMEM [Hq, D] f32
                   m_ref,                  # VMEM [Hq, 128] f32
                   l_ref,                  # VMEM [Hq, 128] f32
                   *, chunk: int, groups: int, scale: float):
    b = pl.program_id(0)
    c = pl.program_id(1)
    num_chunks = pl.num_programs(1)
    length = lengths_ref[b]
    hq, d = q_ref.shape[1], q_ref.shape[2]
    hkv = k_ref.shape[1]

    @pl.when(c == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Live chunk: flash accumulation. Dead chunks (start ≥ length) skip compute;
    # their DMA was already skipped by the clamped index_map. The head-major
    # cache layout makes this ONE batched MXU matmul over all kv heads — the
    # [Hq, D]-row-major q reshaped to [Hkv, G, D] lines up head h's G query
    # rows against its contiguous [CHUNK, D] K/V stream.
    @pl.when(c * chunk < length)
    def _accumulate():
        q3 = (q_ref[0].astype(jnp.float32) * scale).reshape(hkv, groups, d)
        k3 = k_ref[0].astype(jnp.float32)                         # [Hkv, C, D]
        s = jax.lax.dot_general(
            q3, k3, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)                   # [Hkv, G, C]
        s = s.reshape(hq, chunk)
        col = c * chunk + jax.lax.broadcasted_iota(jnp.int32, (hq, chunk), 1)
        s = jnp.where(col < length, s, NEG_INF)
        m_prev = m_ref[:, :1]                                     # [Hq, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)                                    # [Hq, C]
        l_cur = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        v3 = v_ref[0].astype(jnp.float32)                         # [Hkv, C, D]
        pv = jax.lax.dot_general(
            p.reshape(hkv, groups, chunk), v3,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)                   # [Hkv, G, D]
        acc_ref[:] = acc_ref[:] * corr + pv.reshape(hq, d)
        m_ref[:, :1] = m_cur
        l_ref[:, :1] = l_cur

    @pl.when(c == num_chunks - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-9)   # len-0 slots: garbage, not NaN
        o_ref[0, :, :] = (acc_ref[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("chunk", "interpret"))
def decode_attend_pallas(q: jnp.ndarray, cache_k: jnp.ndarray,
                         cache_v: jnp.ndarray, lengths: jnp.ndarray,
                         chunk: int = 256, interpret: bool = False) -> jnp.ndarray:
    """Flash decode attention: q [B,1,Hq,D] over cache [B,Hkv,S,D] (head-major,
    see serving/kv_cache.py), ragged by ``lengths`` [B] (counting the
    just-written token). Returns [B,1,Hq,D].

    Drop-in replacement for ops.attention.decode_attend (same contract: caller
    writes the new token's K/V at position lengths-1 first).
    """
    B, _, Hq, D = q.shape
    Hkv, S = cache_k.shape[1], cache_k.shape[2]
    groups = Hq // Hkv
    # Largest divisor of S not exceeding the requested chunk, so any cache
    # length works (a non-divisible --max-cache-len must not crash on TPU).
    chunk = min(chunk, S)
    while S % chunk:
        chunk -= 1
    num_chunks = S // chunk
    lengths = lengths.astype(jnp.int32)

    def q_map(b, c, lens):
        return (b, 0, 0)

    def kv_map(b, c, lens):
        # Clamp dead chunks to the last live one: repeated block index → Pallas
        # skips the re-fetch, so short slots don't pay full-S bandwidth.
        live = jnp.maximum(pl.cdiv(lens[b], chunk) - 1, 0)
        return (b, 0, jnp.minimum(c, live), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, num_chunks),
        in_specs=[
            pl.BlockSpec((1, Hq, D), q_map),
            pl.BlockSpec((1, Hkv, chunk, D), kv_map),
            pl.BlockSpec((1, Hkv, chunk, D), kv_map),
        ],
        out_specs=pl.BlockSpec((1, Hq, D), q_map),
        scratch_shapes=[
            pltpu.VMEM((Hq, D), jnp.float32),
            pltpu.VMEM((Hq, 128), jnp.float32),
            pltpu.VMEM((Hq, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _decode_kernel, chunk=chunk, groups=groups,
        scale=1.0 / (D ** 0.5))
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        interpret=interpret,
    )(lengths, q[:, 0], cache_k, cache_v)
    return out[:, None]


def supported(cfg=None) -> bool:
    """Pallas decode path is compiled only on TPU backends (interpret elsewhere)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False
